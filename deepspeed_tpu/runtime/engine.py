"""Core training engine.

Reference parity: ``deepspeed/runtime/engine.py`` — ``DeepSpeedEngine``
wrapping the user model with ``forward``/``backward``/``step``, gradient
accumulation, mixed precision, ZeRO dispatch, LR scheduling, monitoring,
and checkpoint save/load.

TPU-native architecture (not a port):

- The hot path is ONE compiled function per engine:
  ``_train_batch_fn(state, batch, step)`` — a ``lax.scan`` over
  gradient-accumulation micro-steps followed by the optimizer update, all
  under ``jit`` with NamedSharding annotations. The reference's grad-hook /
  bucket / side-stream machinery (stage_1_and_2.py:792-1249, stage3.py
  coordinator) collapses into XLA's SPMD partitioner + latency-hiding
  scheduler: annotating grads/master/opt-state with ZeRO shardings makes XLA
  emit the same reduce-scatter/all-gather overlap those 4k lines implement
  by hand.

- The reference's ``forward()/backward()/step()`` trio
  (engine.py:1652,1794,1990) is kept as a compatibility surface: forward
  caches the micro-batch and returns the loss; backward computes+accumulates
  grads (compiled); step applies the update at the accumulation boundary
  (``is_gradient_accumulation_boundary`` semantics preserved).

- fp16 dynamic loss scaling runs *inside* the compiled step via
  ``lax.cond`` skip-update (SURVEY §7 "hard part": no host round-trip).

Model contract: ``model`` is a loss callable ``loss_fn(params, batch)`` or
``loss_fn(params, batch, rng)`` returning a scalar loss (optionally
``(loss, aux_dict)``), or an object exposing ``.loss`` with that signature
(every class in ``deepspeed_tpu.models`` does). ``model_parameters`` is the
parameter pytree.
"""

from __future__ import annotations

import contextlib
import inspect
import os
import time
from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu.config.core import DeepSpeedConfig
from deepspeed_tpu.runtime import lr_schedules
from deepspeed_tpu.runtime.loss_scaler import LossScaleState, has_overflow, make_loss_scale_state
from deepspeed_tpu.runtime.loss_scaler import update as scaler_update
from deepspeed_tpu.runtime.optimizers import build_optimizer
from deepspeed_tpu.runtime.utils import clip_grad_norm_, global_norm
from deepspeed_tpu.runtime.zero.partition import ZeroShardingRules
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import NoopTimer, SynchronizedWallClockTimer, ThroughputTimer


class TrainState(NamedTuple):
    """Everything the compiled step reads/writes. All leaves are jax arrays
    carrying NamedShardings chosen by the ZeRO rules."""
    params: Any          # compute-dtype params (bf16/fp16/fp32)
    master: Any          # fp32 master params (None when compute is fp32)
    opt_state: Any       # optax state, sharded like master
    acc_grads: Any       # fp32 (or configured dtype) accumulation buffers
    scaler: LossScaleState
    micro_steps: jnp.ndarray   # i32
    global_steps: jnp.ndarray  # i32
    skipped_steps: jnp.ndarray # i32 (fp16 overflow skips)


def _loss_fn_of(model) -> Callable:
    if callable(model) and not hasattr(model, "loss"):
        fn = model
    elif hasattr(model, "loss"):
        fn = model.loss
    else:
        raise TypeError("model must be a loss callable loss_fn(params, batch[, rng]) or expose .loss")
    try:
        n_args = len(inspect.signature(fn).parameters)
    except (TypeError, ValueError):
        n_args = 2
    if n_args >= 3:
        return fn
    return lambda params, batch, rng: fn(params, batch)


class DeepSpeedEngine:

    def __init__(self,
                 model,
                 config: Optional[Any] = None,
                 model_parameters=None,
                 optimizer=None,
                 lr_scheduler=None,
                 mesh=None,
                 mpu=None,
                 training_data=None,
                 collate_fn=None,
                 config_class: Optional[DeepSpeedConfig] = None,
                 dont_change_device: bool = False):
        self.client_model = model
        self.loss_fn = _loss_fn_of(model)
        self.mpu = mpu

        dist.init_distributed(verbose=False)

        # ---- mesh ----
        if mesh is None:
            if config_class is None:
                tmp_axes = (config or {}).get("mesh", None) if isinstance(config, dict) else None
                mesh = dist.init_mesh(tmp_axes) if not dist.has_mesh() else dist.get_mesh()
            else:
                mesh = dist.init_mesh(config_class.mesh_axes)
        else:
            dist.set_mesh(mesh)
        self.mesh = mesh

        # ---- config ----
        self._config = config_class or DeepSpeedConfig(config, mpu=mpu, mesh=mesh)
        dist.configure(self._config)
        # vocab-head kernel override: a JSON-level "fused_cross_entropy"
        # knob beats the model config's default (the same engine-pushes-into-
        # model pattern the autotuner's model_overrides use), so bench/serve
        # configs can flip the CE path without rebuilding the model
        fce = self._config.fused_cross_entropy
        mcfg = getattr(model, "config", None)
        if fce is not None and mcfg is not None \
                and hasattr(mcfg, "fused_cross_entropy"):
            import dataclasses
            model.config = dataclasses.replace(mcfg, fused_cross_entropy=fce)
            if hasattr(model, "zoo_cfg"):
                # BertModel caches a derived zoo config; keep it coherent
                model.zoo_cfg = model.config.zoo()
        self.zero_rules = ZeroShardingRules(mesh, self._config.zero_config)
        log_dist(self.zero_rules.describe(), ranks=[0])

        # ---- precision ----
        if self.bfloat16_enabled():
            self.compute_dtype = jnp.bfloat16
        elif self.fp16_enabled():
            self.compute_dtype = jnp.float16
        else:
            self.compute_dtype = jnp.float32
        self.mixed_precision = self.compute_dtype != jnp.float32
        acc_dtype_name = self._config.gradient_accumulation_dtype
        # default: with no accumulation (gas=1) grads pass straight through to
        # the update, so keep them in compute dtype — the persistent fp32
        # accumulator would cost 4 bytes/param for nothing; with gas>1 the
        # reference accumulates in fp32 (bf16_optimizer.py) and so do we
        self._acc_dtype_name = acc_dtype_name
        if acc_dtype_name is None and self.gradient_accumulation_steps() == 1:
            self.grad_acc_dtype = self.compute_dtype
        else:
            self.grad_acc_dtype = {None: jnp.float32, "fp32": jnp.float32, "fp16": jnp.float16,
                                   "bf16": jnp.bfloat16}[acc_dtype_name]

        # ---- optimizer ----
        # 1-bit family: functional optimizers whose COMPRESSED collectives
        # run inside the compiled step (reference fp16/onebit/adam.py:11 —
        # the optimizer owns gradient communication after freeze_step)
        self._onebit = None
        ob_name = (self._config.optimizer_name or "").lower() if optimizer is None else ""
        if ob_name in ("onebitadam", "zerooneadam", "onebitlamb"):
            self._onebit = self._build_onebit_optimizer(ob_name)

        self.client_optimizer = optimizer
        if self._onebit is not None:
            self.tx = None
            self._client_tx_full = False
            self._optimizer_name = ob_name
            if float(self._config.gradient_clipping or 0.0) > 0.0:
                raise NotImplementedError(
                    f"{ob_name}: gradient_clipping does not compose with the compressed "
                    "momentum exchange (the optimizer owns communication); disable it")
        elif optimizer is not None:
            # A user-supplied optax transformation follows standard optax
            # conventions: updates are final (lr and sign already applied),
            # consumed as params + updates. The engine's LR schedule then
            # does NOT rescale them — the client optimizer owns its LR.
            self.tx = optimizer
            self._client_tx_full = True
            self._optimizer_name = "client"
            if self._config.scheduler_name is not None:
                logger.warning("A client optax optimizer was passed together with a scheduler config; "
                               "the engine cannot inject the schedule into a finalized optax chain. "
                               "Use optimizer config {'type': ...} or bake the schedule into the client chain.")
        else:
            self.tx = build_optimizer(self._config.optimizer_name, self._config.optimizer_params)
            self._client_tx_full = False
            self._optimizer_name = self._config.optimizer_name or "adamw"

        # ---- lr schedule ----
        self.client_lr_scheduler = lr_scheduler
        self.lr_scheduler = None
        base_lr = (self._config.optimizer_params or {}).get("lr", 1e-3)
        if lr_scheduler is not None and hasattr(lr_scheduler, "schedule_fn"):
            self._lr_fn = lr_scheduler.schedule_fn
            self.lr_scheduler = lr_scheduler
        elif callable(lr_scheduler):
            self._lr_fn = lr_scheduler
        elif self._config.scheduler_name is not None:
            self._lr_fn = lr_schedules.get_lr_schedule_fn(self._config.scheduler_name,
                                                          self._config.scheduler_params or {})
            sched_cls = getattr(lr_schedules, self._config.scheduler_name)
            self.lr_scheduler = sched_cls(**(self._config.scheduler_params or {}))
        else:
            self._lr_fn = lambda step: jnp.asarray(base_lr, jnp.float32)

        # ---- timers / monitor ----
        self.wall_clock_breakdown_enabled = self._config.wall_clock_breakdown
        self.timers = SynchronizedWallClockTimer() if self.wall_clock_breakdown_enabled else NoopTimer()
        self.tput_timer = ThroughputTimer(batch_size=self.train_batch_size(),
                                          steps_per_output=self._config.steps_per_print)
        from deepspeed_tpu.monitor.monitor import MonitorMaster
        self.monitor = MonitorMaster(self._config.monitor_config)

        # ---- telemetry (metrics registry + compile watchdog) ----
        # when off, _telemetry is None and every hot-path hook is a single
        # attribute check — no timers, no syncs, no registry traffic
        tcfg = self._config.telemetry_config
        self._telemetry = tcfg if tcfg.enabled else None
        self._tel_flops_per_token_v = None
        # health observatory: None when off, so every hot-path hook gates
        # at one attribute check (sentinel collection in the compiled step
        # is likewise a trace-time constant — no runtime branch at all)
        self._health = None
        self._sentinels_on = False
        self._sentinel_layout = None     # (leaf->bucket assignment, names)
        self._t_prev_step_end = None     # data-stall wait-time base
        self._trio_busy_s = 0.0          # per-cycle fwd+bwd+step phase time
        self._tel_wait_total = 0.0
        self._tel_busy_total = 0.0
        self._tel_skip_consec = 0        # health-off sustained-skip warning
        self._tel_skip_seen = 0
        self._tel_skipped_prev = None    # health skip detection base
        self._tel_skipped_cached = None  # per-step skipped_steps fetch
        # flight recorder: None when off — every hot-path emit site is one
        # None check and allocates nothing
        self._tel_events = None
        self._ev_skip_prev = None        # fp16-skip event detection base
        # on-demand jax.profiler capture window; armed by config
        # (telemetry.profile) or engine.profile(steps=N). One None check
        # per train_batch when absent.
        self._profiler = None
        # metrics exposition plane (monitor/exporter.py, monitor/
        # sampler.py): a standalone /metrics endpoint + the background
        # snapshot/SLO sampler — both config-driven, both host-only
        # daemon threads, stopped in destroy()
        self._tel_exporter = None
        self._tel_sampler = None
        pcfg = tcfg.profile
        if pcfg.num_steps > 0:
            from deepspeed_tpu.monitor.trace import ProfileWindow
            self._profiler = ProfileWindow(pcfg.dir, pcfg.start_step,
                                           pcfg.num_steps)
        if self._telemetry is not None:
            from deepspeed_tpu.monitor.health import sample_memory_gauges
            from deepspeed_tpu.monitor.metrics import get_registry
            from deepspeed_tpu.monitor.trace import (get_compile_watchdog,
                                                     get_tracer)
            reg = get_registry()
            reg.set_enabled(True)
            self._tel_reg = reg
            self._tel_watchdog = get_compile_watchdog()
            self._tel_watchdog.storm_threshold = tcfg.compile_storm_threshold
            self._tel_tracer = get_tracer()
            self._tel_sample_memory = sample_memory_gauges
            self._tel_step_hist = reg.histogram(
                "train/step_time_ms", "whole train_batch wall time")
            self._tel_phase_hist = reg.histogram(
                "train/phase_time_ms",
                "fwd/bwd/step breakdown (forward()/backward()/step() trio; "
                "fwd = value_and_grad, bwd = accumulate)",
                labelnames=("phase",))
            self._tel_tokens_gauge = reg.gauge(
                "train/tokens_per_sec", "tokens through the last step")
            self._tel_tflops_gauge = reg.gauge(
                "train/achieved_tflops_per_chip",
                "model flops per token x token rate / chips")
            self._tel_mfu_gauge = reg.gauge(
                "train/mfu", "achieved / peak flops per chip (PaLM-style)")
            self._tel_steps_counter = reg.counter("train/steps")
            self._tel_tokens_counter = reg.counter("train/tokens")
            self._tel_loss_gauge = reg.gauge(
                "train/loss", "last recorded training loss")
            self._tel_grad_norm_hist = reg.histogram(
                "train/grad_norm",
                "pre-clip global gradient norm (reused from the norm "
                "clip_grad_norm_ computes; recorded even with clipping off)")
            self._tel_wait_hist = reg.histogram(
                "train/data_wait_ms",
                "host time between compiled steps (data loading + host prep)")
            self._tel_stall_gauge = reg.gauge(
                "train/data_stall_fraction",
                "cumulative wait / (wait + device step) time")
            if self.fp16_enabled():
                self._tel_skipped_gauge = reg.gauge(
                    "train/skipped_steps",
                    "fp16 overflow skip-update steps so far")
                self._tel_scale_gauge = reg.gauge(
                    "train/loss_scale", "current dynamic loss scale")
            if tcfg.events.enabled:
                from deepspeed_tpu.monitor.events import get_flight_recorder
                self._tel_events = get_flight_recorder().enable(
                    capacity=tcfg.events.capacity)
            hcfg = tcfg.health
            if hcfg.enabled:
                from deepspeed_tpu.monitor.health import HealthMonitor
                self._health = HealthMonitor(
                    hcfg, registry=reg,
                    snapshot_fn=self.telemetry_snapshot,
                    trace_export_fn=self._tel_tracer.export_chrome_trace)
                self._sentinels_on = bool(hcfg.sentinels)
            if tcfg.metrics_port is not None:
                from deepspeed_tpu.monitor.exporter import MetricsExporter
                self._tel_exporter = MetricsExporter(
                    reg, port=tcfg.metrics_port)
                ehost, eport = self._tel_exporter.start()
                logger.info(f"telemetry: /metrics exposition on "
                            f"http://{ehost}:{eport}/metrics")
            from deepspeed_tpu.monitor.sampler import sampler_from_config
            sampler = sampler_from_config(tcfg, reg, self._tel_events)
            if sampler is not None:
                self._tel_sampler = sampler.start()

        # ---- curriculum learning (reference engine.py:1691 legacy path +
        # data_efficiency data_sampling.curriculum_learning) ----
        self.curriculum_scheduler = None
        self._curriculum_metric = None
        raw = self._config._param_dict
        legacy = raw.get("curriculum_learning", {})
        from deepspeed_tpu.runtime.data_pipeline.config import get_data_efficiency_config
        de = get_data_efficiency_config(raw)
        sampling = de["data_sampling"]
        de_curr = sampling["curriculum_learning"]
        curr_cfg = None
        if isinstance(legacy, dict) and legacy.get("enabled", False):
            curr_cfg = legacy
        elif de["enabled"] and sampling["enabled"] and de_curr.get("enabled", False):
            # the parent data_efficiency/data_sampling switches gate the
            # feature (reference runtime/data_pipeline/config.py semantics)
            curr_cfg = de_curr
        if curr_cfg is not None:
            from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
                CurriculumScheduler)
            self.curriculum_scheduler = CurriculumScheduler(dict(curr_cfg))
            self._curriculum_metric = curr_cfg.get("curriculum_type", "seqlen")
        # host-side step counter for curriculum (avoids a device sync per
        # train_batch just to read state.global_steps)
        self._host_global_steps = 0

        # ---- fault tolerance: data-pipeline progress + async checkpoint
        # writer + preemption grace handler (runtime/checkpoint_engine) ----
        # consumed_samples/iterations are recorded in every checkpoint's
        # meta.json so auto_resume can fast-forward the data pipeline
        self._data_progress = {"consumed_samples": 0, "iterations": 0}
        # True only for a user-provided set_dataiterator stream: resume
        # fast-forwards it in place; loader-derived iterators are instead
        # re-created by the epoch-aware resume_loader_iterator path
        self._data_iter_external = False
        self._ckpt_writer = None
        self._preemption = None

        # ---- dataloader ----
        self.training_dataloader = None
        if training_data is not None:
            from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
            # single-controller: this process feeds every dp shard it owns
            dp = dist.get_world_size(dist.data_parallel_axes(self.mesh))
            self.training_dataloader = DeepSpeedDataLoader(
                training_data, batch_size=self.train_micro_batch_size_per_gpu() * dp, collate_fn=collate_fn,
                drop_last=self._config.dataloader_drop_last)

        # ---- state ----
        if model_parameters is None and hasattr(model, "init_params"):
            # key(0): decorrelated from the training rng stream (key(DS_SEED))
            # and unchanged vs earlier releases
            seed_key = jax.random.key(0)
            if self.zero_optimization_stage() >= 3:
                # zero.Init-equivalent abstract construction (reference
                # partition_parameters.py:516): params materialise directly
                # into their ZeRO-3 shards — the full tree never exists in
                # one memory, so > single-device-memory models construct
                from deepspeed_tpu.runtime.zero import Init
                with Init(mesh=self.mesh, config=self._config.zero_config):
                    model_parameters = model.init_params(seed_key)
            else:
                model_parameters = model.init_params(seed_key)
        if model_parameters is None:
            raise ValueError("model_parameters is required (or model must expose init_params(rng))")
        self.state = self._init_state(model_parameters)
        self._rng = jax.random.key(int(os.environ.get("DS_SEED", 42)))

        # compiled functions, built lazily on first use
        self._train_batch_jit: Dict[Tuple, Callable] = {}
        self._accum_batch_jit: Dict[Tuple, Callable] = {}
        self._grad_jit = None
        self._acc_jit = None
        self._apply_jit = None
        self._reset_acc_jit = None
        self._eval_jit = None
        self._cached_grads = None
        self._losses = 0.0

        self.progressive_layer_drop = None
        if self._config.pld_enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
            theta = self._config.pld_params.get("theta", 1.0)
            gamma = self._config.pld_params.get("gamma", 0.001)
            self.progressive_layer_drop = ProgressiveLayerDrop(theta=theta, gamma=gamma)

        # MoQ progressive quantization (reference runtime/quantize.py wired
        # via the "quantize_training" config section; eigenvalue-guided
        # schedule per runtime/eigenvalue.py)
        self.quantizer = None
        self.eigenvalue = None
        qt = getattr(self._config, "quantize_training", {})
        if getattr(self._config, "quantize_training_enabled", False):
            from deepspeed_tpu.runtime.quantize import Quantizer
            bits = qt.get("quantize_bits", {})
            sched = qt.get("quantize_schedule", {})
            algo = qt.get("quantize_algo", {})
            mixed = qt.get("fp16_mixed_quantize", {})
            # only config-present keys: Quantizer's own defaults govern
            kw = {k: v for k, v in dict(
                q_groups=qt.get("quantize_groups"),
                q_mixed_fp16=mixed.get("enabled"),
                q_change_ratio=mixed.get("quantize_change_ratio"),
                q_type=algo.get("q_type"),
                q_rounding=algo.get("q_rounding"),
                q_verbose=qt.get("quantize_verbose"),
                q_eigenvalue=qt.get("eigenvalue", {}).get("enabled"),
                start_bits=bits.get("start_bits"),
                target_bits=bits.get("target_bits"),
                q_period=sched.get("quantize_period"),
            ).items() if v is not None}
            self.quantizer = Quantizer(**kw)
            self._moq_seen_skipped = 0
            ev = qt.get("eigenvalue", {})
            if ev.get("enabled", False):
                from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
                self.eigenvalue = Eigenvalue(
                    verbose=ev.get("verbose", False),
                    max_iter=ev.get("max_iter", 100),
                    tol=ev.get("tol", 1e-2),
                    stability=ev.get("stability", 1e-6),
                    gas_boundary_resolution=ev.get("gas_boundary_resolution", 1))
                self._ev_layer_name = ev.get("layer_name", "layers")
                self._ev_layer_num = ev.get("layer_num", 0)

        ccfg = getattr(self._config, "checkpoint_config", None)
        if ccfg is not None and ccfg.preemption_save and ccfg.save_dir:
            self.enable_preemption_handler(ccfg.save_dir)

        log_dist(f"DeepSpeedEngine ready: optimizer={self._optimizer_name}, "
                 f"dtype={self.compute_dtype.__name__}, mesh={dict(mesh.shape)}, "
                 f"micro_bs={self.train_micro_batch_size_per_gpu()} x gas={self.gradient_accumulation_steps()}",
                 ranks=[0])

    # ------------------------------------------------------------------ #
    # state initialization

    def _init_state(self, model_parameters) -> TrainState:
        rules = self.zero_rules
        tp_specs = getattr(self.client_model, "tp_specs", None)
        if callable(tp_specs):
            tp_specs = tp_specs()

        param_sh = rules.param_shardings(model_parameters, tp_specs)
        master_sh = rules.master_shardings(model_parameters, tp_specs)
        grad_sh = rules.grad_shardings(model_parameters, tp_specs)
        self._param_shardings = param_sh
        self._grad_shardings = grad_sh
        self._master_shardings = master_sh
        self._params_treedef = jax.tree.structure(model_parameters)

        # ---- ZeRO-Offload: fp32 master + optimizer state live on host
        # (or NVMe), stepped by the native cpu_adam; the device program only
        # accumulates grads (reference stage_1_and_2.py:1030-1155, stage3
        # PartitionedOptimizerSwapper) ----
        self._offload = None
        ocfg = self._config.zero_config.offload_optimizer
        if ocfg is not None and ocfg.device != "none":
            if self.client_optimizer is not None:
                raise ValueError("offload_optimizer is incompatible with a client optax optimizer; "
                                 "configure the optimizer via the config instead")
            from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
            self._offload = HostOffloadOptimizer(
                model_parameters,
                optimizer_name=self._optimizer_name,
                optimizer_params=self._config.optimizer_params,
                device=str(ocfg.device.value if hasattr(ocfg.device, "value") else ocfg.device),
                nvme_path=ocfg.nvme_path,
                grad_clip=float(self.gradient_clipping() or 0.0))
            log_dist(f"ZeRO-Offload: optimizer on {self._offload.device} "
                     f"({len(self._offload.order)} tensors, native cpu_{self._optimizer_name})", ranks=[0])

        params = jax.tree.map(
            lambda a, s: jax.device_put(jnp.asarray(a, self.compute_dtype), s), model_parameters, param_sh)
        if self.mixed_precision and self._offload is None:
            master = jax.tree.map(
                lambda a, s: jax.device_put(jnp.asarray(a, jnp.float32), s), model_parameters, master_sh)
        else:
            master = None
        if self._onebit is not None:
            opt_target = master if master is not None else params
            opt_state = self._onebit_init_state(opt_target)
        elif self._offload is None:
            opt_target = master if master is not None else params
            opt_state = self.tx.init(opt_target)
            opt_sh = rules.opt_state_shardings(opt_state, model_parameters, tp_specs)
            opt_state = jax.tree.map(lambda a, s: jax.device_put(a, s) if hasattr(a, "shape") else a,
                                     opt_state, opt_sh)
        else:
            opt_state = ()
        if not self._uses_acc_grad_buffers():
            # the fused step feeds grads straight into the update — no
            # accumulation buffers; the forward/backward/step trio lazily
            # allocates them on first use (_ensure_acc_grads)
            acc_grads = ()
        else:
            acc_grads = jax.tree.map(
                lambda a, s: jax.device_put(jnp.zeros(a.shape, self.grad_acc_dtype), s),
                model_parameters, grad_sh)

        if self.fp16_enabled() and self._config.fp16_config.dynamic_loss_scale:
            args = self._config.dynamic_loss_scale_args
            scaler = make_loss_scale_state(init_scale=args["init_scale"], scale_window=args["scale_window"],
                                           min_scale=args["min_scale"], delayed_shift=args["delayed_shift"])
        elif self.fp16_enabled():
            scaler = make_loss_scale_state(init_scale=self._config.loss_scale or 1.0, dynamic=False)
        else:
            scaler = make_loss_scale_state(init_scale=1.0, dynamic=False)

        # scalars live replicated on the mesh so they compose with sharded
        # leaves in one program; counters must be distinct buffers (the state
        # is donated, and XLA rejects donating one buffer twice)
        rep = NamedSharding(self.mesh, P())
        scaler = jax.tree.map(lambda x: jax.device_put(x, rep), scaler)
        return TrainState(params=params, master=master, opt_state=opt_state, acc_grads=acc_grads,
                          scaler=scaler,
                          micro_steps=jax.device_put(jnp.zeros((), jnp.int32), rep),
                          global_steps=jax.device_put(jnp.zeros((), jnp.int32), rep),
                          skipped_steps=jax.device_put(jnp.zeros((), jnp.int32), rep))

    # ------------------------------------------------------------------ #
    # compiled step builders

    def _micro_grads(self, params, batch, rng, scale):
        """Loss + scaled grads for one micro-batch (compute dtype)."""

        def scaled_loss(p):
            out = self.loss_fn(p, batch, rng)
            loss = out[0] if isinstance(out, tuple) else out
            return loss.astype(jnp.float32) * scale, loss

        grads, loss = jax.grad(scaled_loss, has_aux=True)(params)
        return loss, grads

    def _accumulate(self, acc, grads):
        acc = jax.tree.map(lambda a, g: (a + g.astype(self.grad_acc_dtype)), acc, grads)
        # constrain to ZeRO grad shardings: stage>=2 => XLA reduce-scatters
        return jax.lax.with_sharding_constraint(acc, self._grad_shardings)

    def _apply_update(self, state: TrainState, gas: int, acc=None):
        """Unscale, clip, (maybe skip on overflow), optimizer update.
        Returns ``(new_state, aux)`` where ``aux`` is a (possibly empty)
        dict of health/telemetry scalars computed inside this same
        program: ``grad_norm`` (pre-clip, telemetry on) and ``sentinels``
        (the numerics summary vector, health sentinels on). The gating is
        a trace-time constant — telemetry off compiles the exact same
        program as before.

        ``acc``: gradient tree to consume; defaults to ``state.acc_grads``
        (the GAS-scan buffers). The gas==1 fast path passes the micro-step
        grads directly so no accumulation buffers are read, written, or
        re-zeroed — and with no scan barrier XLA's scheduler is free to
        overlap per-param optimizer updates with the rest of the backward."""
        if self._onebit is not None:
            raise NotImplementedError(
                "1-bit optimizers run their compressed update inside train_batch(); "
                "the forward()/backward()/step() trio is not supported with them")
        from_buffers = acc is None
        if from_buffers:
            acc = state.acc_grads
        scale = state.scaler.loss_scale
        denom = scale * gas
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) / denom, acc)

        overflow = has_overflow(grads) if self.fp16_enabled() else jnp.asarray(False)

        aux: Dict[str, Any] = {}
        raw_grads = grads
        clip = float(self.gradient_clipping() or 0.0)
        # pre-clip global norm computed ONCE and shared: the clip consumes
        # it via its norm= parameter and telemetry records it (even with
        # clipping disabled, the satellite contract)
        norm = None
        if clip > 0.0 or self._telemetry is not None:
            norm = global_norm(grads)
        if clip > 0.0:
            grads, _ = clip_grad_norm_(grads, clip, norm=norm)
        if self._telemetry is not None:
            aux["grad_norm"] = norm

        lr = self._lr_fn(state.global_steps)
        opt_target = state.master if state.master is not None else state.params

        def do_update(_):
            updates, new_opt = self.tx.update(grads, state.opt_state, opt_target)
            if self._client_tx_full:
                # standard optax semantics: updates are final (incl. -lr)
                new_target = jax.tree.map(lambda p, u: p + u.astype(p.dtype), opt_target, updates)
            else:
                # engine-built chains end before lr scaling so the schedule
                # stays inside jit: direction u is descent, applied as p - lr*u
                new_target = jax.tree.map(lambda p, u: p - lr * u.astype(p.dtype), opt_target, updates)
            # sentinel update norm from the update VECTOR, not new - old:
            # a (new - old) subtraction would keep the whole pre-update
            # tree live past the update and defeat donation aliasing (one
            # extra fp32 master copy of peak HBM). ||delta|| = ||u|| for a
            # client chain, lr*||u|| for engine-built chains.
            if self._sentinels_on:
                up_norm = global_norm(updates)
                if not self._client_tx_full:
                    up_norm = lr * up_norm
            else:
                up_norm = jnp.float32(0.0)
            return new_target, new_opt, up_norm

        def skip_update(_):
            return opt_target, state.opt_state, jnp.float32(0.0)

        if self.fp16_enabled():
            new_target, new_opt, up_norm = jax.lax.cond(
                overflow, skip_update, do_update, operand=None)
        else:
            new_target, new_opt, up_norm = do_update(None)

        if state.master is not None:
            new_master = new_target
            new_params = jax.lax.with_sharding_constraint(
                jax.tree.map(lambda m: m.astype(self.compute_dtype), new_master), self._param_shardings)
        else:
            new_master = None
            new_params = jax.lax.with_sharding_constraint(new_target, self._param_shardings)

        if self._sentinels_on:
            # numerics sentinels ride THIS program (no extra compiles or
            # host round-trips): non-finite counts over the raw unscaled
            # grads + post-update params, param/update norms, per-group
            # norm buckets — all cheap reductions XLA fuses into the step
            from deepspeed_tpu.monitor.health import compute_sentinels
            assignment, names = self._sentinel_buckets(raw_grads)
            aux["sentinels"] = compute_sentinels(
                raw_grads, new_target, up_norm, norm, assignment, names)

        new_scaler = scaler_update(state.scaler, overflow)
        # donation aliases the untouched buffers through at zero cost
        zero_acc = (jax.tree.map(jnp.zeros_like, state.acc_grads) if from_buffers
                    else state.acc_grads)
        return state._replace(
            params=new_params, master=new_master, opt_state=new_opt, acc_grads=zero_acc, scaler=new_scaler,
            global_steps=state.global_steps + 1,
            skipped_steps=state.skipped_steps + overflow.astype(jnp.int32)), aux

    def _sentinel_buckets(self, grads_tree):
        """Leaf→layer-group bucket layout for the sentinel vector,
        computed once (at trace time of the first compiled step) and
        cached — the structure is fixed for the engine's lifetime."""
        if self._sentinel_layout is None:
            from deepspeed_tpu.monitor.health import make_bucket_assignment
            assignment, names = make_bucket_assignment(
                grads_tree, self._health.cfg.max_norm_buckets)
            self._sentinel_layout = (assignment, names)
            self._health.set_bucket_names(names)
        return self._sentinel_layout

    def _build_accum_batch_fn(self, gas: int) -> Callable:
        """GAS-scan only (offload path): grads accumulate on device, the
        optimizer update happens on host in :meth:`_host_step`."""

        def accum_batch_fn(state: TrainState, batch, rng):
            scale = state.scaler.loss_scale

            def micro(carry, mb):
                acc, i = carry
                mb_rng = jax.random.fold_in(rng, i)
                loss, grads = self._micro_grads(state.params, mb, mb_rng, scale)
                acc = self._accumulate(acc, grads)
                return (acc, i + 1), loss

            (acc, _), losses = jax.lax.scan(micro, (state.acc_grads, jnp.asarray(0, jnp.int32)), batch, length=gas)
            state = state._replace(acc_grads=acc, micro_steps=state.micro_steps + gas)
            return state, jnp.mean(losses)

        return jax.jit(accum_batch_fn, donate_argnums=(0,))

    def _host_step(self):
        """Offload optimizer boundary: grads → host, native cpu_adam step,
        updated bf16 params → device. Returns metrics."""
        import ml_dtypes

        gas = self.gradient_accumulation_steps()
        scale = float(self.state.scaler.loss_scale) if self.fp16_enabled() else 1.0
        denom = scale * gas
        lr = float(self._lr_fn(self.state.global_steps))

        from deepspeed_tpu.runtime.zero.offload import _leaf_key

        # one tree-level D2H transfer (JAX batches/overlaps the copies)
        host_grads_tree = jax.device_get(self.state.acc_grads)
        grads_host: Dict[str, np.ndarray] = {}
        # offload-path health/telemetry ride the SAME host pass the grads
        # already make (one extra reduction per leaf, no device work)
        grad_sq = 0.0
        nonfinite = 0.0
        for path, leaf in jax.tree_util.tree_flatten_with_path(host_grads_tree)[0]:
            arr = np.asarray(leaf).ravel()
            # one conversion, one divide: .astype copies, then /= is in-place
            arr = arr.astype(np.float32)
            arr /= denom
            if self._telemetry is not None:
                grad_sq += float(np.dot(arr, arr))
            if self._health is not None:
                nonfinite += float(arr.size - np.isfinite(arr).sum())
            grads_host[_leaf_key(path)] = np.ascontiguousarray(arr)

        out_dtype = ml_dtypes.bfloat16 if self.compute_dtype == jnp.bfloat16 else np.float32
        staged, overflow = self._offload.step(grads_host, lr, out_dtype=out_dtype)

        if not overflow:
            np_dtype = {jnp.bfloat16: ml_dtypes.bfloat16, jnp.float16: np.float16,
                        jnp.float32: np.float32}[self.compute_dtype]
            leaves = []
            for key in self._offload.order:
                flat = staged[key]
                if flat.dtype == np.uint16:
                    flat = flat.view(ml_dtypes.bfloat16)
                leaves.append(flat.reshape(self._offload.shape(key)).astype(np_dtype, copy=False))
            host_params = jax.tree.unflatten(self._params_treedef, leaves)
            # one tree-level H2D transfer against the sharding tree
            new_params = jax.device_put(host_params, self._param_shardings)
        else:
            new_params = self.state.params

        zero_acc = self._zeroed_acc(self.state.acc_grads)
        overflow_arr = jnp.asarray(overflow)
        new_scaler = scaler_update(self.state.scaler, overflow_arr) if self.fp16_enabled() else self.state.scaler
        self.state = self.state._replace(
            params=new_params, acc_grads=zero_acc, scaler=new_scaler,
            global_steps=self.state.global_steps + 1,
            skipped_steps=self.state.skipped_steps + int(overflow))
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        metrics = {"loss": self._losses, "lr": lr, "loss_scale": float(new_scaler.loss_scale)}
        if self._telemetry is not None:
            metrics["grad_norm"] = float(np.sqrt(grad_sq))
        if self._health is not None:
            metrics["nonfinite_grads"] = nonfinite
        return metrics

    # ------------------------------------------------------------------ #
    # 1-bit optimizer path (reference runtime/fp16/onebit/*: the optimizer
    # owns gradient communication — full-precision psum during warmup,
    # error-compensated 1-bit compressed allreduce after freeze_step)

    def _build_onebit_optimizer(self, name: str):
        p = dict(self._config.optimizer_params or {})
        mesh = self.mesh
        dp_axes = [ax for ax in ("dp", "fsdp") if mesh.shape.get(ax, 1) > 1]
        other = [ax for ax, sz in mesh.shape.items()
                 if sz > 1 and ax not in ("dp", "fsdp")]
        if other or len(dp_axes) > 1:
            raise NotImplementedError(
                f"{name} supports a single data-parallel mesh axis (got {dict(mesh.shape)}); "
                "the compressed allreduce composes with dp only (reference parity: "
                "1-bit optimizers are incompatible with model parallelism + ZeRO>=2)")
        if self._config.zero_config.stage >= 2:
            raise NotImplementedError(f"{name} is incompatible with ZeRO stage >= 2 "
                                      "(gradients must stay whole for the compressed allreduce)")
        if self.fp16_enabled():
            raise NotImplementedError(f"{name}: use bf16/fp32 (dynamic loss scaling does not "
                                      "compose with the compressed momentum exchange)")
        self._onebit_axis = dp_axes[0] if dp_axes else "dp"
        n = mesh.shape.get(self._onebit_axis, 1)
        common = dict(lr=p.get("lr", 1e-3), betas=tuple(p.get("betas", (0.9, 0.999))),
                      eps=p.get("eps", 1e-8), weight_decay=p.get("weight_decay", 0.0),
                      axis=self._onebit_axis, comm_group_size=n)
        if name == "onebitadam":
            from deepspeed_tpu.runtime.fp16.onebit import OnebitAdam
            return OnebitAdam(freeze_step=p.get("freeze_step", 100), **common)
        if name == "onebitlamb":
            from deepspeed_tpu.runtime.fp16.onebit import OnebitLamb
            return OnebitLamb(freeze_step=p.get("freeze_step", 100), **common)
        from deepspeed_tpu.runtime.fp16.onebit import ZeroOneAdam
        return ZeroOneAdam(var_freeze_step=p.get("var_freeze_step", 100),
                           local_step_clipper=p.get("local_step_clipper", 16), **common)

    _ONEBIT_ERR_FIELDS = ("worker_error", "server_error")

    def _ob_map_errors(self, st, fn):
        """Apply ``fn`` leaf-wise to the worker/server error subtrees,
        wherever they live (OnebitLambState nests an adam state)."""
        if hasattr(st, "adam"):
            return st._replace(adam=self._ob_map_errors(st.adam, fn))
        return st._replace(worker_error=jax.tree.map(fn, st.worker_error),
                           server_error=jax.tree.map(fn, st.server_error))

    def _ob_is_error_path(self, path) -> bool:
        return any(getattr(k, "name", None) in self._ONEBIT_ERR_FIELDS for k in path)

    def _onebit_init_state(self, target):
        """Global optimizer state: per-rank error feedback gets a leading dp
        dim sharded over the dp axis; everything else replicates."""
        n = self.mesh.shape.get(self._onebit_axis, 1)
        st = self._onebit.init(target)
        st = self._ob_map_errors(st, lambda e: jnp.zeros((n,) + e.shape, e.dtype))
        rep = NamedSharding(self.mesh, P())
        shd = NamedSharding(self.mesh, P(self._onebit_axis))

        def put(path, a):
            return jax.device_put(a, shd if self._ob_is_error_path(path) else rep)

        from jax.tree_util import tree_map_with_path
        return tree_map_with_path(put, st)

    def _build_onebit_batch_fn(self, gas: int) -> Callable:
        """Whole step inside shard_map over dp: per-rank LOCAL grads feed the
        1-bit optimizer, which performs the (compressed) communication."""
        from deepspeed_tpu.utils.jax_compat import shard_map

        opt = self._onebit
        axis = self._onebit_axis
        mesh = self.mesh
        has_axis = mesh.shape.get(axis, 1) > 1

        def step(state: TrainState, batch, rng, lr):
            params, master, opt_state = state.params, state.master, state.opt_state

            def per_rank(params, master, opt_state, batch, rng):
                local = self._ob_map_errors(opt_state, lambda e: e[0])

                def micro_grad(carry, mb):
                    acc, i = carry
                    def lf(p):
                        out = self.loss_fn(p, mb, jax.random.fold_in(rng, i))
                        return (out[0] if isinstance(out, tuple) else out).astype(jnp.float32)
                    loss, grads = jax.value_and_grad(lf)(params)
                    acc = jax.tree.map(lambda a, g: a + g.astype(jnp.float32), acc, grads)
                    return (acc, i + 1), loss

                zero = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
                (gsum, _), losses = jax.lax.scan(micro_grad, (zero, jnp.int32(0)), batch)
                grads = jax.tree.map(lambda g: g / gas, gsum)  # LOCAL mean

                target = master if master is not None else params
                new_target, new_local = opt.update(grads, local, target, lr=lr)
                new_opt = self._ob_map_errors(new_local, lambda e: e[None])
                loss = jnp.mean(losses)
                if has_axis:
                    loss = jax.lax.pmean(loss, axis)
                return new_target, new_opt, loss

            rep = P()
            specs = lambda tree, s: jax.tree.map(lambda _: s, tree,
                                                 is_leaf=lambda x: x is None)
            opt_in = jax.tree_util.tree_map_with_path(
                lambda path, _: P(axis) if self._ob_is_error_path(path) else rep,
                opt_state)
            batch_spec = jax.tree.map(lambda _: P(None, axis) if has_axis else P(None), batch)

            wrapped = shard_map(
                per_rank, mesh=mesh,
                in_specs=(specs(params, rep), specs(master, rep), opt_in, batch_spec, rep),
                out_specs=(specs(params, rep), opt_in, rep),
                check_vma=False)
            new_target, new_opt, loss = wrapped(params, master, opt_state, batch, rng)

            if master is not None:
                new_master = new_target
                new_params = jax.lax.with_sharding_constraint(
                    jax.tree.map(lambda m: m.astype(self.compute_dtype), new_master),
                    self._param_shardings)
            else:
                new_master, new_params = None, new_target
            state = state._replace(params=new_params, master=new_master, opt_state=new_opt,
                                   micro_steps=state.micro_steps + gas,
                                   global_steps=state.global_steps + 1)
            return state, loss

        def train_batch_fn(state: TrainState, batch, rng):
            lr = self._lr_fn(state.global_steps)
            state, loss = step(state, batch, rng, lr)
            return state, {"loss": loss, "lr": lr, "loss_scale": state.scaler.loss_scale}

        return jax.jit(train_batch_fn, donate_argnums=(0,))

    def _build_train_batch_fn(self, gas: int) -> Callable:
        """Fused GAS-scan + update, one XLA program. gas == 1 skips the scan
        and the accumulation buffers entirely: the micro-step grads feed the
        optimizer update directly (no acc read/write/re-zero, no scan
        barrier between backward and update)."""
        if self._onebit is not None:
            return self._build_onebit_batch_fn(gas)

        if gas == 1:
            def train_batch_fn(state: TrainState, batch, rng):
                mb = jax.tree.map(lambda x: x[0], batch)
                # fold_in(rng, 0) matches the scan path's micro-step-0 stream
                loss, grads = self._micro_grads(state.params, mb,
                                                jax.random.fold_in(rng, 0),
                                                state.scaler.loss_scale)
                grads = jax.lax.with_sharding_constraint(
                    jax.tree.map(lambda g: g.astype(self.grad_acc_dtype), grads),
                    self._grad_shardings)
                state = state._replace(micro_steps=state.micro_steps + 1)
                state, aux = self._apply_update(state, 1, acc=grads)
                return state, {"loss": loss, "lr": self._lr_fn(state.global_steps - 1),
                               "loss_scale": state.scaler.loss_scale, **aux}

            return jax.jit(train_batch_fn, donate_argnums=(0,))

        def train_batch_fn(state: TrainState, batch, rng):
            scale = state.scaler.loss_scale

            def micro(carry, mb):
                acc, i = carry
                mb_rng = jax.random.fold_in(rng, i)
                loss, grads = self._micro_grads(state.params, mb, mb_rng, scale)
                acc = self._accumulate(acc, grads)
                return (acc, i + 1), loss

            (acc, _), losses = jax.lax.scan(micro, (state.acc_grads, jnp.asarray(0, jnp.int32)), batch, length=gas)
            state = state._replace(acc_grads=acc, micro_steps=state.micro_steps + gas)
            state, aux = self._apply_update(state, gas)
            mean_loss = jnp.mean(losses)
            return state, {"loss": mean_loss, "lr": self._lr_fn(state.global_steps - 1),
                           "loss_scale": state.scaler.loss_scale, **aux}

        return jax.jit(train_batch_fn, donate_argnums=(0,))

    # ------------------------------------------------------------------ #
    # public API

    def train_batch(self, batch=None, data_iter=None):
        """Run one full training batch (gas micro-steps + update) as a single
        compiled program. ``batch`` leaves have leading dim
        ``gas * micro_bs * dp_size`` (this process's share of the global
        batch), or pass ``data_iter`` yielding ``gas`` micro-batches of
        ``micro_bs * dp_size`` samples each."""
        self._check_compression_epoch()
        # snapshot for was_step_applied: +0 makes a fresh buffer so the
        # donated state array's invalidation can't reach it (no host sync)
        self._skipped_before_step = self.state.skipped_steps + 0
        gas = self.gradient_accumulation_steps()
        micro_bs = self.train_micro_batch_size_per_gpu()
        dp = dist.get_world_size(dist.data_parallel_axes(self.mesh))
        expected = gas * micro_bs * dp
        if batch is not None and getattr(self, "_batch_fn", None) is not None:
            # reference semantics: batch_fn normalizes the raw batch BEFORE
            # any shape validation or splitting
            batch = self._batch_fn(batch)
        if batch is not None:
            lead = jax.tree.leaves(batch)[0].shape[0]
            if lead != expected:
                raise ValueError(
                    f"train_batch leading dim {lead} != gas({gas}) * micro_bs({micro_bs}) * dp({dp}) = {expected}")

        if batch is None:
            if data_iter is None:
                data_iter = getattr(self, "_data_iterator", None)
            if data_iter is None:
                if self.training_dataloader is None:
                    raise ValueError("train_batch needs a batch, a data_iter, or engine training_data")
                # standing sequential stream rolling over epochs — the same
                # stream auto_resume's fast-forward reconstructs, so resume
                # stays step-identical on the engine-owned dataloader (a
                # fresh iter() per call would replay the epoch head forever)
                from deepspeed_tpu.runtime.dataloader import RepeatingLoader
                self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
                self._data_iter_external = False
                data_iter = self._data_iterator
            micros = [next(data_iter) for _ in range(gas)]
            if getattr(self, "_batch_fn", None) is not None:
                micros = [self._batch_fn(m) for m in micros]
            batch = jax.tree.map(lambda *xs: jnp.stack(xs), *micros)
        else:
            batch = jax.tree.map(lambda x: jnp.reshape(jnp.asarray(x), (gas, -1) + tuple(x.shape[1:])), batch)

        self._host_global_steps += 1

        # flops profiler (reference engine.py:1664,2060): one-shot profile of
        # the loss computation at the configured step
        fp_cfg = self._config.flops_profiler_config
        if fp_cfg.enabled and self._host_global_steps == fp_cfg.profile_step:
            from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
            prof = FlopsProfiler(model=self.module, ds_engine=self,
                                 recompute_fwd_factor=fp_cfg.recompute_fwd_factor)
            micro = jax.tree.map(lambda x: x[0][:self.train_micro_batch_size_per_gpu()], batch)
            prof.profile_fn(lambda p, b: self.loss_fn(p, b, jax.random.key(0)),
                            self.state.params, micro)
            prof.print_model_profile(profile_step=self._host_global_steps,
                                     output_file=fp_cfg.output_file)
            self.flops_profiler = prof

        # curriculum learning: truncate the sequence dim to the scheduled
        # difficulty (reference engine.py:1691-1694 legacy seqlen curriculum).
        # Only dims equal to the batch's sequence length are sliced, so 2-D
        # masks [.., S, S] truncate on BOTH key/query dims and non-sequence
        # feature dims stay intact.
        if self.curriculum_scheduler is not None and self._curriculum_metric == "seqlen":
            difficulty = self.curriculum_scheduler.update_difficulty(self._host_global_steps)
            leaves = jax.tree.leaves(batch)
            seq = max((x.shape[2] for x in leaves if x.ndim >= 3), default=0)
            if difficulty < seq:
                def trunc(x):
                    # leaves are [gas, B, S, ...]: the sequence dim is dim 2;
                    # dim 3 is sliced ONLY for square [.., S, S] attention
                    # masks — a feature dim that merely equals S (e.g.
                    # one-hot labels with vocab == S) must stay intact
                    if x.ndim >= 3 and x.shape[2] == seq:
                        x = jax.lax.slice_in_dim(x, 0, difficulty, axis=2)
                        if x.ndim == 4 and x.shape[3] == seq:
                            x = jax.lax.slice_in_dim(x, 0, difficulty, axis=3)
                    return x
                batch = jax.tree.map(trunc, batch)

        # shard the batch over the data axes
        dp_axes = tuple(dist.data_parallel_axes(self.mesh))
        if dp_axes:
            bat = dp_axes if len(dp_axes) > 1 else dp_axes[0]
            sp = "sp" if ("sp" in self.mesh.shape and self.mesh.shape["sp"] > 1) else None

            sp_size = self.mesh.shape["sp"] if sp else 0

            def shard_leaf(x):
                # [gas, B, ...]; seq dim (2) additionally sharded over sp
                # when it divides evenly (non-sequence leaves fall back to dp-only)
                if sp and x.ndim >= 3 and x.shape[2] % sp_size == 0:
                    spec = P(None, bat, sp)
                else:
                    spec = P(None, bat)
                return jax.device_put(x, NamedSharding(self.mesh, spec))

            batch = jax.tree.map(shard_leaf, batch)

        self.tput_timer.start()
        prof = self._profiler
        if prof is not None:
            # profile-window boundary: starts/stops the jax.profiler
            # capture when an armed window begins/ends at this step
            prof.tick()
        t0 = time.perf_counter() if self._telemetry is not None else 0.0
        self._rng, step_rng = jax.random.split(self._rng)
        with (prof.annotate("train_batch") if prof is not None
              and prof.active else contextlib.nullcontext()):
            if self._offload is not None:
                fn = self._accum_batch_jit.get(gas)
                if fn is None:
                    fn = self._watched(self._build_accum_batch_fn(gas),
                                       f"engine.accum_batch[gas={gas}]")
                    self._accum_batch_jit[gas] = fn
                self.state, mean_loss = fn(self.state, batch, step_rng)
                self._losses = mean_loss
                metrics = self._host_step()
            else:
                fn = self._train_batch_jit.get(gas)
                if fn is None:
                    fn = self._watched(self._build_train_batch_fn(gas),
                                       f"engine.train_batch[gas={gas}]")
                    self._train_batch_jit[gas] = fn
                self.state, metrics = fn(self.state, batch, step_rng)
        self.tput_timer.stop(global_step=True)
        self._data_progress["iterations"] += 1
        self._data_progress["consumed_samples"] += self.train_batch_size()
        if self._telemetry is not None:
            # telemetry-on accepts one host sync per step: the wall clock
            # must bracket the device work for step time / MFU to mean
            # anything (off-mode never reaches this branch)
            jax.block_until_ready(metrics["loss"])
            dt_s = time.perf_counter() - t0
            # wait = host time since the previous step's end up to this
            # step's dispatch: data loading + host-side prep — the
            # input-bound signal the data-stall detector compares against
            # the bracketed device time
            wait_s = (t0 - self._t_prev_step_end
                      if self._t_prev_step_end is not None else 0.0)
            self._tel_record_step(batch, dt_s, metrics, wait_s)
            if self._health is not None:
                # observe BEFORE the flush so a flush-step anomaly is in
                # the very snapshot it fired on (matches the step() order)
                self._observe_health(metrics, dt_s, wait_s)
            self._tel_maybe_flush()
            self._t_prev_step_end = time.perf_counter()
        if self.quantizer is not None:
            self._quantize_step(batch)
        self._write_monitor_events(metrics)
        self._report_progress(metrics)
        return metrics["loss"]

    def _quantize_step(self, batch):
        """MoQ post-step hook (reference fp16 optimizers calling
        ``quantizer.quantize`` after each step, runtime/quantize.py): walks
        the per-leaf bit schedule and fake-quantizes the live params. With
        eigenvalue enabled, per-block curvature is re-estimated at gas
        boundaries while a precision switch is pending, and the MEAN across
        blocks scales the stacked-layers leaves' periods (deviation from the
        reference's per-block factor, forced by the stacked-layers leaf
        layout; max is useless here because post_process normalizes the
        largest eigenvalue to 1.0)."""
        # fp16 overflow steps skipped their update: don't advance the bit
        # schedule on them either (reference defers quantize on overflow)
        overflow = False
        if self.fp16_enabled():
            cur = int(self.state.skipped_steps)
            overflow = cur > self._moq_seen_skipped
            self._moq_seen_skipped = cur

        block_ev = None
        if self.eigenvalue is not None and \
                self._host_global_steps % self.eigenvalue.gas_boundary_resolution == 0 \
                and self.quantizer.any_precision_switch():
            micro = jax.tree.map(lambda x: x[0], batch)
            params = self.state.params
            name = self._ev_layer_name
            n_blocks = self._ev_layer_num or 0
            if n_blocks > 0 and name in params:
                masks = self.eigenvalue.layer_masks(params, name, n_blocks)
            else:
                masks = [jax.tree.map(lambda a: jnp.ones(a.shape, jnp.float32), params)]
            self._rng, ev_rng = jax.random.split(self._rng)

            def scalar_loss(p):
                out = self.loss_fn(p, micro, ev_rng)
                return out[0] if isinstance(out, tuple) else out

            vals = self.eigenvalue.compute_eigenvalue(
                scalar_loss, params, masks, rng=ev_rng)
            # post_process normalizes to [0,1] with max==1; the zoo stacks
            # all layers in one leaf, so aggregate with the MEAN (a
            # max would be the constant 1.0 and carry no information)
            block_ev = {name: sum(vals) / len(vals)} if vals else None
        new_params = self.quantizer.quantize_tree(self.state.params,
                                                  overflow=overflow,
                                                  block_eigenvalue=block_ev)
        # quantize ops run eagerly: pin the results back onto the param
        # shardings so the donated train-step jit sees identical layouts
        new_params = jax.device_put(new_params, self._param_shardings)
        self.state = self.state._replace(params=new_params)

    def _check_compression_epoch(self) -> None:
        """A CompressionScheduler transition changes what the model
        computes; compiled programs captured the OLD trace, so drop them
        when the wrapped model's epoch moved. Consulted on every public
        entry that traces the model (train_batch / forward / backward /
        eval_batch); step() needs no check — _apply_jit only runs the
        optimizer update, never the model."""
        epoch = getattr(self.client_model, "compression_epoch", None)
        if epoch is not None and epoch != getattr(self, "_compression_epoch_seen", None):
            if getattr(self, "_compression_epoch_seen", None) is not None:
                self._train_batch_jit.clear()
                self._grad_jit = self._apply_jit = self._eval_jit = None
            self._compression_epoch_seen = epoch

    # ---- reference-shaped trio ---- #

    def forward(self, batch):
        """Compute loss AND grads for a micro-batch in one pass (value_and_grad
        costs the same as grad alone); grads are cached so ``backward()`` just
        accumulates them — the reference's fwd/bwd split without running the
        model twice."""
        self._check_compression_epoch()
        if self._grad_jit is None:
            def vg_fn(state: TrainState, b, rng):
                return self._micro_grads(state.params, b, rng, state.scaler.loss_scale)
            self._grad_jit = self._watched(jax.jit(vg_fn), "engine.forward")
        batch = jax.tree.map(jnp.asarray, batch)
        self._rng, rng = jax.random.split(self._rng)
        t0 = time.perf_counter()
        loss, grads = self._grad_jit(self.state, batch, rng)
        self._tel_phase("fwd", t0, loss)
        self._cached_grads = grads
        self._losses = loss
        return loss

    __call__ = forward

    def backward(self, loss=None, batch=None, allreduce_gradients=True, release_loss=False):
        """Accumulate the grads computed by ``forward()`` (or compute them for
        an explicitly given micro-batch)."""
        if batch is not None:
            # forward() owns the whole micro-grad path (compression-epoch
            # check, batch conversion, rng split, jit build) — delegating
            # keeps the rng stream identical to the forward()+backward() style
            self.forward(batch)
        if getattr(self, "_cached_grads", None) is None:
            raise RuntimeError("backward() called before forward(); pass batch= explicitly if needed")
        self._ensure_acc_grads()

        if self._acc_jit is None:
            def acc_fn(state: TrainState, grads):
                acc = self._accumulate(state.acc_grads, grads)
                return state._replace(acc_grads=acc, micro_steps=state.micro_steps + 1)
            self._acc_jit = self._watched(jax.jit(acc_fn, donate_argnums=(0,)),
                                          "engine.backward")

        t0 = time.perf_counter()
        self.state = self._acc_jit(self.state, self._cached_grads)
        self._tel_phase("bwd", t0, self.state.micro_steps)
        self._cached_grads = None
        return self._losses

    def _uses_acc_grad_buffers(self) -> bool:
        """Whether the compiled step reads/writes state.acc_grads (the
        gas==1 fused path, the 1-bit path, and 1F1B pipelines do not)."""
        if self._onebit is not None:
            return False
        return not (self.gradient_accumulation_steps() == 1 and self._offload is None)

    def _ensure_acc_grads(self) -> None:
        """Materialize the accumulation buffers the gas==1 fused path skips
        (only the forward/backward/step trio needs them)."""
        if self.state.acc_grads == ():
            acc = jax.tree.map(
                lambda p, s: jax.device_put(jnp.zeros(p.shape, self.grad_acc_dtype), s),
                self.state.params, self._grad_shardings)
            self.state = self.state._replace(acc_grads=acc)

    def _zeroed_acc(self, acc):
        """Zero the accumulation buffers through the donated reset jit —
        reuses the buffers in place (no transient second tree)."""
        if self._reset_acc_jit is None:
            self._reset_acc_jit = jax.jit(
                lambda a: jax.tree.map(jnp.zeros_like, a), donate_argnums=(0,))
        return self._reset_acc_jit(acc)

    def is_gradient_accumulation_boundary(self) -> bool:
        return int(self.state.micro_steps) % self.gradient_accumulation_steps() == 0

    def step(self, lr_kwargs=None):
        """Apply the optimizer update at the accumulation boundary
        (no-op otherwise, matching reference engine.py:1990)."""
        if not self.is_gradient_accumulation_boundary():
            return
        self._skipped_before_step = self.state.skipped_steps + 0
        if self._offload is not None:
            t0 = time.perf_counter()
            metrics = self._host_step()
            if self._telemetry is not None:
                self._host_global_steps += 1
                self._tel_record_update(metrics)
                # wait/stall series record under plain telemetry, exactly
                # like the train_batch path (health only adds detectors)
                busy, wait = self._trio_wait_busy(
                    self._trio_busy_s + time.perf_counter() - t0)
                if self._health is not None:
                    self._observe_health(metrics, busy, wait)
                self._tel_maybe_flush()
            self._write_monitor_events(metrics)
            self._report_progress(metrics)
            return
        if self._apply_jit is None:
            gas = self.gradient_accumulation_steps()
            self._apply_jit = self._watched(
                jax.jit(partial(self._apply_update, gas=gas), donate_argnums=(0,)),
                "engine.step")
        t0 = time.perf_counter()
        self.state, aux = self._apply_jit(self.state)
        self._tel_phase("step", t0, self.state.global_steps)
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        metrics = {"loss": self._losses, "lr": self.get_lr()[0],
                   "loss_scale": self.state.scaler.loss_scale, **aux}
        if self._telemetry is not None:
            # keep the host step counter moving on the trio path too, so
            # the flush cadence (and snapshot step stamps) work without a
            # device fetch; train_batch and step() are alternative
            # boundaries, never both for one update
            self._host_global_steps += 1
            self._tel_record_update(metrics)
            # _tel_phase("step") above already folded this apply into the
            # cycle's busy accumulator; wait/stall series record under
            # plain telemetry (health only adds the detectors on top)
            busy, wait = self._trio_wait_busy(self._trio_busy_s)
            if self._health is not None:
                self._observe_health(metrics, busy, wait)
            self._tel_maybe_flush()
        self._write_monitor_events(metrics)
        self._report_progress(metrics)

    def eval_batch(self, batch):
        """Evaluation loss — DETERMINISTIC: the loss is called with rng=None,
        which the model zoo's convention reads as "no stochasticity" (dropout
        off, no MoE routing jitter/RTS draw), matching the reference's
        module.eval() semantics."""
        self._check_compression_epoch()
        if self._eval_jit is None:
            def eval_fn(params, b):
                out = self.loss_fn(params, b, None)
                return out[0] if isinstance(out, tuple) else out
            self._eval_jit = self._watched(jax.jit(eval_fn), "engine.eval_batch")
        return self._eval_jit(self.state.params, jax.tree.map(jnp.asarray, batch))

    # ------------------------------------------------------------------ #
    # accessors (reference engine.py:479-858 config properties)

    def train_batch_size(self) -> int:
        return self._config.train_batch_size

    def set_train_batch_size(self, train_batch_size: int) -> None:
        """Adjust the global batch by changing gradient-accumulation steps;
        the micro-batch size is unchanged (reference ``engine.py:426`` —
        elastic/curriculum batch scaling). The compiled step cache is keyed
        by gas, so a new gas compiles once and is then hot."""
        micro = self.train_micro_batch_size_per_gpu()
        dp = dist.get_world_size(dist.data_parallel_axes(self.mesh))
        if train_batch_size % (micro * dp):
            raise ValueError(
                f"Train batch size ({train_batch_size}) must be divisible by "
                f"micro-batch ({micro}) x data parallelism ({dp})")
        new_gas = train_batch_size // (micro * dp)
        if new_gas < 1:
            raise ValueError(f"Train batch size ({train_batch_size}) must cover "
                             f"at least one micro-batch per dp rank ({micro * dp})")
        self._config.train_batch_size = train_batch_size
        self._config.gradient_accumulation_steps = new_gas
        # the trio's cached apply step froze the OLD gas (grad divisor):
        # rebuild it at the new one
        self._apply_jit = None
        self.tput_timer.batch_size = train_batch_size
        if new_gas > 1 and self._acc_dtype_name is None and \
                self.grad_acc_dtype != jnp.float32:
            # engines born at gas==1 pinned accumulation to the compute dtype
            # (no buffers existed); gas>1 accumulates in fp32 per the
            # init-time rule, so restore it before (re)allocating buffers
            self.grad_acc_dtype = jnp.float32
            if self.state is not None and self.state.acc_grads != ():
                self.state = self.state._replace(acc_grads=())
        if self._uses_acc_grad_buffers():
            # the gas>1 scan path reads state.acc_grads; 1-bit/offload-free
            # gas==1 engines skip them entirely
            self._ensure_acc_grads()

    def train_micro_batch_size_per_gpu(self) -> int:
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self._config.gradient_accumulation_steps

    def gradient_clipping(self) -> float:
        return self._config.gradient_clipping

    def zero_optimization_stage(self) -> int:
        return self._config.zero_optimization_stage

    def fp16_enabled(self) -> bool:
        return self._config.fp16_enabled

    def bfloat16_enabled(self) -> bool:
        return self._config.bfloat16_enabled

    def steps_per_print(self) -> int:
        return self._config.steps_per_print

    def zero_enabled(self) -> bool:
        return self._config.zero_enabled

    # -- reference surface conveniences (engine.py:479-858, 2168-2510) -- #

    def zero_optimization(self) -> bool:
        return self._config.zero_optimization_stage > 0

    def optimizer_name(self):
        return self._optimizer_name

    def scheduler_name(self):
        return self._config.scheduler_name

    def scheduler_params(self):
        return self._config.scheduler_params

    def dynamic_loss_scale(self) -> bool:
        # static fp16 (loss_scale != 0) reports False, like the reference
        return self.fp16_enabled() and self._config.fp16_config.dynamic_loss_scale

    def wall_clock_breakdown(self) -> bool:
        return bool(self._config.wall_clock_breakdown)

    def pld_enabled(self) -> bool:
        return bool(self._config.pld_enabled)

    def curriculum_enabled_legacy(self) -> bool:
        return bool(self._config.curriculum_enabled_legacy)

    def random_ltd_enabled(self) -> bool:
        cfg = getattr(self._config, "data_efficiency_config", {}) or {}
        return bool(cfg.get("data_routing", {}).get("random_ltd",
                                                    {}).get("enabled", False))

    def get_batch_info(self):
        """(train_batch_size, micro_batch_size, gradient_accumulation_steps)
        — reference engine.py get_batch_info."""
        return (self.train_batch_size(),
                self.train_micro_batch_size_per_gpu(),
                self.gradient_accumulation_steps())

    def train(self, mode: bool = True):
        """torch-style mode toggle kept for port compatibility. The zoo is
        functional — train/eval behavior is chosen per call (e.g. MoE
        forward(train=...), eval_batch) — so this records intent only."""
        self._training_mode = bool(mode)
        return self

    def eval(self):
        return self.train(False)

    def was_step_applied(self) -> bool:
        """True when the most recent boundary step updated params (i.e. was
        not an fp16 overflow skip) — reference engine.py was_step_applied."""
        before = getattr(self, "_skipped_before_step", None)
        if before is None:
            return False
        return int(self.state.skipped_steps) == int(before)

    def module_state_dict(self):
        """The module parameters (reference module_state_dict: the
        checkpoint-shaped weights view)."""
        return self.state.params

    def load_module_state_dict(self, state_dict, strict: bool = True):
        """Replace the module parameters with ``state_dict``, resharded
        onto the engine's param shardings; fp32 masters (device or
        host-offloaded) follow so the optimizer continues from the new
        weights (reference load_module_state_dict). ``strict=False``
        overlays only the leaves present in ``state_dict`` (by path),
        keeping the rest."""
        from deepspeed_tpu.utils.pytree import leaf_key, leaf_paths

        if strict:
            import jax.tree_util as jtu
            if jtu.tree_structure(state_dict) != jtu.tree_structure(self.state.params):
                raise ValueError("state_dict structure does not match module "
                                 "parameters (pass strict=False to overlay "
                                 "matching leaves only)")
            new_params = jax.tree.map(
                lambda a, p: jax.device_put(jnp.asarray(a, p.dtype), p.sharding),
                state_dict, self.state.params)
        else:
            # pair by PATH KEY, never by flatten order (dict flattening is
            # key-sorted while leaf_paths preserves insertion order)
            overlay = leaf_paths(state_dict)
            leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
                self.state.params)
            new_leaves = [
                jax.device_put(jnp.asarray(overlay.get(leaf_key(path), p),
                                           p.dtype), p.sharding)
                for path, p in leaves_with_path]
            new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        replace = {"params": new_params}
        if self.state.master is not None:
            # cast on device: no host round-trip for model-sized trees
            replace["master"] = jax.tree.map(
                lambda a, m: jax.device_put(a.astype(jnp.float32), m.sharding),
                new_params, self.state.master)
        self.state = self.state._replace(**replace)
        if self._offload is not None:
            # host/NVMe fp32 masters are the authoritative weights for the
            # next step — refresh them or the load is silently reverted
            from deepspeed_tpu.utils.pytree import leaf_key
            flat_new = jax.tree_util.tree_flatten_with_path(new_params)[0]
            self._offload.load_masters(
                {leaf_key(path): np.asarray(jax.device_get(leaf), np.float32).ravel()
                 for path, leaf in flat_new})

    def set_dataloader(self, loader) -> None:
        """Reference pipe-engine surface: replace the training dataloader
        and start a STANDING iterator over it (successive batchless
        train_batch calls consume successive micro-batches, not the first
        gas items forever)."""
        self.training_dataloader = loader
        self._data_iterator = iter(loader) if loader is not None else None
        self._data_iter_external = False
        # progress describes the data pipeline; a new pipeline starts at 0
        self._data_progress = {"consumed_samples": 0, "iterations": 0}

    def set_dataiterator(self, iterator) -> None:
        """Reference pipe-engine surface: a standing iterator yielding
        micro-batches for batchless train_batch calls."""
        self._data_iterator = iterator
        self._data_iter_external = iterator is not None
        # progress describes the data pipeline; a new pipeline starts at 0
        self._data_progress = {"consumed_samples": 0, "iterations": 0}

    def set_batch_fn(self, fn) -> None:
        """Post-process every batch (or micro-batch from an iterator)
        before it enters the compiled step (reference set_batch_fn)."""
        self._batch_fn = fn

    def zero_grad(self) -> None:
        """Zero the gradient-accumulation buffers (reference zero_grad /
        optimizer.zero_grad between trio steps)."""
        if self.state.acc_grads != ():
            self.state = self.state._replace(
                acc_grads=self._zeroed_acc(self.state.acc_grads))
        self._cached_grads = None

    def empty_partition_cache(self) -> None:
        """Reference frees gathered ZeRO-3 params here; gathers live inside
        the compiled step under XLA's allocator, so there is no persistent
        partition cache to free. Kept as an explicit no-op."""

    def memory_breakdown(self):
        """Live-buffer breakdown per device (reference memory_breakdown /
        see_memory_usage)."""
        out = {}
        for d in jax.local_devices():
            try:
                stats = d.memory_stats() or {}
            except Exception:  # backend without memory stats (CPU)
                stats = {}
            out[str(d)] = {k: stats[k] for k in ("bytes_in_use",
                                                 "peak_bytes_in_use",
                                                 "bytes_limit") if k in stats}
        return out

    def dump_state(self) -> None:
        """Log a one-shot engine state summary (reference dump_state)."""
        log_dist(
            f"DeepSpeedEngine state: optimizer={self._optimizer_name}, "
            f"dtype={self.compute_dtype.__name__}, mesh={dict(self.mesh.shape)}, "
            f"batch={self.get_batch_info()}, zero_stage={self.zero_optimization_stage()}, "
            f"global_steps={self.global_steps}, skipped={self.skipped_steps}, "
            f"loss_scale={self.loss_scale}", ranks=[0])

    def save_16bit_model(self, save_dir, save_filename: str = "model_16bit.npz",
                         exclude_frozen_parameters: bool = False):
        """Write the module weights as a single 16-bit flat-key .npz
        (reference save_16bit_model / zero3 consolidated fp16 save — params
        here are full logical arrays, so no cross-rank gather is needed).
        Returns the written path."""
        import os

        from deepspeed_tpu.utils.pytree import leaf_paths

        if exclude_frozen_parameters:
            raise NotImplementedError(
                "exclude_frozen_parameters: the functional engine has no "
                "frozen-parameter registry; filter the tree before saving")

        os.makedirs(save_dir, exist_ok=True)
        path = os.path.join(save_dir, save_filename)
        flat = {}
        for key, leaf in leaf_paths(self.state.params).items():
            a = np.asarray(leaf)
            if a.dtype == np.float32:
                import ml_dtypes
                a = a.astype(ml_dtypes.bfloat16)
            # npz has no bf16: store raw bits + dtype tag
            if a.dtype.name == "bfloat16":
                flat[key + "::bf16"] = a.view(np.uint16)
            else:
                flat[key] = a
        np.savez(path, **flat)
        log_dist(f"saved 16-bit model weights to {path}", ranks=[0])
        return path

    def save_fp16_model(self, save_dir, save_filename: str = "model_16bit.npz"):
        """Reference alias for save_16bit_model."""
        return self.save_16bit_model(save_dir, save_filename)

    def destroy(self) -> None:
        """Drop compiled executables and large state references (reference
        engine.destroy): the engine is unusable afterwards."""
        self.disable_preemption_handler()
        if self._profiler is not None:
            self._profiler.stop()   # a dangling capture wedges the profiler
        if self._tel_sampler is not None:
            self._tel_sampler.stop()
            self._tel_sampler = None
        if self._tel_exporter is not None:
            self._tel_exporter.stop()
            self._tel_exporter = None
        if self._ckpt_writer is not None:
            self._ckpt_writer.stop()
            self._ckpt_writer = None
        self._train_batch_jit = {}
        self._grad_jit = None
        self._apply_jit = None
        self._eval_jit = None
        self._acc_jit = None
        self._reset_acc_jit = None
        self._cached_grads = None
        self._offload = None
        self.state = None

    @property
    def global_steps(self) -> int:
        return int(self.state.global_steps)

    @property
    def micro_steps(self) -> int:
        return int(self.state.micro_steps)

    @property
    def skipped_steps(self) -> int:
        return int(self.state.skipped_steps)

    def get_lr(self):
        return [float(self._lr_fn(self.state.global_steps))]

    def get_type(self):
        """Optimizer type per param group (reference engine.py:2171)."""
        return [self._optimizer_name]

    def get_mom(self):
        """Momentum per param group (reference engine.py:2174): SGD-family
        reports ``momentum``, Adam-family ``betas``; a client-supplied optax
        chain reports [None] (its momenta are not introspectable)."""
        from deepspeed_tpu.runtime.optimizers import optimizer_momenta
        return [optimizer_momenta(self._optimizer_name,
                                  self._config.optimizer_params)]

    def get_pld_theta(self):
        """Current progressive-layer-drop theta, or None when PLD is off
        (reference engine.py:2180)."""
        if self.progressive_layer_drop is not None:
            return self.progressive_layer_drop.get_theta()
        return None

    def get_global_grad_norm(self) -> float:
        if self.state.acc_grads == ():  # gas==1 fused path keeps no buffers
            return 0.0
        grads = jax.tree.map(lambda g: g.astype(jnp.float32), self.state.acc_grads)
        return float(global_norm(grads))

    @property
    def loss_scale(self) -> float:
        return float(self.state.scaler.loss_scale)

    @property
    def module(self):
        return self.client_model

    @property
    def optimizer(self):
        return self.tx

    def __getattr__(self, name):
        # delegate unknown attributes to the client model (reference :464)
        client = self.__dict__.get("client_model")
        if client is not None and hasattr(client, name):
            return getattr(client, name)
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    # ------------------------------------------------------------------ #
    # monitoring / reporting

    def _write_monitor_events(self, metrics) -> None:
        if not self.monitor.enabled:
            return
        step = self.global_steps
        events = [("Train/Samples/train_loss", float(metrics["loss"]), step),
                  ("Train/Samples/lr", float(metrics["lr"]), step)]
        if self.fp16_enabled():
            events.append(("Train/Samples/loss_scale", float(metrics["loss_scale"]), step))
        self.monitor.write_events(events)

    def _report_progress(self, metrics) -> None:
        if not self.steps_per_print():
            return  # no host-device sync when printing is off (keeps dispatch async)
        step = self.global_steps
        if step % self.steps_per_print() == 0:
            log_dist(f"step={step}, skipped={self.skipped_steps}, lr={float(metrics['lr']):.3e}, "
                     f"loss={float(metrics['loss']):.4f}", ranks=[0])

    # ------------------------------------------------------------------ #
    # telemetry

    def _watched(self, fn, name: str):
        """Route a compiled entry point through the compile watchdog when
        telemetry is on (counts compilations, records compile wall time +
        input shapes, flags recompilation storms)."""
        if self._telemetry is None:
            return fn
        return self._tel_watchdog.watch(fn, name)

    def _tel_phase(self, phase: str, t0: float, sync_on) -> None:
        """Record one trio-phase duration (blocks on ``sync_on`` so the
        wall clock brackets the device work)."""
        if self._telemetry is None:
            return
        jax.block_until_ready(sync_on)
        dur = time.perf_counter() - t0
        self._tel_phase_hist.labels(phase=phase).observe(dur * 1e3)
        if self._tel_events is not None:
            now = time.monotonic_ns()
            dur_ns = int(dur * 1e9)
            self._tel_events.emit("train.phase",
                                  step=self._host_global_steps,
                                  t_ns=now - dur_ns, dur_ns=dur_ns,
                                  phase=phase)
        # accumulated per update cycle: the trio path's device-busy time
        # (fwd + bwd + step), consumed by _trio_wait_busy at the boundary
        self._trio_busy_s += dur

    def _trio_wait_busy(self, busy_s: float):
        """Trio/offload boundary wait accounting: ``busy_s`` is the
        compiled/host work this cycle actually measured (accumulated phase
        durations); the wait is the REST of the boundary-to-boundary wall
        time — data loading and host prep between the timed calls — so the
        data-stall detector sees input-bound trio runs too, not just
        train_batch ones. Resets the cycle accumulators and feeds the
        cumulative train/data_stall_fraction gauge."""
        now = time.perf_counter()
        wall = (now - self._t_prev_step_end
                if self._t_prev_step_end is not None else busy_s)
        self._t_prev_step_end = now
        self._trio_busy_s = 0.0
        wait_s = max(wall - busy_s, 0.0)
        self._tel_account_wait(wait_s, busy_s)
        return busy_s, wait_s

    def _tel_account_wait(self, wait_s: float, busy_s: float) -> None:
        """The single home of wait/stall accounting (train_batch and the
        trio boundary both feed it): the data-wait histogram plus the
        cumulative wait/(wait+busy) stall gauge."""
        wait_s = max(wait_s, 0.0)
        self._tel_wait_hist.observe(wait_s * 1e3)
        self._tel_wait_total += wait_s
        self._tel_busy_total += max(busy_s, 0.0)
        tot = self._tel_wait_total + self._tel_busy_total
        if tot > 0:
            self._tel_stall_gauge.set(self._tel_wait_total / tot)

    def _tel_record_step(self, batch, dt_s: float, metrics=None,
                         wait_s: float = 0.0) -> None:
        """Per-step series: step time, tokens/sec, achieved TFLOPs + MFU
        (PaLM-style: model flops/token x token rate / peak), data-wait
        time, loss/grad-norm/fp16 gauges, plus the periodic JSONL /
        MonitorMaster flush (memory gauges sampled on the same cadence)."""
        self._tel_step_hist.observe(dt_s * 1e3)
        self._tel_steps_counter.inc()
        self._tel_tracer.add_event("train_batch",
                                   time.perf_counter() - dt_s, dt_s)
        if self._tel_events is not None:
            now = time.monotonic_ns()
            dur = int(dt_s * 1e9)
            self._tel_events.emit("train.step", step=self._host_global_steps,
                                  t_ns=now - dur, dur_ns=dur)
        lead = jax.tree.leaves(batch)[0]
        dims = lead.shape[:3] if lead.ndim >= 3 else lead.shape[:2]
        tokens = 1
        for d in dims:
            tokens *= int(d)
        tps = tokens / max(dt_s, 1e-9)
        self._tel_tokens_gauge.set(tps)
        self._tel_tokens_counter.inc(tokens)
        fpt = self._tel_flops_per_token(batch)
        n_chips = max(1, int(np.prod(list(self.mesh.shape.values()))))
        achieved = tps * fpt / 1e12 / n_chips
        self._tel_tflops_gauge.set(achieved)
        peak = self._tel_peak_tflops()
        self._tel_mfu_gauge.set(achieved / peak if peak > 0 else 0.0)
        self._tel_account_wait(wait_s, dt_s)
        if metrics is not None:
            self._tel_record_update(metrics)

    def _tel_maybe_flush(self) -> None:
        """JSONL/MonitorMaster flush on the ``steps_per_snapshot`` cadence,
        with memory gauges sampled just before (every step under health —
        host-side dict reads, ~µs). Shared by the train_batch path and the
        trio/offload step() boundary so a trio run feeds the sink (and the
        ``dscli health`` screen) too."""
        tcfg = self._telemetry
        n = tcfg.steps_per_snapshot
        flush = bool(n) and self._host_global_steps % n == 0
        if flush or self._health is not None:
            self._tel_sample_memory(self._tel_reg)
        if flush:
            if tcfg.jsonl_path:
                self._tel_reg.write_jsonl(tcfg.jsonl_path,
                                          step=self._host_global_steps)
            if tcfg.publish_to_monitor:
                self._tel_reg.publish(self.monitor, self._host_global_steps)

    def _tel_record_update(self, metrics) -> None:
        """Optimizer-update series shared by every path that applies an
        update (fused train_batch, the trio's step(), the offload host
        step): loss gauge, the pre-clip grad-norm histogram, and — fp16 —
        the skipped-steps / loss-scale gauges with a rate-limited warning
        when overflow skips persist (today's `lax.cond` skip is otherwise
        invisible unless you read the state object)."""
        import math as _math
        self._tel_loss_gauge.set(float(metrics["loss"]))
        gn = metrics.get("grad_norm")
        if gn is not None:
            gn = float(gn)
            if _math.isfinite(gn):
                self._tel_grad_norm_hist.observe(gn)
        if self.fp16_enabled():
            skipped = int(self.state.skipped_steps)
            # one blocking scalar fetch per step, shared with
            # _observe_health (which runs right after on every boundary)
            self._tel_skipped_cached = skipped
            self._tel_skipped_gauge.set(skipped)
            self._tel_scale_gauge.set(float(metrics["loss_scale"]))
            if self._tel_events is not None:
                if self._ev_skip_prev is not None \
                        and skipped > self._ev_skip_prev:
                    self._tel_events.emit(
                        "train.fp16_skip", step=self._host_global_steps,
                        skipped_total=skipped,
                        loss_scale=float(metrics["loss_scale"]))
                self._ev_skip_prev = skipped
            if self._health is None:
                # the HealthMonitor's sustained-overflow detector owns
                # this when enabled; health-off still surfaces it
                self._warn_sustained_skips(skipped)

    def _warn_sustained_skips(self, skipped_total: int) -> None:
        window = self._telemetry.health.overflow_window
        delta = skipped_total - self._tel_skip_seen
        self._tel_skip_seen = skipped_total
        self._tel_skip_consec = self._tel_skip_consec + 1 if delta > 0 else 0
        if window and self._tel_skip_consec and \
                self._tel_skip_consec % window == 0:
            logger.warning(
                f"fp16 overflow skipped the last {self._tel_skip_consec} "
                f"consecutive optimizer updates (total skipped "
                f"{skipped_total}, loss scale {self.loss_scale:.4g}). The "
                "run is making no progress — check for numerics issues or "
                "lower the initial loss scale.")

    def _observe_health(self, metrics, dt_s: float, wait_s: float) -> None:
        """Feed one step's record through the health detectors (host side;
        sentinel values were computed inside the compiled step and arrive
        as one small vector — fetching them costs no extra device sync
        beyond the one telemetry-on already performs)."""
        from deepspeed_tpu.monitor.health import StepHealth, sentinel_to_dict
        # global_steps, not _host_global_steps: the trio/offload step()
        # paths never bump the latter, and a constant step number would
        # permanently mute the per-detector warn/dump rate limiting
        rec = StepHealth(step=int(self.state.global_steps),
                         loss=float(metrics["loss"]),
                         loss_scale=float(metrics.get("loss_scale", 1.0)),
                         step_time_s=dt_s, wait_time_s=wait_s)
        gn = metrics.get("grad_norm")
        if gn is not None:
            rec.grad_norm = float(gn)
        sen = metrics.get("sentinels")
        if sen is not None:
            d = sentinel_to_dict(sen, self._health.bucket_names)
            rec.grad_norm = d["grad_norm"]
            rec.nonfinite_grads = d["nonfinite_grads"]
            rec.nonfinite_params = d["nonfinite_params"]
            rec.update_ratio = d["update_ratio"]
            rec.bucket_norms = tuple(d["bucket_norms"].values())
        elif "nonfinite_grads" in metrics:  # offload host path
            rec.nonfinite_grads = float(metrics["nonfinite_grads"])
        if self.fp16_enabled():
            # reuse _tel_record_update's single skipped_steps fetch;
            # detect this boundary's skip against the previous total
            after = getattr(self, "_tel_skipped_cached", None)
            if after is None:
                after = int(self.state.skipped_steps)
            prev = self._tel_skipped_prev
            if prev is None:
                before = getattr(self, "_skipped_before_step", None)
                prev = int(before) if before is not None else after
            rec.skipped = after > prev
            self._tel_skipped_prev = after
        self._health.observe_step(rec)

    def health_report(self) -> Dict:
        """The health observatory's one-call summary: anomaly counts,
        loss/grad-norm EWMAs, consecutive-skip and data-stall state, the
        last step record, and a fresh memory sample. ``{"enabled": False}``
        when ``telemetry.health`` is off."""
        if self._health is None:
            return {"enabled": False}
        return self._health.report()

    def _tel_flops_per_token(self, batch) -> float:
        """Training flops per token, computed once per engine: the flops
        profiler's ``cost_analysis()`` path on the loss forward for ONE
        sample (x3 for fwd+bwd, plus the configured recompute factor),
        falling back to the model's analytic ``flops_per_token``."""
        if self._tel_flops_per_token_v is not None:
            return self._tel_flops_per_token_v
        fpt = 0.0
        try:
            from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
            prof = FlopsProfiler(model=self.module, ds_engine=self)
            micro = jax.tree.map(lambda x: x[0][:1], batch)
            # rng=None: the zoo's deterministic eval convention — dropout
            # off changes flops negligibly and avoids threading an rng
            prof.profile_fn(lambda p, b: self.loss_fn(p, b, None),
                            self.state.params, micro)
            lead = jax.tree.leaves(micro)[0]
            micro_tokens = int(np.prod(lead.shape))
            fwd = float(prof.get_total_flops())
            if fwd > 0 and micro_tokens > 0:
                fac = 3.0 + float(getattr(self._config.flops_profiler_config,
                                          "recompute_fwd_factor", 0.0) or 0.0)
                fpt = fwd * fac / micro_tokens
        except Exception as e:  # profiling must never break the step
            logger.warning(f"telemetry: flops profile failed ({e}); "
                           "falling back to analytic flops_per_token")
        if not fpt:
            try:
                fpt = float(self.module.flops_per_token())
            except Exception:
                fpt = 0.0
        self._tel_flops_per_token_v = fpt
        return fpt

    def _tel_peak_tflops(self) -> float:
        """MFU denominator: config > DS_PEAK_TFLOPS env / accelerator
        device-kind table > 0 (gauge reads 0 rather than fabricating)."""
        p = float(self._telemetry.peak_tflops_per_chip or 0.0)
        if p > 0:
            return p
        try:
            from deepspeed_tpu.accelerator import get_accelerator
            return float(get_accelerator().peak_tflops())
        except Exception:
            return 0.0

    def telemetry_snapshot(self) -> Dict:
        """Whole-process registry snapshot plus the compile watchdog's
        summary. Empty dict when telemetry is off."""
        if self._telemetry is None:
            return {}
        if self._health is not None:
            # refresh the memory gauges so on-demand snapshots (and the
            # debug bundles that embed them) carry current HBM numbers
            self._tel_sample_memory(self._tel_reg)
        snap = self._tel_reg.snapshot()
        snap["compile"] = self._tel_watchdog.summary()
        return snap

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write recorded host spans as chrome-trace JSON (view in
        Perfetto / chrome://tracing); returns the path, or None when
        telemetry is off."""
        if self._telemetry is None:
            return None
        path = path or self._telemetry.chrome_trace_path
        if not path:
            raise ValueError("no trace path: pass one or set "
                             "telemetry.chrome_trace_path")
        return self._tel_tracer.export_chrome_trace(path)

    def profile(self, steps: int, log_dir: Optional[str] = None):
        """Arm an on-demand device-profile capture: the next ``steps``
        ``train_batch`` calls run under ``jax.profiler`` and the trace
        lands in ``log_dir`` (default ``telemetry.profile.dir``) —
        summarize with ``dscli profile <log_dir>``. Works with telemetry
        off (it is a profiler window, not a metrics feature); raises if a
        capture is already running. Returns the armed window."""
        if self._profiler is None:
            from deepspeed_tpu.monitor.trace import ProfileWindow
            pcfg = self._config.telemetry_config.profile
            self._profiler = ProfileWindow(log_dir or pcfg.dir)
        self._profiler.arm(steps, log_dir=log_dir)
        return self._profiler

    # ------------------------------------------------------------------ #
    # checkpointing

    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True,
                        asynchronous=None):
        """Two-phase crash-safe save. ``asynchronous`` overrides the config's
        ``checkpoint.async_save``: True snapshots device state to host and
        returns while the background writer persists/commits; False blocks
        until the tag is durably on disk."""
        from deepspeed_tpu.runtime.checkpoint_engine.engine import save_engine_checkpoint
        return save_engine_checkpoint(self, save_dir, tag=tag, client_state=client_state,
                                      save_latest=save_latest, asynchronous=asynchronous)

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False, strict=False, load_data_progress=False):
        from deepspeed_tpu.runtime.checkpoint_engine.engine import load_engine_checkpoint
        result = load_engine_checkpoint(self, load_dir, tag=tag,
                                        load_optimizer_states=load_optimizer_states,
                                        load_module_only=load_module_only,
                                        strict=strict,
                                        load_data_progress=load_data_progress)
        # resync the host-side curriculum counter with the restored step
        self._host_global_steps = int(self.global_steps)
        return result

    def auto_resume(self, save_dir, tag=None, strict=False):
        """Verified auto-resume: restore params/optimizer/loss-scaler/RNG/
        counters from the newest INTACT checkpoint under ``save_dir``
        (walking back past corrupt/partial tags) and fast-forward the data
        pipeline to the recorded progress, so the resumed loss curve is
        step-identical to an uninterrupted run. Returns ``(path,
        client_state)``; ``(None, {})`` when nothing is there to resume
        (fresh start) unless ``strict``."""
        return self.load_checkpoint(save_dir, tag=tag, strict=strict,
                                    load_data_progress=True)

    def flush_checkpoints(self, timeout=None):
        """Block until every queued async checkpoint is durably committed.
        Raises the writer's error if a queued save failed."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.drain(timeout=timeout, raise_on_error=True)

    def emergency_save(self, save_dir):
        """Preemption-grace save: drain in-flight async saves (best effort,
        bounded — preemption grace windows are short and the synchronous
        save below captures newer state anyway), then take one synchronous
        verified save of the current state."""
        if self._ckpt_writer is not None:
            try:
                self._ckpt_writer.drain(timeout=30)
            except Exception as e:
                logger.warning(f"emergency save: drain failed ({e}); "
                               f"taking the synchronous save anyway")
        result = self.save_checkpoint(save_dir, asynchronous=False)
        # ship the flight-recorder tail next to the emergency tag: the
        # post-mortem gets the event timeline leading into the signal
        # (no-op when the recorder is off; never fails the save)
        from deepspeed_tpu.monitor.events import dump_events_jsonl
        dump_events_jsonl(save_dir)
        return result

    def enable_preemption_handler(self, save_dir, signals=None,
                                  exit_on_signal=True):
        """Install the SIGTERM/SIGINT grace handler: on signal, drain the
        checkpoint writer, emergency-save to ``save_dir``, exit
        ``128+signum`` (TPU preemption / maintenance SIGTERMs become clean
        resumable exits)."""
        from deepspeed_tpu.runtime.checkpoint_engine.safe_engine import PreemptionHandler
        if self._preemption is not None:
            self._preemption.uninstall()
        kwargs = {} if signals is None else {"signals": tuple(signals)}
        self._preemption = PreemptionHandler(
            self, save_dir, exit_on_signal=exit_on_signal, **kwargs).install()
        return self._preemption

    def disable_preemption_handler(self):
        if self._preemption is not None:
            self._preemption.uninstall()
            self._preemption = None

    def _checkpoint_writer(self):
        """Lazy per-engine async writer; failures feed checkpoint metrics
        and the health observatory's ckpt_failure detector."""
        if self._ckpt_writer is None:
            from deepspeed_tpu.runtime.checkpoint_engine.engine import (
                _checkpoint_cfg, _notify_ckpt_result)
            from deepspeed_tpu.runtime.checkpoint_engine.safe_engine import AsyncCheckpointWriter
            ccfg = _checkpoint_cfg(self)
            self._ckpt_writer = AsyncCheckpointWriter(
                max_pending=ccfg.max_pending,
                retries=ccfg.retries,
                retry_backoff_s=ccfg.retry_backoff_s,
                keep_last=ccfg.keep_last,
                on_result=lambda ok, steps: _notify_ckpt_result(self, ok, steps))
        return self._ckpt_writer

    def _fast_forward_data(self, iterations):
        """Advance the data pipeline past ``iterations`` already-consumed
        train_batch calls (``iterations * gas`` micro-batches) so resume
        neither replays nor skips batches. Works on the engine's standing
        ``set_dataiterator`` iterator (advanced in place — re-create it
        fresh before auto_resume) or on ``training_dataloader`` (epoch
        seed + in-epoch position recomputed, then a standing iterator that
        rolls over epochs is installed)."""
        micro = int(iterations) * self.gradient_accumulation_steps()
        if micro <= 0:
            return
        it = getattr(self, "_data_iterator", None)
        # a loader-derived standing iterator (set_dataloader / train_batch's
        # auto-install) is NOT advanced in place: it is a plain single-epoch
        # iter that StopIterations past the first epoch and knows nothing of
        # shuffle-seed replay — the loader path below re-creates it at the
        # right position instead
        if it is not None and (getattr(self, "_data_iter_external", False)
                               or self.training_dataloader is None):
            for _ in range(micro):
                next(it)
            log_dist(f"auto_resume: fast-forwarded data iterator by "
                     f"{micro} micro-batches", ranks=[0])
            return
        if self.training_dataloader is None:
            logger.warning(
                f"auto_resume: {micro} micro-batches of recorded progress "
                f"but no engine-owned data pipeline to fast-forward; pass a "
                f"freshly-created iterator via set_dataiterator BEFORE "
                f"auto_resume, or expect replayed batches")
            return
        from deepspeed_tpu.runtime.dataloader import resume_loader_iterator
        self._data_iterator = resume_loader_iterator(
            self.training_dataloader, micro)
        self._data_iter_external = False
        log_dist(f"auto_resume: dataloader fast-forwarded by {micro} "
                 f"micro-batches", ranks=[0])
