"""Training-time progressive quantization (QAT scheduler).

Reference parity: ``deepspeed/runtime/quantize.py:13`` (``Quantizer`` —
per-parameter bit-width schedule that walks ``start_bits → target_bits``,
doubling the period at each drop; optional eigenvalue-guided stretching
(curvier blocks quantize slower, factor ``1 + floor(λ·4)``); mixed-fp16
blending that anneals from the fp16 value to the quantized one; high-bit
sym/asym with nearest or stochastic rounding, ternary (2-bit,
0.7·mean-|x| threshold) and binary (sign·mean-|x|) low-bit modes).

Functional redesign: parameters are pytree leaves, so the per-param state
(current bits, period) lives in the ``Quantizer`` keyed by tree path, and
``quantize_tree`` maps ``params → params`` — pure array math inside, host
schedule outside (bit drops happen O(log) times per run, not per step).
The stochastic path routes through the named SR op
(:mod:`deepspeed_tpu.ops.quantizer.kernels`).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

TWO_D_PARAMS = 6


def _quantize_highbit(x, bits: int, groups: int, symmetric: bool, stochastic: bool,
                      seed: int):
    if stochastic:
        from deepspeed_tpu.ops.quantizer.kernels import (ds_sr_quantize,
                                                         ds_sr_quantize_asym)
        return (ds_sr_quantize(x, groups, bits, seed=seed) if symmetric
                else ds_sr_quantize_asym(x, groups, bits, seed=seed))
    from deepspeed_tpu.ops.quantizer.kernels import ds_quantize, ds_quantize_asym
    return ds_quantize(x, groups, bits) if symmetric else \
        ds_quantize_asym(x, groups, bits)


def _quantize_ternary(x, groups: int):
    flat = x.astype(jnp.float32).reshape(groups, -1)
    n = flat.shape[1]
    m = jnp.sum(jnp.abs(flat), axis=1, keepdims=True) / n
    thres = 0.7 * m
    mask = jnp.abs(flat) > thres
    denom = jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1)
    alpha = jnp.sum(jnp.abs(flat) * mask, axis=1, keepdims=True) / denom
    out = jnp.where(flat > thres, alpha, jnp.where(flat < -thres, -alpha, 0.0))
    return out.reshape(x.shape).astype(x.dtype)


def _quantize_binary(x, groups: int):
    flat = x.astype(jnp.float32).reshape(groups, -1)
    m = jnp.sum(jnp.abs(flat), axis=1, keepdims=True) / flat.shape[1]
    return (jnp.sign(flat) * m).reshape(x.shape).astype(x.dtype)


class Quantizer:
    """Reference constructor surface; ``layer_paths`` replaces the
    id()-keyed param registry (functional trees have no stable ids)."""

    def __init__(self, q_groups: int = 1, q_mixed_fp16: bool = False,
                 q_change_ratio: float = 0.01, q_type: str = "symmetric",
                 q_rounding: str = "nearest", q_verbose: bool = False,
                 q_eigenvalue: bool = False, start_bits: int = 16,
                 target_bits: int = 8, q_period: int = 100):
        self.q_groups = q_groups
        self.q_mixed_fp16 = q_mixed_fp16
        self.q_change_ratio = q_change_ratio
        self.q_type = q_type
        self.q_rounding = q_rounding
        self.q_verbose = q_verbose
        self.q_eigenvalue = q_eigenvalue
        self.qsteps = 0
        self.quantize_real_ratio = 1.0
        self.start_bits = start_bits
        self.target_bits = target_bits
        self.default_period = q_period
        # per-leaf schedule state: path -> {"bits": int, "period": int}
        self._state: Dict[Any, Dict[str, int]] = {}

    # -------------------- checkpoint -------------------- #

    def state_dict(self):
        """Host schedule state (saved in engine checkpoints so a resumed run
        continues mid-schedule instead of resetting to start_bits)."""
        return {"qsteps": self.qsteps,
                "quantize_real_ratio": self.quantize_real_ratio,
                "leaf_state": {k: dict(v) for k, v in self._state.items()}}

    def load_state_dict(self, sd):
        self.qsteps = int(sd["qsteps"])
        self.quantize_real_ratio = float(sd["quantize_real_ratio"])
        self._state = {k: dict(v) for k, v in sd["leaf_state"].items()}

    # -------------------- schedule -------------------- #

    def step(self):
        self.qsteps += 1

    def update_fp16_ratio(self):
        if self.q_mixed_fp16:
            self.quantize_real_ratio = max(0.0, self.quantize_real_ratio -
                                           self.q_change_ratio)

    def _leaf_state(self, path):
        if path not in self._state:
            self._state[path] = {"bits": self.start_bits,
                                 "period": self.default_period}
        return self._state[path]

    def any_precision_switch(self) -> bool:
        """Will any leaf drop a bit within the next schedule window?
        (reference ``any_precision_switch`` — gates eigenvalue recompute)."""
        if not self._state:
            return True
        n = max(len(self._state), 1)
        return any(st["bits"] != self.target_bits and
                   self.qsteps + TWO_D_PARAMS * n >= st["period"]
                   for st in self._state.values())

    # -------------------- quantization -------------------- #

    def _compute_one(self, path, x, eigenvalue: Optional[float], leaf_idx: int = 0):
        st = self._leaf_state(path)
        if st["bits"] != self.target_bits and self.qsteps >= st["period"]:
            factor = 1 + math.floor(eigenvalue * 4) if eigenvalue is not None else 1
            self.quantize_real_ratio = 1.0
            st["period"] = (st["period"] << 1) * factor
            st["bits"] -= 1
            if self.q_verbose:
                logger.info(f"quantize {path}: bits={st['bits']} "
                            f"step={self.qsteps} period={st['period']}")
        if st["bits"] < self.target_bits:
            raise ValueError("Quantization bit is lower than target precision bits!")

        bits = st["bits"]
        sym = self.q_type == "symmetric"
        if bits >= 3:
            # mix the leaf index into the seed: same-shaped tensors must not
            # draw the same rounding noise in a given step
            q = _quantize_highbit(x, bits, self.q_groups, sym,
                                  stochastic=self.q_rounding != "nearest",
                                  seed=self.qsteps + 7919 * leaf_idx)
        elif bits == 2:
            if not sym or self.q_rounding != "nearest":
                raise ValueError("ternary quantization requires symmetric/nearest")
            q = _quantize_ternary(x, self.q_groups)
        else:
            if not sym or self.q_rounding != "nearest":
                raise ValueError("binary quantization requires symmetric/nearest")
            q = _quantize_binary(x, self.q_groups)

        if self.q_mixed_fp16 and bits >= self.target_bits - 1:
            q = self.quantize_real_ratio * x + (1 - self.quantize_real_ratio) * q
        return q

    def quantize_tree(self, params, overflow: bool = False,
                      block_eigenvalue: Optional[Dict[str, float]] = None):
        """Quantize every rank>=2 leaf per its schedule; ``block_eigenvalue``
        maps an exact path SEGMENT (e.g. a layer name key) to its normalized
        eigenvalue (reference ``quantize(parameter_group, overflow, ...)``)."""
        if overflow and not self.q_eigenvalue:
            return params
        self.step()
        self.update_fp16_ratio()

        flat = jax.tree_util.tree_flatten_with_path(params)
        leaves, treedef = flat
        out = []
        for idx, (path, leaf) in enumerate(leaves):
            key = jax.tree_util.keystr(path)
            # exact path segments, so "layer1" cannot match "layer10"
            segments = {str(getattr(e, "key", getattr(e, "idx", e)))
                        for e in path}
            if getattr(leaf, "ndim", 0) < 2:
                out.append(leaf)
                continue
            ev = None
            if block_eigenvalue:
                for prefix, val in block_eigenvalue.items():
                    if prefix in segments:
                        ev = val
                        break
            out.append(self._compute_one(key, leaf, ev, leaf_idx=idx))
        return jax.tree_util.tree_unflatten(treedef, [l for l in out])
