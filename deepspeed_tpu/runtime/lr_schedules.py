"""Learning-rate schedules.

Reference parity: ``deepspeed/runtime/lr_schedules.py`` — ``LRRangeTest``,
``OneCycle``, ``WarmupLR``, ``WarmupDecayLR`` with the same knob names.

TPU-native design: each schedule is a *pure function* ``step -> lr`` so it can
live inside the compiled train step (no host round-trip per step). The class
wrappers keep the reference's stateful surface (``step()``, ``get_lr()``,
``state_dict()``/``load_state_dict()``) for drop-in use and checkpointing.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


# --------------------------------------------------------------------- #
# Pure schedule functions (jit-safe; use jnp when the input is traced)

def _np(step):
    import jax.numpy as jnp
    return jnp if hasattr(step, "dtype") or hasattr(step, "aval") else math


def lr_range_test_fn(lr_range_test_min_lr: float = 1e-3,
                     lr_range_test_step_size: int = 2000,
                     lr_range_test_step_rate: float = 1.0,
                     lr_range_test_staircase: bool = False) -> Callable:
    """Increasing-LR sweep for finding stable LR ranges."""

    def schedule(step):
        import jax.numpy as jnp
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval) if hasattr(interval, "dtype") else math.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle_fn(cycle_min_lr: float,
                 cycle_max_lr: float,
                 decay_lr_rate: float = 0.0,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 cycle_first_stair_count: int = 0,
                 cycle_second_stair_count: Optional[int] = None,
                 decay_step_size: int = 0) -> Callable:
    """1-cycle policy: ramp min→max over the first phase, back down over the
    second, then optional decay below min."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        import jax.numpy as jnp
        np_ = jnp
        up_frac = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down_frac = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        in_decay = step > total_cycle
        cycle_lr = jnp.where(
            step <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up_frac,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac,
        )
        if decay_step_size > 0:
            decay_intervals = jnp.floor((step - total_cycle) / decay_step_size)
            decayed = cycle_min_lr / (1.0 + decay_lr_rate * jnp.maximum(decay_intervals, 0.0))
            return jnp.where(in_decay, decayed, cycle_lr)
        return jnp.where(in_decay, cycle_min_lr, cycle_lr)

    return schedule


def warmup_lr_fn(warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000,
                 warmup_type: str = WARMUP_LOG_RATE) -> Callable:
    """Warm up from min to max then hold."""

    def schedule(step):
        import jax.numpy as jnp
        step_f = step * 1.0
        frac = jnp.clip(step_f / warmup_num_steps, 0.0, 1.0)
        if warmup_type == WARMUP_LOG_RATE:
            # log-shaped ramp: lr scales with log(step)/log(warmup_steps)
            gamma = jnp.where(step_f > 0, jnp.log1p(step_f) / math.log1p(warmup_num_steps), 0.0)
            gamma = jnp.clip(gamma, 0.0, 1.0)
        else:
            gamma = frac
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma

    return schedule


def warmup_decay_lr_fn(total_num_steps: int,
                       warmup_min_lr: float = 0.0,
                       warmup_max_lr: float = 0.001,
                       warmup_num_steps: int = 1000,
                       warmup_type: str = WARMUP_LOG_RATE) -> Callable:
    """Warm up then linearly decay to zero by ``total_num_steps``."""
    warm = warmup_lr_fn(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        import jax.numpy as jnp
        lr = warm(step)
        decay = jnp.clip(
            (total_num_steps - step) * 1.0 / max(1, total_num_steps - warmup_num_steps), 0.0, 1.0)
        return jnp.where(step <= warmup_num_steps, lr, warmup_max_lr * decay)

    return schedule


SCHEDULE_FNS = {
    LR_RANGE_TEST: lr_range_test_fn,
    ONE_CYCLE: one_cycle_fn,
    WARMUP_LR: warmup_lr_fn,
    WARMUP_DECAY_LR: warmup_decay_lr_fn,
}


def get_lr_schedule_fn(name: str, params: Dict[str, Any]) -> Callable:
    if name not in SCHEDULE_FNS:
        raise ValueError(f"Unknown LR schedule {name}; valid: {VALID_LR_SCHEDULES}")
    # drop reference-only knobs that do not affect the lr curve
    params = {k: v for k, v in params.items() if k not in ("cycle_momentum", "cycle_min_mom", "cycle_max_mom",
                                                           "decay_mom_rate", "last_batch_iteration")}
    return SCHEDULE_FNS[name](**params)


# --------------------------------------------------------------------- #
# Stateful wrappers (reference-shaped API)

class _ScheduleBase:
    """Stateful wrapper over a pure schedule fn; mirrors the reference's
    scheduler objects (step/get_lr/state_dict)."""

    def __init__(self, schedule_fn: Callable, last_batch_iteration: int = -1):
        self._fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self) -> List[float]:
        return [float(self._fn(max(0, self.last_batch_iteration)))]

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def state_dict(self) -> Dict:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict) -> None:
        self.last_batch_iteration = sd["last_batch_iteration"]

    @property
    def schedule_fn(self) -> Callable:
        return self._fn


class LRRangeTest(_ScheduleBase):

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3, lr_range_test_step_size=2000,
                 lr_range_test_step_rate=1.0, lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__(
            lr_range_test_fn(lr_range_test_min_lr, lr_range_test_step_size, lr_range_test_step_rate,
                             lr_range_test_staircase), last_batch_iteration)


class OneCycle(_ScheduleBase):

    def __init__(self, optimizer=None, cycle_min_lr=0.0, cycle_max_lr=0.001, decay_lr_rate=0.0,
                 cycle_first_step_size=2000, cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0, last_batch_iteration=-1, **_momentum_unused):
        super().__init__(
            one_cycle_fn(cycle_min_lr, cycle_max_lr, decay_lr_rate, cycle_first_step_size, cycle_second_step_size,
                         cycle_first_stair_count, cycle_second_stair_count, decay_step_size), last_batch_iteration)


class WarmupLR(_ScheduleBase):

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001, warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE, last_batch_iteration=-1):
        super().__init__(
            warmup_lr_fn(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type), last_batch_iteration)


class WarmupDecayLR(_ScheduleBase):

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE, last_batch_iteration=-1):
        super().__init__(
            warmup_decay_lr_fn(total_num_steps, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type),
            last_batch_iteration)


# ------------------------------------------------------------------ #
# CLI tuning arguments (reference lr_schedules.py:52-120
# add_tuning_arguments / parse_arguments / override_*_params)

def add_tuning_arguments(parser):
    """Add the LR-schedule tuning CLI group (reference ``:52``). Defaults
    are ``None`` so :func:`override_params` only overrides what the user
    actually passed."""
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help=f"LR schedule: one of {VALID_LR_SCHEDULES}")
    # LRRangeTest
    group.add_argument("--lr_range_test_min_lr", type=float, default=None)
    group.add_argument("--lr_range_test_step_size", type=int, default=None)
    group.add_argument("--lr_range_test_step_rate", type=float, default=None)
    # type=bool would turn ANY non-empty string (incl. "false") into True
    group.add_argument("--lr_range_test_staircase", default=None,
                       type=lambda s: s.lower() in ("1", "true", "yes"))
    # OneCycle
    group.add_argument("--cycle_first_step_size", type=int, default=None)
    group.add_argument("--cycle_first_stair_count", type=int, default=None)
    group.add_argument("--cycle_second_step_size", type=int, default=None)
    group.add_argument("--cycle_second_stair_count", type=int, default=None)
    group.add_argument("--decay_step_size", type=int, default=None)
    group.add_argument("--cycle_min_lr", type=float, default=None)
    group.add_argument("--cycle_max_lr", type=float, default=None)
    group.add_argument("--decay_lr_rate", type=float, default=None)
    # Warmup(Decay)LR
    group.add_argument("--warmup_min_lr", type=float, default=None)
    group.add_argument("--warmup_max_lr", type=float, default=None)
    group.add_argument("--warmup_num_steps", type=int, default=None)
    group.add_argument("--warmup_type", type=str, default=None)
    group.add_argument("--total_num_steps", type=int, default=None)
    return parser


def parse_arguments():
    """Parse only the tuning group from sys.argv (reference ``:114``):
    returns ``(known_args, unknown_args)``."""
    import argparse
    parser = add_tuning_arguments(argparse.ArgumentParser())
    return parser.parse_known_args()


def override_params(args, name: str, params: Dict[str, Any]) -> Dict[str, Any]:
    """Fold CLI tuning args into a ``scheduler.params`` dict for schedule
    ``name`` — the single-function form of the reference's four
    ``override_*_params`` helpers. Only non-None args override."""
    if name not in SCHEDULE_FNS:
        raise ValueError(f"Unknown LR schedule {name}; valid: {VALID_LR_SCHEDULES}")
    import inspect
    accepted = set(inspect.signature(SCHEDULE_FNS[name]).parameters)
    out = dict(params)
    for key in accepted:
        val = getattr(args, key, None)
        if val is not None:
            out[key] = val
    return out
