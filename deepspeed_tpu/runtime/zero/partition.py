"""ZeRO stages as sharding-spec programs.

This replaces the reference's hook-driven partitioning machinery
(``runtime/zero/stage_1_and_2.py:575`` round-robin partitioning,
``stage3.py`` parameter partitioning + ``partitioned_param_coordinator.py``
fetch/release) with declarative ``NamedSharding`` rules. XLA's SPMD
partitioner then inserts and schedules the all-gathers/reduce-scatters the
reference issues by hand — including the overlap the reference implements
with side streams (``overlap_comm``) and the prefetch machinery
(``prefetch_bucket_sz``), both of which fall out of XLA's latency-hiding
scheduler.

Mapping:

- **stage 0** (plain DP): params/grads/opt-state replicated; grad psum.
- **stage 1**: optimizer state + fp32 master params sharded over the dp axis;
  grads replicated (allreduce); params replicated.
- **stage 2**: + gradients sharded over dp (XLA turns the grad psum +
  slice-for-update into a reduce-scatter).
- **stage 3**: + compute params sharded over dp; XLA all-gathers each
  parameter just before use and frees it after (gather-on-use). The
  reference's persistence threshold (``stage3_param_persistence_threshold``)
  maps to "small params stay replicated".

Sharding choice per array: shard the *largest* dimension divisible by the
partition-axis size; fall back to replication when nothing divides (the
reference pads flat buffers instead — unnecessary here since each array is
partitioned independently and XLA handles ragged layouts per-dim).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.zero.config import ZeroConfig


def _partition_axes(mesh: Mesh, zero_config: ZeroConfig) -> Tuple[str, ...]:
    """Mesh axes ZeRO partitions over: the configured axis plus fsdp if present."""
    axes = []
    for ax in (zero_config.partition_axis, "fsdp"):
        if ax in mesh.shape and mesh.shape[ax] > 1 and ax not in axes:
            axes.append(ax)
    return tuple(axes)


def sanitize_tp_spec(mesh: Mesh, arr_shape: Tuple[int, ...],
                     tp_spec: Optional[P]) -> Optional[P]:
    """Drop TP axis entries whose mesh axes are absent or whose size doesn't
    divide the dim (e.g. an odd vocab over tp=2 falls back to replication on
    that dim). The single axis-drop policy shared by ZeRO parameter sharding
    and the quantized-inference sharding (`ops/quant.py quantized_shardings`)."""
    import math
    if tp_spec is None:
        return None
    out = []
    for i, entry in enumerate(tp_spec):
        if entry is None or i >= len(arr_shape):
            out.append(None if i >= len(arr_shape) else entry)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        if any(a not in mesh.shape for a in axes):
            out.append(None)  # axis absent from this mesh (e.g. no tp)
            continue
        size = math.prod(mesh.shape[a] for a in axes)
        out.append(entry if arr_shape[i] % size == 0 else None)
    return P(*out)


class ZeroShardingRules:
    """Produces NamedShardings for params / master params / grads / opt state.

    TP-sharded models compose transparently: a param that already carries a
    TP PartitionSpec keeps its TP dims; ZeRO sharding picks among the
    remaining dims. (Reference analogue: ZeRO groups are orthogonal to the
    model-parallel group, ``utils/groups.py``.)
    """

    def __init__(self, mesh: Mesh, zero_config: Optional[ZeroConfig] = None):
        self.mesh = mesh
        self.config = zero_config or ZeroConfig()
        self.stage = self.config.stage
        self.axes = _partition_axes(mesh, self.config)
        import math
        self.axis_size = math.prod(mesh.shape[a] for a in self.axes) if self.axes else 1

    # -------------------- per-array spec builders -------------------- #

    def _sanitize_tp(self, arr_shape: Tuple[int, ...], tp_spec: Optional[P]) -> Optional[P]:
        return sanitize_tp_spec(self.mesh, arr_shape, tp_spec)

    def _zero_spec(self, arr_shape: Tuple[int, ...], tp_spec: Optional[P], threshold: int) -> P:
        """Shard over the ZeRO axes, avoiding dims already taken by TP."""
        import math
        tp_spec = self._sanitize_tp(arr_shape, tp_spec)
        if not self.axes or self.axis_size <= 1:
            return tp_spec or P()
        numel = math.prod(arr_shape) if arr_shape else 1
        if numel < threshold or not arr_shape:
            return tp_spec or P()
        taken = set()
        base = list(tp_spec) if tp_spec is not None else [None] * len(arr_shape)
        while len(base) < len(arr_shape):
            base.append(None)
        for i, s in enumerate(base):
            if s is not None:
                taken.add(i)
        # shard the largest free, divisible dim
        free = [i for i in range(len(arr_shape)) if i not in taken]
        free.sort(key=lambda i: -arr_shape[i])
        ax = self.axes if len(self.axes) > 1 else self.axes[0]
        for i in free:
            if arr_shape[i] % self.axis_size == 0 and arr_shape[i] >= self.axis_size:
                base[i] = ax
                return P(*base)
        return tp_spec or P()

    def param_spec(self, arr, tp_spec: Optional[P] = None) -> P:
        """Compute-parameter sharding: stage 3 shards (gather-on-use), lower
        stages replicate (modulo TP)."""
        if self.stage < 3:
            return self._sanitize_tp(arr.shape, tp_spec) or P()
        return self._zero_spec(arr.shape, tp_spec, int(self.config.param_persistence_threshold))

    def master_spec(self, arr, tp_spec: Optional[P] = None) -> P:
        """fp32 master param + optimizer state sharding: stages >= 1 shard."""
        if self.stage < 1:
            return self._sanitize_tp(arr.shape, tp_spec) or P()
        return self._zero_spec(arr.shape, tp_spec, 0)

    def grad_spec(self, arr, tp_spec: Optional[P] = None) -> P:
        """Gradient (accumulation buffer) sharding: stages >= 2 shard, which
        makes XLA lower the DP reduction as reduce-scatter."""
        if self.stage < 2:
            return self._sanitize_tp(arr.shape, tp_spec) or P()
        return self._zero_spec(arr.shape, tp_spec, 0)

    # -------------------- pytree-level API -------------------- #

    def _tree_specs(self, tree, spec_fn, tp_specs=None) -> Any:
        if tp_specs is None:
            return jax.tree.map(lambda a: spec_fn(a, None), tree)
        return jax.tree.map(spec_fn, tree, tp_specs)

    def param_shardings(self, params, tp_specs=None):
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self._tree_specs(params, self.param_spec, tp_specs))

    def master_shardings(self, params, tp_specs=None):
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self._tree_specs(params, self.master_spec, tp_specs))

    def grad_shardings(self, params, tp_specs=None):
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self._tree_specs(params, self.grad_spec, tp_specs))

    def opt_state_shardings(self, opt_state, params, tp_specs=None):
        """Optimizer-state sharding: any subtree of the state congruent with
        the parameter tree (optax moments like Adam's mu/nu) gets the master
        shardings mapped param-wise BY TREE PATH — two same-shape params with
        different TP specs keep their own specs. Everything else (counts,
        scalars, non-congruent leaves) replicates."""
        master = jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                              self._tree_specs(params, self.master_spec, tp_specs))
        rep = NamedSharding(self.mesh, P())
        pdef = jax.tree.structure(params)
        if pdef.num_leaves <= 1 and jax.tree.structure(0) == pdef:
            # params is a single bare array: structure matching is vacuous,
            # fall back to shape matching
            p = jax.tree.leaves(params)[0]
            m = jax.tree.leaves(master)[0]
            return jax.tree.map(
                lambda leaf: m if getattr(leaf, "shape", None) == p.shape else rep,
                opt_state)

        def is_param_tree(x):
            try:
                return jax.tree.structure(x) == pdef
            except Exception:  # pragma: no cover - defensive
                return False

        def map_node(node):
            if is_param_tree(node):
                return master
            return rep  # plain leaf: count scalars etc.

        return jax.tree.map(map_node, opt_state, is_leaf=is_param_tree)

    def describe(self) -> str:
        return (f"ZeRO stage {self.stage} over axes {self.axes} (size {self.axis_size}); "
                f"params {'sharded' if self.stage >= 3 else 'replicated'}, "
                f"grads {'sharded' if self.stage >= 2 else 'replicated'}, "
                f"optimizer+master {'sharded' if self.stage >= 1 else 'replicated'}")
