"""TiledLinear — split a large linear into a grid of small tiles.

Reference parity: ``deepspeed/runtime/zero/tiling.py:36`` (``TiledLinear``
splits ``Linear(in, out)`` into ``in_splits × out_splits`` sub-linears so
ZeRO-3 fetches one small tile at a time instead of materialising the full
weight — bounding the gather working set for giant layers).

TPU redesign: tiles live as ONE stacked param
``w [out_splits, in_splits, in/in_splits, out/out_splits]`` so ZeRO/TP
sharding rules and optimizers see a normal leaf. The forward offers two
lowerings:

- ``scan_tiles=False`` (default): a single einsum — XLA sees the whole
  contraction and fuses/schedules it (fastest when the layer fits);
- ``scan_tiles=True``: ``lax.scan`` over the out-split dim, so with ZeRO-3
  sharding on the leading dim XLA gathers ONE row of tiles per scan step
  and frees it after — the reference's bounded-working-set behavior,
  expressed as compiler-visible control flow instead of hooks.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.init_on_device import honors_on_device


class TiledLinear:
    """y = x @ W + b with W stored as an [out_splits, in_splits] tile grid.

    ``in_features`` must divide by ``in_splits`` and ``out_features`` by
    ``out_splits`` (the reference round-robins remainders; here the zoo's
    dims are tile-friendly and uneven splits raise loudly).
    """

    def __init__(self, in_features: int, out_features: int,
                 in_splits: int = 1, out_splits: int = 1,
                 bias: bool = True, scan_tiles: bool = False):
        if in_features % in_splits or out_features % out_splits:
            raise ValueError(
                f"TiledLinear: {in_features}x{out_features} not divisible by "
                f"splits {in_splits}x{out_splits}")
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.tile_in = in_features // in_splits
        self.tile_out = out_features // out_splits
        self.use_bias = bias
        self.scan_tiles = scan_tiles

    # -------------------- params -------------------- #

    @honors_on_device
    def init_params(self, rng, dtype=jnp.float32) -> Dict[str, Any]:
        scale = self.in_features**-0.5
        w = jax.random.normal(
            rng, (self.out_splits, self.in_splits, self.tile_in, self.tile_out),
            dtype) * scale
        p = {"w": w}
        if self.use_bias:
            p["b"] = jnp.zeros((self.out_features,), dtype)
        return p

    def from_dense(self, w, b=None) -> Dict[str, Any]:
        """Tile an existing dense ``w [in, out]`` (reference
        ``copy_params_from``)."""
        w = jnp.asarray(w)
        if w.shape != (self.in_features, self.out_features):
            raise ValueError(f"dense weight {w.shape} != "
                             f"({self.in_features}, {self.out_features})")
        t = w.reshape(self.in_splits, self.tile_in,
                      self.out_splits, self.tile_out)
        p = {"w": jnp.transpose(t, (2, 0, 1, 3))}
        if self.use_bias:
            if b is None:
                raise ValueError("bias=True but no dense bias given")
            p["b"] = jnp.asarray(b)
        return p

    def to_dense(self, params) -> jnp.ndarray:
        return jnp.transpose(params["w"], (1, 2, 0, 3)).reshape(
            self.in_features, self.out_features)

    # -------------------- forward -------------------- #

    def __call__(self, params, x):
        lead = x.shape[:-1]
        xt = x.reshape(lead + (self.in_splits, self.tile_in))
        w = params["w"]
        if self.scan_tiles:
            # one out-row of tiles per step: ZeRO-3 gathers w[o] only while
            # this step is live
            def step(_, wo):
                return None, jnp.einsum("...it,itu->...u", xt, wo)
            _, ys = jax.lax.scan(step, None, w)           # [O, ..., tile_out]
            y = jnp.moveaxis(ys, 0, -2).reshape(lead + (self.out_features,))
        else:
            y = jnp.einsum("...it,oitu->...ou", xt, w).reshape(
                lead + (self.out_features,))
        if self.use_bias:
            y = y + params["b"]
        return y
