"""ZeRO public API: ``Init`` construct-time partitioning and
``GatheredParameters``.

Reference parity: ``deepspeed/runtime/zero/partition_parameters.py`` —
``zero.Init`` (:516, modules constructed inside the context allocate
already-partitioned parameters, so a model larger than one device's memory
can be built) and ``GatheredParameters`` (:1382, momentarily gather a
partitioned parameter for user code, re-partition on exit).

TPU redesign: the reference intercepts ``nn.Module.__init__`` and slices
each tensor as it is created. Here parameter construction is a *function*
(``init_params(rng)``), so zero.Init compiles that function with sharded
output layouts — ``jax.eval_shape`` first (no memory), then
``jax.jit(init_fn, out_shardings=zero3_shardings)`` so XLA materialises each
shard directly on its own device. The full parameter tree never exists in
any single memory; per-host cost is 1/N of the model. The model zoo's
``init_params`` routes through the active ``Init`` context automatically,
matching the reference's construct-inside-the-context UX.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.zero.config import ZeroConfig
from deepspeed_tpu.runtime.zero.partition import ZeroShardingRules

_local = threading.local()


def active_init() -> Optional["Init"]:
    """The innermost enabled ``zero.Init`` context, or None."""
    stack = getattr(_local, "init_stack", None)
    return stack[-1] if stack else None


def materialize_sharded(init_fn: Callable, rng, shardings) -> Any:
    """Run ``init_fn(rng)`` with each output leaf materialised directly into
    its shard layout (no full-tree staging anywhere)."""
    return jax.jit(init_fn, out_shardings=shardings)(rng)


class Init:
    """Construct-time ZeRO-3 parameter partitioning context.

    Usage (mirrors reference ``zero.Init``)::

        with deepspeed_tpu.zero.Init(mesh=mesh):
            params = model.init_params(rng)     # arrives ZeRO-3 sharded

    or explicitly: ``params = Init(mesh=mesh).materialize(model.init_params,
    rng, tp_specs=model.tp_specs())``.
    """

    def __init__(self, mesh=None, config: Optional[Any] = None, enabled: bool = True,
                 dtype=None, tp_specs=None):
        import deepspeed_tpu.comm as dist
        self.enabled = enabled
        self.mesh = mesh if mesh is not None else (dist.get_mesh() if dist.has_mesh() else None)
        if self.mesh is None:
            raise ValueError("zero.Init needs a device mesh (pass mesh= or dist.init_mesh first)")
        if config is None:
            zcfg = ZeroConfig(stage=3)
        elif isinstance(config, ZeroConfig):
            zcfg = config
        else:
            zcfg = ZeroConfig(**(config.get("zero_optimization", config) if isinstance(config, dict) else {}))
        if zcfg.stage < 3:
            zcfg = zcfg.model_copy(update={"stage": 3})
        self.rules = ZeroShardingRules(self.mesh, zcfg)
        self.dtype = dtype
        self.tp_specs = tp_specs

    # -- context management (construct-inside-the-context UX) --

    def __enter__(self):
        if self.enabled:
            stack = getattr(_local, "init_stack", None)
            if stack is None:
                stack = _local.init_stack = []
            stack.append(self)
        return self

    def __exit__(self, *exc):
        if self.enabled:
            _local.init_stack.pop()
        return False

    # -- materialization --

    def shardings(self, shapes, tp_specs=None):
        """NamedSharding tree (ZeRO-3 param specs) for a shape/array tree."""
        tp = tp_specs if tp_specs is not None else self.tp_specs
        if tp is not None:
            specs = jax.tree.map(lambda a, s: self.rules.param_spec(a, s), shapes, tp)
        else:
            specs = jax.tree.map(lambda a: self.rules.param_spec(a, None), shapes)
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs)

    def materialize(self, init_fn: Callable, rng, tp_specs=None):
        """``init_fn(rng)`` -> ZeRO-3-sharded parameter tree, one shard per
        device, never staging the full tree."""
        fn = init_fn
        if self.dtype is not None:
            fn = lambda r: jax.tree.map(lambda a: a.astype(self.dtype), init_fn(r))
        shapes = jax.eval_shape(fn, rng)
        return materialize_sharded(fn, rng, self.shardings(shapes, tp_specs))


class GatheredParameters:
    """Momentarily gather partitioned parameters for user code (reference
    ``partition_parameters.py:1382``).

    JAX arrays are immutable, so the context yields a *mutable host copy*
    (numpy leaves). On exit the (possibly modified) values are re-partitioned
    to the original shardings and exposed as ``.params``::

        gp = GatheredParameters(params)
        with gp as full:
            full["embed"]["tokens"][0] = 0.0     # numpy, mutable
        params = gp.params                        # re-sharded

    With ``modifier_rank=None`` semantics of the reference (read-only use),
    simply ignore ``.params``.
    """

    def __init__(self, params, shardings=None):
        self.params = params
        self._shardings = shardings or jax.tree.map(lambda a: a.sharding, params)
        self._gathered = None

    def __enter__(self):
        import numpy as np
        self._gathered = jax.tree.map(lambda a: np.array(a), jax.device_get(self.params))
        return self._gathered

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.params = jax.tree.map(
                lambda h, s: jax.device_put(jnp.asarray(h), s),
                self._gathered, self._shardings)
        self._gathered = None
        return False


__all__ = ["Init", "GatheredParameters", "ZeroConfig", "ZeroShardingRules",
           "active_init", "materialize_sharded"]
