"""ZeRO-Offload / ZeRO-Infinity host optimizer.

Reference parity: the CPU-offload path of
``deepspeed/runtime/zero/stage_1_and_2.py:1030-1155`` (optimizer states on
host, stepped by the native cpu_adam) and the NVMe swap path of
``stage3.py:671,1735`` (``PartitionedOptimizerSwapper``).

TPU-native architecture: the compiled device program only accumulates sharded
grads; at the accumulation boundary the engine hands the grad pytree here.
fp32 master weights + Adam moments live in host numpy buffers (``cpu``) or on
NVMe via the aio engine (``nvme``); the update runs in the native SIMD
cpu_adam with a fused bf16 convert of the updated params into staging buffers
that go straight back to HBM (the reference's ``ds_adam_step_plus_copy``
overlap, csrc/adam/cpu_adam.cpp:290).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from deepspeed_tpu.utils.logging import logger


from deepspeed_tpu.utils.pytree import leaf_key as _leaf_key


class HostOffloadOptimizer:
    """Adam/Adagrad over host-resident (or NVMe-resident) optimizer state."""

    def __init__(self, model_parameters, *, optimizer_name: str = "adamw",
                 optimizer_params: Optional[dict] = None, device: str = "cpu",
                 nvme_path: Optional[str] = None, aio_config: Optional[dict] = None,
                 grad_clip: float = 0.0):
        optimizer_params = dict(optimizer_params or {})
        self.grad_clip = grad_clip
        self.device = device
        name = (optimizer_name or "adamw").lower()

        if name in ("adam", "adamw"):
            from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam
            adamw = name == "adamw" or optimizer_params.get("adam_w_mode", False)
            self.opt = DeepSpeedCPUAdam(
                lr=optimizer_params.get("lr", 1e-3),
                betas=tuple(optimizer_params.get("betas", (0.9, 0.999))),
                eps=optimizer_params.get("eps", 1e-8),
                weight_decay=optimizer_params.get("weight_decay", 0.0),
                adamw_mode=adamw)
        elif name == "adagrad":
            from deepspeed_tpu.ops.adagrad import DeepSpeedCPUAdagrad
            self.opt = DeepSpeedCPUAdagrad(
                lr=optimizer_params.get("lr", 1e-2),
                eps=optimizer_params.get("eps", 1e-10),
                weight_decay=optimizer_params.get("weight_decay", 0.0))
        else:
            raise ValueError(f"offload_optimizer supports adam/adamw/adagrad on host, got '{name}'")

        # flatten params to keyed fp32 host masters
        leaves_with_path = jax.tree_util.tree_flatten_with_path(model_parameters)[0]
        self._shapes: Dict[str, Tuple[int, ...]] = {}
        self._order: List[str] = []
        self._masters: Dict[str, np.ndarray] = {}
        for path, leaf in leaves_with_path:
            key = _leaf_key(path)
            self._order.append(key)
            self._shapes[key] = tuple(leaf.shape)
            master = np.asarray(jax.device_get(leaf), dtype=np.float32).ravel()
            self._masters[key] = np.ascontiguousarray(master)

        # optimizer state tensors (beyond the master) and the opt attribute
        # dicts they bind to during NVMe stepping
        if name in ("adam", "adamw"):
            self._state_attrs = {"exp_avg": "_m", "exp_avg_sq": "_v"}
        else:
            self._state_attrs = {"exp_avg_sq": "_h"}

        self.swapper = None
        if device == "nvme":
            from deepspeed_tpu.runtime.swap_tensor import PartitionedOptimizerSwapper
            if not nvme_path:
                raise ValueError("offload_optimizer device=nvme requires nvme_path")
            self.swapper = PartitionedOptimizerSwapper(
                nvme_path, aio_config, state_keys=("master",) + tuple(self._state_attrs))
            for key in self._order:
                self.swapper.register_partition(key, self._masters[key])
            self._masters = {}  # masters now live on NVMe
            logger.info(f"offloaded optimizer state for {len(self._order)} tensors to NVMe at {nvme_path}")

    # ------------------------------------------------------------------ #
    def _clip_coef(self, grads: Dict[str, np.ndarray]) -> float:
        if self.grad_clip <= 0:
            return 1.0
        sq = 0.0
        for g in grads.values():
            gf = g.astype(np.float32) if g.dtype != np.float32 else g
            sq += float(np.dot(gf, gf))
        norm = sq**0.5
        return min(1.0, self.grad_clip / (norm + 1e-6))

    def step(self, grads: Dict[str, np.ndarray], lr: float,
             out_dtype=np.float32) -> Tuple[Dict[str, np.ndarray], bool]:
        """Apply one update. ``grads`` maps leaf key → flat fp32 (or
        bf16-as-uint16) host array. Returns (staged updated params keyed by
        leaf, overflow_flag). Staged arrays are bf16-as-uint16 when
        ``out_dtype`` is bfloat16, else fp32 masters."""
        overflow = False
        for g in grads.values():
            gf = g.view(ml_dtypes.bfloat16) if g.dtype == np.uint16 else g
            # float64 accumulator: no copy of gf, and no fp32-sum overflow
            # false-positives on large tensors
            if not np.isfinite(np.sum(gf, dtype=np.float64)):
                overflow = True
                break
        if overflow:
            return {}, True

        coef = self._clip_coef({k: (g.view(ml_dtypes.bfloat16).astype(np.float32)
                                    if g.dtype == np.uint16 else g)
                                for k, g in grads.items()}) if self.grad_clip > 0 else 1.0
        if coef != 1.0:
            grads = {k: (g.view(ml_dtypes.bfloat16).astype(np.float32) * coef).astype(np.float32)
                     if g.dtype == np.uint16 else g * coef
                     for k, g in grads.items()}

        bf16_out = np.dtype(out_dtype) == np.dtype(ml_dtypes.bfloat16)
        staged: Dict[str, np.ndarray] = {}
        self.opt.begin_step(lr=lr)

        if self.swapper is not None:
            def step_fn(key, numel, states):
                # bind the swapped-in buffers as this partition's optimizer
                # state so the native kernel updates them in place (they are
                # written back to NVMe by step_all)
                for state_name, attr in self._state_attrs.items():
                    getattr(self.opt, attr)[key] = states[state_name][:numel]
                out = np.empty(numel, np.uint16) if bf16_out else None
                self.opt.step(key, states["master"][:numel], grads[key], param_out_bf16=out)
                staged[key] = out if bf16_out else states["master"][:numel].copy()
            self.swapper.step_all(step_fn)
            # drop the bindings: the buffers return to the swapper pool after
            # write-back, so keeping views would alias other partitions' data
            for attr in set(self._state_attrs.values()):
                getattr(self.opt, attr).clear()
        else:
            for key in self._order:
                master = self._masters[key]
                out = np.empty(master.size, np.uint16) if bf16_out else None
                self.opt.step(key, master, grads[key], param_out_bf16=out)
                staged[key] = out if bf16_out else master
        return staged, False

    # ------------------------------------------------------------------ #
    def masters(self) -> Dict[str, np.ndarray]:
        if self.swapper is not None:
            return {k: self.swapper.read_master(k) for k in self._order}
        return dict(self._masters)

    def load_masters(self, masters: Dict[str, np.ndarray]) -> None:
        for k, v in masters.items():
            v = np.ascontiguousarray(np.asarray(v, np.float32).ravel())
            if self.swapper is not None:
                self.swapper.swapper.swap_out(f"{k}.master", v)
            else:
                self._masters[k] = v

    def state_dict(self) -> dict:
        if self.swapper is not None:
            # NVMe: the authoritative state lives in the swap files, not in
            # the (cleared) opt attribute dicts
            sd = {"step": self.opt.step_count, "lr": self.opt.lr, "masters": {}}
            for state_name in self._state_attrs:
                sd[state_name] = {}
            for k in self._order:
                sd["masters"][k] = self.swapper.read_state(k, "master")
                for state_name in self._state_attrs:
                    sd[state_name][k] = self.swapper.read_state(k, state_name)
            return sd
        sd = self.opt.state_dict()
        sd["masters"] = self.masters()
        return sd

    def load_state_dict(self, sd: dict) -> None:
        masters = sd.pop("masters", None)
        if self.swapper is not None:
            self.opt.step_count = sd.get("step", 0)
            self.opt.lr = sd.get("lr", self.opt.lr)
            for k in self._order:
                if masters and k in masters:
                    self.swapper.write_state(k, "master", np.asarray(masters[k], np.float32).ravel())
                for state_name in self._state_attrs:
                    if state_name in sd and k in sd[state_name]:
                        self.swapper.write_state(k, state_name,
                                                 np.asarray(sd[state_name][k], np.float32).ravel())
            return
        self.opt.load_state_dict(sd)
        if masters:
            self.load_masters(masters)

    @property
    def order(self) -> List[str]:
        return list(self._order)

    def shape(self, key: str) -> Tuple[int, ...]:
        return self._shapes[key]
