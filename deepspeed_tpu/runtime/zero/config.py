"""ZeRO config (reference parity: deepspeed/runtime/zero/config.py:76 and
deepspeed/runtime/zero/offload_config.py).

On TPU, ZeRO stages are expressed as sharding-rule programs over the ``dp``
mesh axis rather than hook-driven partitioning (see SURVEY.md §7):

- stage 0: replicated params/grads/optimizer states (plain DP)
- stage 1: optimizer states sharded over dp
- stage 2: + gradients reduce-scattered (sharded grad accumulation buffers)
- stage 3: + parameters sharded, all-gathered on use by XLA (FSDP-style)

Offload devices map to TPU-VM host memory (``cpu``) and NVMe via the aio
engine. The knob names keep the reference JSON schema so configs port over.
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

from pydantic import Field, model_validator

from deepspeed_tpu.config.config_utils import ConfigModel, pp_int

ZERO_OPTIMIZATION = "zero_optimization"


def read_zero_config_deprecated(param_dict: dict) -> dict:
    """Support the ancient ``"zero_optimization": true`` boolean form."""
    zero_config_dict = {}
    zero_config_dict["stage"] = 1 if param_dict[ZERO_OPTIMIZATION] else 0
    if zero_config_dict["stage"] > 0:
        zero_config_dict["allgather_bucket_size"] = param_dict.get("allgather_size", 5e8)
    return zero_config_dict


def get_zero_config(param_dict: dict) -> "ZeroConfig":
    zero_config_dict = param_dict.get(ZERO_OPTIMIZATION, {})
    if isinstance(zero_config_dict, bool):
        zero_config_dict = read_zero_config_deprecated(param_dict)
    return ZeroConfig(**zero_config_dict)


class OffloadDeviceEnum(str, Enum):
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(ConfigModel):
    """Where/how to offload partitioned parameters (ZeRO-3)."""
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(5, ge=0)
    buffer_size: int = Field(pp_int(1e8), ge=0)
    max_in_cpu: int = Field(pp_int(1e9), ge=0)
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(ConfigModel):
    """Where/how to offload optimizer states + computation."""
    device: OffloadDeviceEnum = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = Field(4, ge=0)
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False

    @property
    def pipeline(self) -> bool:
        return self.pipeline_read or self.pipeline_write


class ZeroConfig(ConfigModel):
    """`"zero_optimization"` section of the config JSON."""

    stage: int = Field(0, ge=0, le=3)

    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = Field(pp_int(5e8), ge=0)
    allgather_partitions: bool = True
    allgather_bucket_size: int = Field(pp_int(5e8), ge=0)
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True

    elastic_checkpoint: bool = False

    # Offload
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None

    # Stage-3 specific
    sub_group_size: int = Field(pp_int(1e9), ge=0)
    cpu_offload_param: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_param",
                                 "new_param_fn": (lambda val: DeepSpeedZeroOffloadParamConfig(device="cpu")
                                                  if val else None)})
    cpu_offload_use_pin_memory: Optional[bool] = Field(None, json_schema_extra={"deprecated": True})
    cpu_offload: Optional[bool] = Field(
        None, json_schema_extra={"deprecated": True, "new_param": "offload_optimizer",
                                 "new_param_fn": (lambda val: DeepSpeedZeroOffloadOptimizerConfig(device="cpu")
                                                  if val else None)})

    prefetch_bucket_size: int = Field(pp_int(5e7), ge=0, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(pp_int(1e5), ge=0, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(pp_int(2**62), ge=0, alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(pp_int(1e9), ge=0, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(pp_int(1e9), ge=0, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(False, alias="stage3_gather_16bit_weights_on_model_save")
    stage3_gather_fp16_weights_on_model_save: bool = Field(
        False, json_schema_extra={"deprecated": True, "new_param": "gather_16bit_weights_on_model_save"})

    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False

    # TPU-native extensions
    # Mesh axis (or axes) the ZeRO partitioning rides on. Defaults to the data
    # axis; on multi-slice topologies set to the ICI-local axis so all-gathers
    # stay off DCN.
    partition_axis: str = "dp"
    # Parameters smaller than param_persistence_threshold stay replicated
    # (maps the reference's persistent-param machinery to a sharding choice).

    @model_validator(mode="after")
    def overlap_comm_valid(self):
        if self.overlap_comm is None:
            # Reference default: True for stage 3, False otherwise. Under XLA
            # the compiler overlaps collectives regardless; kept for parity.
            self.overlap_comm = self.stage == 3
        return self

    @property
    def offload_optimizer_device(self) -> str:
        return self.offload_optimizer.device if self.offload_optimizer else "none"

    @property
    def offload_param_device(self) -> str:
        return self.offload_param.device if self.offload_param else "none"
