"""Pluggable checkpoint engines.

Reference parity: ``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py``
(the create/save/load/commit ABC) and ``torch_checkpoint_engine.py`` /
``nebula_checkpoint_engine.py``.

TPU-native implementations:

- ``OrbaxCheckpointEngine`` — the default. Orbax natively understands
  ``jax.Array`` shardings, writes each process's addressable shards
  (multi-host safe), and restores with the target sharding — this subsumes
  both the reference's per-rank ZeRO checkpoint files
  (``_save_zero_checkpoint``) and its TP/PP-aware merge logic at load.
- ``AsyncCheckpointEngine`` — Nebula-equivalent tiered/async save: snapshot
  to host memory, write in a background thread, ``commit`` waits.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Any, Optional

from deepspeed_tpu.utils.logging import log_dist, logger


class CheckpointEngine:

    def __init__(self, config_params=None):
        pass

    def create(self, tag: str):
        """Notify start of a checkpoint under ``tag``."""

    def save(self, state_dict: Any, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None, template: Any = None):
        raise NotImplementedError

    def commit(self, tag: str) -> bool:
        """Flush/publish everything saved under ``tag``."""
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    """Synchronous orbax-backed save/load of jax pytrees."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def create(self, tag: str):
        log_dist(f"[Orbax] Saving checkpoint under tag {tag}", ranks=[0])

    def save(self, state_dict: Any, path: str):
        path = os.path.abspath(path)
        if os.path.exists(path):
            shutil.rmtree(path)
        self._ckptr.save(path, state_dict)
        self._ckptr.wait_until_finished()

    def load(self, path: str, map_location=None, template: Any = None):
        path = os.path.abspath(path)
        if template is not None:
            return self._ckptr.restore(path, target=template)
        return self._ckptr.restore(path)

    def commit(self, tag: str) -> bool:
        self._ckptr.wait_until_finished()
        return True


class AsyncCheckpointEngine(OrbaxCheckpointEngine):
    """Nebula-style async tiered save (reference nebula_checkpoint_engine.py):
    the device→host snapshot happens synchronously, the disk write in a
    background thread; ``commit`` joins all pending writes."""

    def __init__(self, config_params=None):
        super().__init__(config_params)
        self._pending: list = []

    def save(self, state_dict: Any, path: str):
        import jax

        # snapshot to host memory synchronously so training can proceed
        host_state = jax.tree.map(lambda x: jax.device_get(x) if hasattr(x, "addressable_shards") else x,
                                  state_dict)
        t = threading.Thread(target=super().save, args=(host_state, path), daemon=True)
        t.start()
        self._pending.append(t)

    def commit(self, tag: str) -> bool:
        for t in self._pending:
            t.join()
        self._pending.clear()
        return super().commit(tag)
