"""Engine-level checkpoint save/load.

Reference parity: ``deepspeed/runtime/engine.py:2512-3259`` —
``save_checkpoint``/``load_checkpoint`` with tag directories, the ``latest``
tag file, tag validation, module+optimizer+scheduler+rng+config state, and
ZeRO partitioned state. Because orbax writes each process's shards, the
reference's separate per-dp-rank ZeRO files and mp-rank files collapse into
one sharded tree per tag.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _tag_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


def _opt_state_labels(opt_state):
    """One label per flattened opt_state leaf (flatten order):
    {"moment": "mu"|"nu"|None, "param": dotted-path-or-"", "path": keystr}.
    Adam-family optax states expose first/second moments as ``mu``/``nu``
    namedtuple fields over the param tree; anything else gets moment=None so
    downstream tools treat it as opaque extra state instead of guessing."""
    from jax.tree_util import GetAttrKey, tree_flatten_with_path

    from deepspeed_tpu.utils.pytree import leaf_key

    flat, _ = tree_flatten_with_path(opt_state)
    labels = []
    for path, _leaf in flat:
        moment = None
        param = ""
        for i, entry in enumerate(path):
            if isinstance(entry, GetAttrKey) and entry.name in ("mu", "nu"):
                moment = entry.name
                param = leaf_key(path[i + 1:])
                break
        labels.append({"moment": moment, "param": param,
                       "path": jax.tree_util.keystr(path)})
    return labels


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None, client_state=None,
                           save_latest: bool = True) -> bool:
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    tag = str(tag)

    # tag validation (reference engine.py:2800): all processes must agree on
    # the tag; process 0's tag is broadcast and compared against the local one
    if engine._config.checkpoint_tag_validation_enabled and jax.process_count() > 1:
        import hashlib

        from jax.experimental import multihost_utils
        local = np.frombuffer(hashlib.sha256(tag.encode()).digest()[:8], dtype=np.int64).copy()
        agreed = multihost_utils.broadcast_one_to_all(local)
        if not np.array_equal(local, agreed):
            msg = f"Checkpoint tag '{tag}' differs across processes; checkpoints would be inconsistent"
            if engine._config.checkpoint_tag_validation_fail:
                raise ValueError(msg)
            logger.warning(msg)

    os.makedirs(os.path.abspath(save_dir), exist_ok=True)
    path = _tag_dir(save_dir, tag)

    ckpt_engine = engine.checkpoint_engine if hasattr(engine, "checkpoint_engine") else None
    if ckpt_engine is None:
        from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import OrbaxCheckpointEngine
        ckpt_engine = OrbaxCheckpointEngine()
        engine.checkpoint_engine = ckpt_engine

    ckpt_engine.create(tag)

    state = engine.state
    tree = {
        "params": state.params,
        "acc_grads": state.acc_grads,
        "scaler": {
            "loss_scale": state.scaler.loss_scale,
            "good_steps": state.scaler.good_steps,
            "hysteresis": state.scaler.hysteresis,
        },
        "counters": {
            "micro_steps": state.micro_steps,
            "global_steps": state.global_steps,
            "skipped_steps": state.skipped_steps,
        },
    }
    if state.master is not None:
        tree["master"] = state.master
    opt_labels = None
    if state.opt_state is not None:
        # flatten the optax state to a dict orbax can store without the types
        flat, treedef = jax.tree.flatten(state.opt_state)
        tree["opt_state_flat"] = {f"leaf_{i}": leaf for i, leaf in enumerate(flat)}
        opt_labels = _opt_state_labels(state.opt_state)

    ckpt_engine.save(tree, os.path.join(path, "state"))

    # ZeRO-Offload: host optimizer state (fp32 masters + moments) is saved
    # per-process as an npz next to the sharded device state (reference saves
    # per-dp-rank zero files, engine.py:3136)
    offload = getattr(engine, "_offload", None)
    if offload is not None:
        sd = offload.state_dict()
        arrays = {}
        for group in ("masters", "exp_avg", "exp_avg_sq"):
            for k, v in sd.get(group, {}).items():
                arrays[f"{group}|{k}"] = v
        np.savez(os.path.join(path, f"offload_state_p{jax.process_index()}.npz"),
                 step=sd.get("step", 0), lr=sd.get("lr", 0.0), **arrays)

    meta = {
        "tag": tag,
        "global_steps": int(state.global_steps),
        "micro_steps": int(state.micro_steps),
        "skipped_steps": int(state.skipped_steps),
        "ds_config": engine._config._param_dict,
        "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
        "client_state": client_state or {},
        "framework_version": 1,
    }
    if getattr(engine, "quantizer", None) is not None:
        # MoQ host schedule: a resumed run must continue mid-schedule
        meta["moq_state"] = engine.quantizer.state_dict()
    if opt_labels is not None:
        # structured identity of every opt_state_flat leaf, so tools
        # (ds_to_universal) never have to guess moments by shape matching
        meta["opt_state_labels"] = opt_labels
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        if save_latest:
            with open(os.path.join(os.path.abspath(save_dir), "latest"), "w") as f:
                f.write(tag)
    ckpt_engine.commit(tag)
    log_dist(f"Saved checkpoint {tag} to {path}", ranks=[0])
    return True


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None, load_optimizer_states: bool = True,
                           load_module_only: bool = False):
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        latest_path = os.path.join(load_dir, "latest")
        if not os.path.exists(latest_path):
            logger.warning(f"No 'latest' file at {load_dir}; cannot auto-resolve tag")
            return None, {}
        with open(latest_path) as f:
            tag = f.read().strip()
    path = _tag_dir(load_dir, tag)
    if not os.path.isdir(path):
        logger.warning(f"Checkpoint {path} does not exist")
        return None, {}

    from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import OrbaxCheckpointEngine
    ckpt_engine = getattr(engine, "checkpoint_engine", None) or OrbaxCheckpointEngine()

    state = engine.state
    template = {
        "params": state.params,
        "acc_grads": state.acc_grads,
        "scaler": {
            "loss_scale": state.scaler.loss_scale,
            "good_steps": state.scaler.good_steps,
            "hysteresis": state.scaler.hysteresis,
        },
        "counters": {
            "micro_steps": state.micro_steps,
            "global_steps": state.global_steps,
            "skipped_steps": state.skipped_steps,
        },
    }
    if state.master is not None:
        template["master"] = state.master
    # the saved tree always contains opt_state_flat; restore with the full
    # template and drop what wasn't requested afterwards (orbax rejects
    # structure mismatches between saved tree and template)
    flat, treedef = jax.tree.flatten(state.opt_state)
    template["opt_state_flat"] = {f"leaf_{i}": leaf for i, leaf in enumerate(flat)}

    restored = ckpt_engine.load(os.path.join(path, "state"), template=template)
    # re-commit every restored leaf to its template sharding (orbax may
    # return host/default-device arrays for replicated scalars)
    restored = jax.tree.map(
        lambda r, t: jax.device_put(r, t.sharding) if hasattr(t, "sharding") else r, restored, template)

    new_scaler = state.scaler._replace(
        loss_scale=restored["scaler"]["loss_scale"],
        good_steps=restored["scaler"]["good_steps"],
        hysteresis=restored["scaler"]["hysteresis"])
    kwargs = dict(
        params=restored["params"],
        master=restored.get("master", state.master),
        acc_grads=restored["acc_grads"],
        scaler=new_scaler,
        micro_steps=restored["counters"]["micro_steps"],
        global_steps=restored["counters"]["global_steps"],
        skipped_steps=restored["counters"]["skipped_steps"],
    )
    if load_module_only:
        kwargs = dict(params=restored["params"])
    if load_optimizer_states and not load_module_only and "opt_state_flat" in restored:
        leaves = [restored["opt_state_flat"][f"leaf_{i}"] for i in range(len(flat))]
        kwargs["opt_state"] = jax.tree.unflatten(treedef, leaves)
    engine.state = state._replace(**kwargs)

    offload = getattr(engine, "_offload", None)
    offload_path = os.path.join(path, f"offload_state_p{jax.process_index()}.npz")
    if offload is not None and load_optimizer_states and not load_module_only and os.path.exists(offload_path):
        with np.load(offload_path) as z:
            sd = {"step": int(z["step"]), "lr": float(z["lr"]),
                  "masters": {}, "exp_avg": {}, "exp_avg_sq": {}}
            for name in z.files:
                if "|" in name:
                    group, key = name.split("|", 1)
                    sd[group][key] = z[name]
        offload.load_state_dict(sd)

    meta = {}
    meta_path = os.path.join(path, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        if engine.lr_scheduler is not None and meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        if getattr(engine, "quantizer", None) is not None and meta.get("moq_state"):
            engine.quantizer.load_state_dict(meta["moq_state"])
    log_dist(f"Loaded checkpoint {tag} from {path} (step {engine.global_steps})", ranks=[0])
    return path, meta.get("client_state", {})
