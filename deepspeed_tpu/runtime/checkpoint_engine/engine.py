"""Engine-level checkpoint save/load.

Reference parity: ``deepspeed/runtime/engine.py:2512-3259`` —
``save_checkpoint``/``load_checkpoint`` with tag directories, the ``latest``
tag file, tag validation, module+optimizer+scheduler+rng+config state, and
ZeRO partitioned state.

Two storage engines:

- ``safe`` (default, single-process) — the crash-safe two-phase format of
  :mod:`.safe_engine`: one ``state.npz`` of flat dotted-key host arrays plus
  ``meta.json`` and optional offload npz files, committed atomically under a
  per-file blake2b ``manifest.json``. Loads are **all-or-nothing**: every
  byte is read, verified, and staged in host memory before ``engine.state``
  is touched, and an auto-resolved tag that fails verification walks back to
  the newest intact one.
- ``orbax`` — the multi-host path (each process writes its addressable
  shards). Selected via ``checkpoint.engine: "orbax"`` or automatically when
  ``jax.process_count() > 1``. No manifest; loads are unverified but still
  staged-before-apply.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine import safe_engine
from deepspeed_tpu.runtime.checkpoint_engine.safe_engine import (
    MANIFEST, META_FILE, STATE_FILE, CheckpointCorruptError,
    CheckpointPayload, CheckpointWriteError)
from deepspeed_tpu.utils.logging import log_dist, logger

RNG_KEY = "__rng_key_data__"


def _tag_dir(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


def _opt_state_labels(opt_state):
    """One label per flattened opt_state leaf (flatten order):
    {"moment": "mu"|"nu"|None, "param": dotted-path-or-"", "path": keystr}.
    Adam-family optax states expose first/second moments as ``mu``/``nu``
    namedtuple fields over the param tree; anything else gets moment=None so
    downstream tools treat it as opaque extra state instead of guessing."""
    from jax.tree_util import GetAttrKey, tree_flatten_with_path

    from deepspeed_tpu.utils.pytree import leaf_key

    flat, _ = tree_flatten_with_path(opt_state)
    labels = []
    for path, _leaf in flat:
        moment = None
        param = ""
        for i, entry in enumerate(path):
            if isinstance(entry, GetAttrKey) and entry.name in ("mu", "nu"):
                moment = entry.name
                param = leaf_key(path[i + 1:])
                break
        labels.append({"moment": moment, "param": param,
                       "path": jax.tree_util.keystr(path)})
    return labels


# --------------------------------------------------------------------- #
# the state tree <-> flat keys (shared by save and load so they never
# disagree about structure)

def _state_tree(engine) -> Dict[str, Any]:
    state = engine.state
    tree: Dict[str, Any] = {
        "params": state.params,
        "acc_grads": state.acc_grads,
        "scaler": {
            "loss_scale": state.scaler.loss_scale,
            "good_steps": state.scaler.good_steps,
            "hysteresis": state.scaler.hysteresis,
        },
        "counters": {
            "micro_steps": state.micro_steps,
            "global_steps": state.global_steps,
            "skipped_steps": state.skipped_steps,
        },
    }
    if state.master is not None:
        tree["master"] = state.master
    flat, _ = jax.tree.flatten(state.opt_state)
    tree["opt_state_flat"] = {f"leaf_{i}": leaf for i, leaf in enumerate(flat)}
    return tree


def _flatten_tree(tree, prefix: str = "") -> Dict[str, Any]:
    """dict/list/tuple tree -> {'a.b.0.c': leaf}: the shared dotted-key
    scheme (utils.pytree.leaf_paths) with sequence descent, so saved keys
    and the offline tools' lookups can never drift apart. Empty containers
    vanish (they carry no data; the load template re-supplies them)."""
    from deepspeed_tpu.utils.pytree import leaf_paths
    return leaf_paths(tree, prefix, descend_sequences=True)


def _rebuild_from_flat(template, flat: Dict[str, Any], prefix: str = ""):
    """Walk the TEMPLATE structure, pulling each leaf from ``flat`` by its
    dotted key — missing keys are a structure mismatch (KeyError)."""
    if isinstance(template, dict):
        return {k: _rebuild_from_flat(v, flat, prefix + str(k) + ".")
                for k, v in template.items()}
    if isinstance(template, (list, tuple)):
        seq = [_rebuild_from_flat(v, flat, prefix + str(i) + ".")
               for i, v in enumerate(template)]
        return type(template)(seq) if isinstance(template, tuple) else seq
    key = prefix[:-1]
    if key not in flat:
        raise KeyError(f"checkpoint is missing state leaf {key!r}")
    return flat[key]


def _checkpoint_cfg(engine):
    """The training ``CheckpointConfig``, defaulted when the engine's config
    carries none (or an unrelated one, e.g. the inference config's) — so the
    knob defaults live in exactly one place."""
    from deepspeed_tpu.config.core import CheckpointConfig
    ccfg = getattr(engine._config, "checkpoint_config", None)
    return ccfg if isinstance(ccfg, CheckpointConfig) else CheckpointConfig()


def _storage_kind(engine) -> str:
    kind = _checkpoint_cfg(engine).engine
    if kind == "safe" and jax.process_count() > 1:
        # the safe engine serializes full logical arrays host-side; in a
        # multi-controller job only orbax writes per-process shards
        return "orbax"
    return kind


def _notify_ckpt_result(engine, ok: bool, steps: Optional[int]) -> None:
    health = getattr(engine, "_health", None)
    if health is not None and hasattr(health, "observe_checkpoint"):
        try:
            health.observe_checkpoint(ok, step=steps)
        except Exception as e:
            logger.warning(f"health checkpoint observation failed: {e}")


# --------------------------------------------------------------------- #
# save

def _build_meta(engine, tag: str, client_state) -> Dict[str, Any]:
    """The checkpoint meta dict, shared by the safe and orbax save paths so
    a field added to one can never silently miss the other."""
    meta = {
        "tag": tag,
        "global_steps": int(engine.global_steps),
        "micro_steps": int(engine.micro_steps),
        "skipped_steps": int(engine.skipped_steps),
        "ds_config": engine._config._param_dict,
        "lr_scheduler": engine.lr_scheduler.state_dict() if engine.lr_scheduler is not None else None,
        "client_state": client_state or {},
        "framework_version": 1,
        "data_progress": dict(getattr(engine, "_data_progress", {}) or {}),
    }
    if getattr(engine, "quantizer", None) is not None:
        # MoQ host schedule: a resumed run must continue mid-schedule
        meta["moq_state"] = engine.quantizer.state_dict()
    if engine.state.opt_state is not None:
        # structured identity of every opt_state_flat leaf, so tools
        # (ds_to_universal) never have to guess moments by shape matching
        meta["opt_state_labels"] = _opt_state_labels(engine.state.opt_state)
    return meta


def _offload_arrays(sd: Dict[str, Any], copy: bool = False) -> Dict[str, Any]:
    """Flatten an offload optimizer state_dict to '|'-keyed npz arrays.
    ``copy=True`` for async saves: the non-swapper state_dict returns the
    LIVE master buffers, which cpu_adam keeps mutating in place while the
    background writer serializes."""
    out: Dict[str, Any] = {"step": np.asarray(sd.get("step", 0)),
                           "lr": np.asarray(sd.get("lr", 0.0))}
    for group in ("masters", "exp_avg", "exp_avg_sq"):
        for k, v in sd.get(group, {}).items():
            out[f"{group}|{k}"] = np.array(v, copy=True) if copy else v
    return out


def save_engine_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                           client_state=None, save_latest: bool = True,
                           asynchronous: Optional[bool] = None) -> bool:
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    tag = str(tag)

    # tag validation (reference engine.py:2800): all processes must agree on
    # the tag; process 0's tag is broadcast and compared against the local one
    if engine._config.checkpoint_tag_validation_enabled and jax.process_count() > 1:
        import hashlib

        from jax.experimental import multihost_utils
        local = np.frombuffer(hashlib.sha256(tag.encode()).digest()[:8], dtype=np.int64).copy()
        agreed = multihost_utils.broadcast_one_to_all(local)
        if not np.array_equal(local, agreed):
            msg = f"Checkpoint tag '{tag}' differs across processes; checkpoints would be inconsistent"
            if engine._config.checkpoint_tag_validation_fail:
                raise ValueError(msg)
            logger.warning(msg)

    save_dir = os.path.abspath(save_dir)
    os.makedirs(save_dir, exist_ok=True)

    if _storage_kind(engine) == "orbax":
        if asynchronous:
            logger.warning(
                f"checkpoint {tag}: async save is not supported on the "
                "orbax path; saving synchronously")
        return _save_orbax(engine, save_dir, tag, client_state, save_latest)

    ccfg = _checkpoint_cfg(engine)
    if asynchronous is None:
        asynchronous = bool(ccfg.async_save)

    # ---- phase 1: device -> host snapshot on the caller's thread ----
    t0 = time.perf_counter()
    steps = int(engine.global_steps)
    host_tree = jax.device_get(_state_tree(engine))
    arrays = {k: np.asarray(v) for k, v in _flatten_tree(host_tree).items()}
    arrays[RNG_KEY] = np.asarray(jax.random.key_data(engine._rng)) \
        if getattr(engine, "_rng", None) is not None else np.zeros((2,), np.uint32)

    extra_npz: Dict[str, Dict[str, Any]] = {}
    offload = getattr(engine, "_offload", None)
    if offload is not None:
        # ZeRO-Offload: host optimizer state (fp32 masters + moments) rides
        # as its own npz next to the device state (reference saves per-dp-
        # rank zero files, engine.py:3136)
        extra_npz[f"offload_state_p{jax.process_index()}.npz"] = \
            _offload_arrays(offload.state_dict(), copy=asynchronous)

    meta = _build_meta(engine, tag, client_state)
    meta["format"] = "safe-v1"
    if asynchronous:
        # the writer thread serializes this later; live references
        # (ds_config, schedules) must not tear under concurrent mutation
        import copy as _copy
        meta = _copy.deepcopy(meta)

    payload = CheckpointPayload(tag=tag, arrays=arrays, meta=meta,
                                extra_npz=extra_npz, global_steps=steps,
                                update_latest=save_latest)
    mets = safe_engine._ckpt_metrics()
    mets["snapshot_ms"].observe((time.perf_counter() - t0) * 1e3)
    now = time.monotonic_ns()
    dur = int((time.perf_counter() - t0) * 1e9)
    safe_engine._emit_ckpt_event("ckpt.snapshot", t_ns=now - dur,
                                 dur_ns=dur, step=steps, tag=tag,
                                 asynchronous=bool(asynchronous))

    if asynchronous:
        writer = engine._checkpoint_writer()
        # runtime config changes (e.g. retention) apply to future jobs
        writer.keep_last = ccfg.keep_last
        writer.retries = ccfg.retries
        writer.retry_backoff_s = ccfg.retry_backoff_s
        writer.submit(save_dir, payload)
        log_dist(f"Queued async checkpoint {tag} for {save_dir} "
                 f"(depth {writer.queue_depth})", ranks=[0])
        return True

    # ---- phase 2 inline (synchronous save) ----
    t1 = time.perf_counter()
    try:
        total = safe_engine.write_tag(
            save_dir, payload, retries=ccfg.retries,
            retry_backoff_s=ccfg.retry_backoff_s, keep_last=ccfg.keep_last)
    except CheckpointWriteError:
        mets["failures"].inc()
        _notify_ckpt_result(engine, False, steps)
        raise
    mets["save_ms"].observe((time.perf_counter() - t1) * 1e3)
    mets["bytes"].observe(total)
    mets["saves"].inc()
    _notify_ckpt_result(engine, True, steps)
    log_dist(f"Saved checkpoint {tag} to {_tag_dir(save_dir, tag)} "
             f"({total / 1e6:.2f} MB)", ranks=[0])
    return True


def _save_orbax(engine, save_dir: str, tag: str, client_state,
                save_latest: bool) -> bool:
    """The multi-host orbax path. The historical ordering bug — ``latest``
    plain-written BEFORE ``ckpt_engine.commit`` — is fixed: the pointer
    moves atomically (tmp+fsync+rename) strictly after commit."""
    path = _tag_dir(save_dir, tag)

    ckpt_engine = engine.checkpoint_engine if hasattr(engine, "checkpoint_engine") else None
    if ckpt_engine is None:
        from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import OrbaxCheckpointEngine
        ckpt_engine = OrbaxCheckpointEngine()
        engine.checkpoint_engine = ckpt_engine

    ckpt_engine.create(tag)
    tree = _state_tree(engine)
    ckpt_engine.save(tree, os.path.join(path, "state"))

    offload = getattr(engine, "_offload", None)
    if offload is not None:
        np.savez(os.path.join(path, f"offload_state_p{jax.process_index()}.npz"),
                 **_offload_arrays(offload.state_dict()))

    meta = _build_meta(engine, tag, client_state)
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
    ckpt_engine.commit(tag)
    # `latest` moves ONLY after the tag is fully committed (regression:
    # a crash between the old early write and commit left `latest`
    # pointing at an uncommitted tag)
    if jax.process_index() == 0 and save_latest:
        safe_engine.atomic_write_text(os.path.join(save_dir, "latest"), tag)
    log_dist(f"Saved checkpoint {tag} to {path}", ranks=[0])
    return True


# --------------------------------------------------------------------- #
# load

def _prepare_tag_load(engine, path: str, verify: bool):
    """Stage EVERYTHING a load needs in host memory — verified manifest,
    decoded state arrays rebuilt against the engine's template, parsed
    meta, offload state — without touching the engine. Raises on any
    missing/corrupt piece; the caller decides walk-back vs abort."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint {path} does not exist")
    legacy = (not os.path.isfile(os.path.join(path, MANIFEST))
              and os.path.isdir(os.path.join(path, "state")))
    template = _state_tree(engine)
    if legacy:
        from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import OrbaxCheckpointEngine
        ckpt_engine = getattr(engine, "checkpoint_engine", None) or OrbaxCheckpointEngine()
        restored = ckpt_engine.load(os.path.join(path, "state"), template=template)
        rng_data = None
        logger.info(f"checkpoint {path}: legacy orbax tag (no manifest; "
                    f"loading unverified)")
    else:
        if verify:
            rep = safe_engine.verify_tag(path)
            if not rep.intact:
                raise CheckpointCorruptError(
                    f"checkpoint {path} failed verification: "
                    + "; ".join(rep.errors))
        flat = safe_engine.read_npz(os.path.join(path, STATE_FILE))
        rng_data = flat.pop(RNG_KEY, None)
        restored = _rebuild_from_flat(template, flat)

    with open(os.path.join(path, META_FILE)) as f:
        meta = json.load(f)

    offload_sd = None
    offload_path = os.path.join(
        path, f"offload_state_p{jax.process_index()}.npz")
    if os.path.exists(offload_path):
        with np.load(offload_path) as z:
            offload_sd = {"step": int(z["step"]), "lr": float(z["lr"]),
                          "masters": {}, "exp_avg": {}, "exp_avg_sq": {}}
            for name in z.files:
                if "|" in name:
                    group, key = name.split("|", 1)
                    offload_sd[group][key] = z[name]

    return {"path": path, "template": template, "restored": restored,
            "meta": meta, "offload_sd": offload_sd, "rng_data": rng_data}


def _apply_prepared(engine, prepared, load_optimizer_states: bool,
                    load_module_only: bool, load_data_progress: bool) -> None:
    """The only function that mutates the engine — runs strictly after
    every read and check succeeded (all-or-nothing)."""
    state = engine.state
    template, restored = prepared["template"], prepared["restored"]
    # re-commit every restored leaf to its template sharding (host arrays /
    # replicated scalars land back on the mesh)
    restored = jax.tree.map(
        lambda r, t: jax.device_put(r, t.sharding) if hasattr(t, "sharding") else r,
        restored, template)

    new_scaler = state.scaler._replace(
        loss_scale=restored["scaler"]["loss_scale"],
        good_steps=restored["scaler"]["good_steps"],
        hysteresis=restored["scaler"]["hysteresis"])
    kwargs = dict(
        params=restored["params"],
        master=restored.get("master", state.master),
        acc_grads=restored["acc_grads"],
        scaler=new_scaler,
        micro_steps=restored["counters"]["micro_steps"],
        global_steps=restored["counters"]["global_steps"],
        skipped_steps=restored["counters"]["skipped_steps"],
    )
    if load_module_only:
        kwargs = dict(params=restored["params"])
    if load_optimizer_states and not load_module_only and "opt_state_flat" in restored:
        flat, treedef = jax.tree.flatten(state.opt_state)
        leaves = [restored["opt_state_flat"][f"leaf_{i}"] for i in range(len(flat))]
        kwargs["opt_state"] = jax.tree.unflatten(treedef, leaves)
    engine.state = state._replace(**kwargs)

    offload = getattr(engine, "_offload", None)
    if offload is not None and load_optimizer_states and not load_module_only \
            and prepared["offload_sd"] is not None:
        offload.load_state_dict(prepared["offload_sd"])

    meta = prepared["meta"]
    if engine.lr_scheduler is not None and meta.get("lr_scheduler"):
        engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    if getattr(engine, "quantizer", None) is not None and meta.get("moq_state"):
        engine.quantizer.load_state_dict(meta["moq_state"])

    if prepared["rng_data"] is not None and hasattr(engine, "_rng"):
        engine._rng = jax.random.wrap_key_data(
            jnp.asarray(prepared["rng_data"]))

    progress = meta.get("data_progress") or {}
    if hasattr(engine, "_data_progress"):
        engine._data_progress = {
            "consumed_samples": int(progress.get("consumed_samples", 0)),
            "iterations": int(progress.get("iterations", 0)),
        }
    if load_data_progress and progress.get("iterations"):
        ff = getattr(engine, "_fast_forward_data", None)
        if ff is not None:
            ff(int(progress["iterations"]))


def load_engine_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                           load_optimizer_states: bool = True,
                           load_module_only: bool = False,
                           strict: bool = False,
                           load_data_progress: bool = False):
    """Resolve, verify, stage, then apply. Auto-resolved tags (``tag=None``)
    walk back newest-first past corrupt/partial tags to the newest intact
    one. ``strict=True`` turns the silent ``(None, {})`` for a missing
    ``latest``/directory into ``FileNotFoundError``. A checkpoint that
    EXISTS but is corrupt (with no intact fallback) always raises
    :class:`CheckpointCorruptError` — data loss is never silent."""
    load_dir = os.path.abspath(load_dir)
    try:
        # a crash mid tag-overwrite leaves the tag only as .tmp/.old
        # survivors; promote them before resolving candidates
        safe_engine.recover_interrupted(load_dir)
    except OSError:
        pass
    verify = _checkpoint_cfg(engine).verify_on_load

    explicit = tag is not None
    candidates = []
    if explicit:
        candidates = [str(tag)]
    else:
        latest = safe_engine._latest_target(load_dir)
        if latest:
            candidates.append(latest)
        for rep in safe_engine.list_tags(load_dir):
            if rep.tag not in candidates:
                candidates.append(rep.tag)
        if not candidates:
            if strict:
                raise FileNotFoundError(
                    f"no 'latest' file or checkpoint tags in {load_dir}")
            logger.warning(f"No checkpoint found at {load_dir}; "
                           f"cannot auto-resolve tag")
            return None, {}

    errors = []
    for cand in candidates:
        path = _tag_dir(load_dir, cand)
        try:
            prepared = _prepare_tag_load(engine, path, verify=verify)
        except FileNotFoundError as e:
            errors.append(f"{cand}: {e}")
            if explicit:
                if strict:
                    raise
                logger.warning(str(e))
                return None, {}
            continue
        except Exception as e:
            errors.append(f"{cand}: {e}")
            if explicit:
                raise CheckpointCorruptError(
                    f"checkpoint tag {cand} is unusable: {e}") from e
            logger.warning(f"checkpoint {cand} unusable ({e}); "
                           f"walking back to an older tag")
            continue
        _apply_prepared(engine, prepared, load_optimizer_states,
                        load_module_only, load_data_progress)
        if cand != candidates[0]:
            logger.warning(
                f"resumed from {cand} after skipping "
                f"{candidates.index(cand)} corrupt/partial newer tag(s)")
        log_dist(f"Loaded checkpoint {cand} from {path} "
                 f"(step {engine.global_steps})", ranks=[0])
        return path, prepared["meta"].get("client_state", {})

    raise CheckpointCorruptError(
        f"no intact checkpoint in {load_dir}: " + "; ".join(errors))
