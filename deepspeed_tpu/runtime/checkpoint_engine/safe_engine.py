"""Crash-safe two-phase checkpoint storage.

CheckFreq-style decoupling (Mohan et al., FAST '21): the *snapshot* (device →
host copy) happens on the training thread and is cheap; the *persist*
(serialize + fsync + atomic publish) runs here — inline for synchronous
saves, or on :class:`AsyncCheckpointWriter`'s background thread with a
bounded queue for stall-free training.

Durability contract, in commit order:

1. Everything for a tag is written into ``<save_dir>/.tmp.<tag>``; each file
   is fsynced as it closes.
2. ``manifest.json`` — per-file blake2b + byte size, computed by **re-reading
   the persisted bytes** (the manifest attests to what is actually on disk,
   not what we meant to write) — is written last inside the temp dir.
3. The temp dir is fsynced and atomically renamed to ``<save_dir>/<tag>``;
   the parent dir is fsynced. A tag directory therefore either exists with a
   complete manifest or does not exist at all.
4. Only then is ``latest`` updated, itself via tmp + fsync + rename.
5. Retention GC (``keep_last``) runs last and never deletes the newest
   *verified* tag nor the tag ``latest`` points to.

A crash at any byte leaves either the previous consistent state (steps 1-3
incomplete: only ``.tmp.*`` debris, swept on the next save) or the new one.
Transient I/O errors (ENOSPC/EIO from flaky or full storage) are retried
with exponential backoff from a clean temp dir; a fault that outlives the
retry budget surfaces as :class:`CheckpointWriteError` plus the
``checkpoint/failures`` metric and the health observatory's ``ckpt_failure``
detector — never as a half-published tag.

All file writes flow through ``utils.fault_injection.guarded_write`` so the
fault-injection harness can deterministically kill, fail, or delay any byte.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import queue
import shutil
import signal as _signal
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.utils import fault_injection
from deepspeed_tpu.utils.fault_injection import SimulatedCrash
from deepspeed_tpu.utils.logging import logger

MANIFEST = "manifest.json"
STATE_FILE = "state.npz"
META_FILE = "meta.json"
MANIFEST_FORMAT = 1
_DTYPE_TAG = "::dt="
_HASH_CHUNK = 1 << 20


def _emit_ckpt_event(kind: str, **data) -> None:
    """Flight-recorder hook for the checkpoint phases (snapshot is emitted
    by the engine-side save path; serialize/commit/retry here). Disabled
    recorder = one flag check; diagnostics never fail a save."""
    try:
        from deepspeed_tpu.monitor.events import get_flight_recorder
        get_flight_recorder().emit(kind, **data)
    except Exception:
        pass


class CheckpointWriteError(RuntimeError):
    """A checkpoint save failed after exhausting its retry budget. The
    previous committed checkpoints are untouched."""


class CheckpointCorruptError(RuntimeError):
    """A checkpoint tag failed manifest verification (and walk-back was
    disallowed or found no intact tag)."""


# --------------------------------------------------------------------- #
# low-level durable I/O

def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_bytes_durable(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        fault_injection.guarded_write(f, data, path)
        f.flush()
        os.fsync(f.fileno())


def atomic_write_text(path: str, text: str) -> None:
    """tmp + fsync + rename + dir fsync: readers see the old content or the
    new, never a torn write."""
    tmp = path + ".tmp"
    _write_bytes_durable(tmp, text.encode())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


class _InjectedFile:
    """File wrapper routing writes through the fault-injection harness;
    everything else (tell/seek/flush — zipfile needs them) delegates."""

    def __init__(self, f, path: str):
        self._f = f
        self._path = path

    def write(self, data) -> int:
        fault_injection.guarded_write(self._f, data, self._path)
        return len(data)

    def __getattr__(self, name):
        return getattr(self._f, name)


# --------------------------------------------------------------------- #
# array (de)serialization — flat {dotted key: ndarray} <-> one npz

def _descr_roundtrips(dt: np.dtype) -> bool:
    import warnings
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return np.lib.format.descr_to_dtype(
                np.lib.format.dtype_to_descr(dt)) == dt
    except Exception:
        return False


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def encode_arrays(flat: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """npz cannot represent non-native dtypes (bf16, fp8): store their raw
    bits as unsigned ints under ``key::dt=<name>``."""
    out: Dict[str, np.ndarray] = {}
    for k, v in flat.items():
        a = np.asarray(v)
        if _descr_roundtrips(a.dtype):
            out[k] = a
        else:
            out[k + _DTYPE_TAG + a.dtype.name] = a.view(
                np.dtype(f"u{a.dtype.itemsize}"))
    return out


def decode_arrays(npz) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for k in npz.files:
        if _DTYPE_TAG in k:
            base, name = k.split(_DTYPE_TAG, 1)
            out[base] = npz[k].view(_resolve_dtype(name))
        else:
            out[k] = npz[k]
    return out


def write_npz(path: str, flat: Dict[str, Any]) -> None:
    """np.savez-compatible container written through the injected file (so
    every byte is fault-injectable), with the zip close guarded: an injected
    crash/fault mid-stream must propagate, not the ZipFile destructor's
    complaint about the abandoned handle."""
    import zipfile

    from numpy.lib import format as npformat

    encoded = encode_arrays(flat)
    with open(path, "wb") as raw:
        zf = zipfile.ZipFile(_InjectedFile(raw, path), mode="w",
                             compression=zipfile.ZIP_STORED, allowZip64=True)
        try:
            for k, a in encoded.items():
                with zf.open(k + ".npy", "w", force_zip64=True) as member:
                    npformat.write_array(member, np.asarray(a),
                                         allow_pickle=False)
        finally:
            try:
                zf.close()
            except BaseException:
                # mid-fault: the stream is already broken — the original
                # exception (OSError / SimulatedCrash) is what matters
                if not fault_injection.active():
                    raise
        raw.flush()
        os.fsync(raw.fileno())


def read_npz(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as z:
        return decode_arrays(z)


def _blake2b_file(path: str) -> Tuple[str, int]:
    h = hashlib.blake2b(digest_size=16)
    n = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_HASH_CHUNK)
            if not chunk:
                break
            h.update(chunk)
            n += len(chunk)
    return h.hexdigest(), n


# --------------------------------------------------------------------- #
# tag payload + write

@dataclasses.dataclass
class CheckpointPayload:
    """Everything one committed tag persists. ``arrays`` are HOST numpy
    (phase 1 already happened); ``extra_npz`` maps extra file names (e.g.
    ``offload_state_p0.npz``) to their own flat array dicts."""
    tag: str
    arrays: Dict[str, Any]
    meta: Dict[str, Any]
    extra_npz: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    global_steps: Optional[int] = None
    update_latest: bool = True


def _tmp_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, f".tmp.{tag}")


# tags currently being written in THIS process (writer thread or inline
# save): retention sweep and crash recovery must not touch their temp/aside
# dirs — e.g. a synchronous emergency save racing a still-draining async job
_IN_FLIGHT_LOCK = threading.Lock()
_IN_FLIGHT: Dict[str, int] = {}


def _mark_in_flight(save_dir: str, tag: str, delta: int) -> None:
    key = os.path.join(os.path.abspath(save_dir), tag)
    with _IN_FLIGHT_LOCK:
        n = _IN_FLIGHT.get(key, 0) + delta
        if n > 0:
            _IN_FLIGHT[key] = n
        else:
            _IN_FLIGHT.pop(key, None)


def _tag_in_flight(save_dir: str, tag: str) -> bool:
    key = os.path.join(os.path.abspath(save_dir), tag)
    with _IN_FLIGHT_LOCK:
        return key in _IN_FLIGHT


def _is_tag_dir(save_dir: str, name: str) -> bool:
    if name.startswith(".") or name.endswith(".old"):
        return False
    p = os.path.join(save_dir, name)
    if not os.path.isdir(p):
        return False
    return (os.path.isfile(os.path.join(p, MANIFEST))
            or os.path.isdir(os.path.join(p, "state"))     # legacy orbax
            or os.path.isfile(os.path.join(p, META_FILE)))


def _write_tag_once(save_dir: str, payload: CheckpointPayload) -> int:
    """One attempt at steps 1-3 of the durability contract. Returns the
    committed byte total. Raises OSError on I/O faults (retryable) and lets
    SimulatedCrash propagate untouched."""
    tag_dir = os.path.join(save_dir, payload.tag)
    tmp = _tmp_dir(save_dir, payload.tag)
    if os.path.isdir(tmp):
        shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)

    t_ser = time.monotonic_ns()
    write_npz(os.path.join(tmp, STATE_FILE), payload.arrays)
    for name, flat in payload.extra_npz.items():
        write_npz(os.path.join(tmp, name), flat)
    _write_bytes_durable(
        os.path.join(tmp, META_FILE),
        json.dumps(payload.meta, indent=2, default=str).encode())

    files: Dict[str, Dict[str, Any]] = {}
    total = 0
    for name in sorted(os.listdir(tmp)):
        digest, size = _blake2b_file(os.path.join(tmp, name))
        files[name] = {"blake2b": digest, "bytes": size}
        total += size
    manifest = {"format": MANIFEST_FORMAT, "tag": payload.tag,
                "global_steps": payload.global_steps,
                "created_unix": time.time(), "files": files}
    _write_bytes_durable(os.path.join(tmp, MANIFEST),
                         json.dumps(manifest, indent=2).encode())
    _fsync_dir(tmp)
    t_commit = time.monotonic_ns()
    _emit_ckpt_event("ckpt.serialize", t_ns=t_ser, dur_ns=t_commit - t_ser,
                     step=payload.global_steps, tag=payload.tag, bytes=total)

    if os.path.isdir(tag_dir):
        # overwriting an existing tag: park it aside so there is never a
        # moment with a half-published dir under the tag name
        aside = tag_dir + ".old"
        if os.path.isdir(aside):
            shutil.rmtree(aside, ignore_errors=True)
        os.replace(tag_dir, aside)
        os.replace(tmp, tag_dir)
        shutil.rmtree(aside, ignore_errors=True)
    else:
        os.replace(tmp, tag_dir)
    _fsync_dir(save_dir)
    _emit_ckpt_event("ckpt.commit", t_ns=t_commit,
                     dur_ns=time.monotonic_ns() - t_commit,
                     step=payload.global_steps, tag=payload.tag, bytes=total)
    return total


def _retry_os(fn, what: str, retries: int, retry_backoff_s: float):
    """Run ``fn``, retrying OSErrors with exponential backoff; budget
    exhaustion surfaces as :class:`CheckpointWriteError` so callers'
    failure accounting (metrics, health detector) always sees it."""
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            attempt += 1
            if attempt > max(retries, 0):
                raise CheckpointWriteError(
                    f"{what} failed after {attempt} attempt(s): {e}") from e
            _emit_ckpt_event("ckpt.retry", what=what, attempt=attempt,
                             error=str(e))
            delay = retry_backoff_s * (2 ** (attempt - 1))
            logger.warning(
                f"{what}: transient error ({e}); "
                f"retry {attempt}/{retries} in {delay:.2g}s")
            if delay > 0:
                time.sleep(delay)


def write_tag(save_dir: str, payload: CheckpointPayload, *,
              retries: int = 3, retry_backoff_s: float = 0.5,
              keep_last: int = 0) -> int:
    """The full commit sequence with retry-with-backoff around the write
    attempt. Returns committed bytes; raises :class:`CheckpointWriteError`
    when the fault outlives the budget. ``latest`` moves only after the tag
    is durably committed."""
    save_dir = os.path.abspath(save_dir)
    os.makedirs(save_dir, exist_ok=True)
    _mark_in_flight(save_dir, payload.tag, +1)
    try:
        try:
            total = _retry_os(lambda: _write_tag_once(save_dir, payload),
                              f"checkpoint {payload.tag}: save",
                              retries, retry_backoff_s)
        except CheckpointWriteError:
            shutil.rmtree(_tmp_dir(save_dir, payload.tag), ignore_errors=True)
            raise
    finally:
        _mark_in_flight(save_dir, payload.tag, -1)
    if payload.update_latest:
        # a straggling async job must not move `latest` BACKWARD past a tag
        # a later save already committed (e.g. a sync emergency save that
        # gave up draining the writer) — the pointer only ever advances
        cur = _latest_target(save_dir)
        cur_steps = None
        if cur and cur != payload.tag:
            cur_dir = os.path.join(save_dir, cur)
            if os.path.isdir(cur_dir):
                cur_steps = _tag_steps_hint(cur_dir, cur)
        if (payload.global_steps is not None and cur_steps is not None
                and cur_steps > payload.global_steps):
            logger.warning(
                f"checkpoint {payload.tag} (step {payload.global_steps}) "
                f"committed, but latest already points at newer {cur} "
                f"(step {cur_steps}); pointer not moved backward")
        else:
            # the pointer write shares the retry budget: the tag is already
            # durable here, and a transient fault on `latest` must not escape
            # as a raw OSError that bypasses failure accounting
            _retry_os(lambda: atomic_write_text(
                          os.path.join(save_dir, "latest"), payload.tag),
                      f"checkpoint {payload.tag}: latest pointer",
                      retries, retry_backoff_s)
    if keep_last > 0:
        try:
            # the tag just committed is verified by construction (manifest
            # hashed from re-read bytes) — no need to re-hash it for GC
            gc_tags(save_dir, keep_last, assume_intact=(payload.tag,))
        except Exception as e:   # GC must never fail a committed save
            logger.warning(f"checkpoint retention GC failed: {e}")
    return total


def unflatten_dotted(flat: Dict[str, Any]) -> Dict[str, Any]:
    """{'a.b.c': leaf} -> nested dicts. The inverse of the save-side
    flattening for dict-only trees (integer segments from list/tuple nodes
    stay string keys — offline tools only walk dict sections)."""
    out: Dict[str, Any] = {}
    for key, leaf in flat.items():
        parts = key.split(".")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return out


def read_state_tree(tag_dir: str) -> Dict[str, Any]:
    """Offline-tool loader for ONE tag's state tree, either format: the
    safe engine's ``state.npz`` (rebuilt to nested dicts) or a legacy orbax
    ``state`` directory."""
    npz_path = os.path.join(tag_dir, STATE_FILE)
    if os.path.isfile(npz_path):
        flat = read_npz(npz_path)
        flat.pop("__rng_key_data__", None)
        return unflatten_dotted(flat)
    state_dir = os.path.join(tag_dir, "state")
    if os.path.isdir(state_dir):
        import orbax.checkpoint as ocp
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(state_dir)
    raise FileNotFoundError(
        f"no checkpoint state ({STATE_FILE} or state/) under {tag_dir}")


# --------------------------------------------------------------------- #
# verification / discovery / retention

@dataclasses.dataclass
class TagReport:
    tag: str
    path: str
    intact: bool
    legacy: bool = False
    global_steps: Optional[int] = None
    errors: List[str] = dataclasses.field(default_factory=list)


def _tag_steps_hint(path: str, tag: str) -> Optional[int]:
    """Cheap ordering key: manifest (no hashing) > meta.json > trailing int
    in the tag name."""
    for name in (MANIFEST, META_FILE):
        p = os.path.join(path, name)
        if os.path.isfile(p):
            try:
                with open(p) as f:
                    steps = json.load(f).get("global_steps")
                if steps is not None:
                    return int(steps)
            except (ValueError, OSError):
                pass
    digits = ""
    for ch in reversed(tag):
        if ch.isdigit():
            digits = ch + digits
        elif digits:
            break
    return int(digits) if digits else None


def verify_tag(path: str) -> TagReport:
    """Full integrity check of one tag directory: manifest present and
    parseable, every listed file present with matching size and blake2b.
    Legacy (orbax-format) tags have no manifest and report
    ``legacy=True, intact=True`` — loadable but unverifiable."""
    tag = os.path.basename(path.rstrip(os.sep))
    rep = TagReport(tag=tag, path=path, intact=False)
    if not os.path.isdir(path):
        rep.errors.append("missing directory")
        return rep
    man_path = os.path.join(path, MANIFEST)
    if not os.path.isfile(man_path):
        if os.path.isdir(os.path.join(path, "state")):
            rep.legacy = True
            rep.intact = True
            rep.global_steps = _tag_steps_hint(path, tag)
            rep.errors.append("legacy orbax tag: no manifest to verify")
            return rep
        rep.errors.append(f"missing {MANIFEST}")
        return rep
    try:
        with open(man_path) as f:
            man = json.load(f)
        files = man["files"]
    except (ValueError, KeyError, OSError) as e:
        rep.errors.append(f"{MANIFEST} unreadable: {e}")
        return rep
    rep.global_steps = man.get("global_steps")
    for name, info in files.items():
        fpath = os.path.join(path, name)
        if not os.path.isfile(fpath):
            rep.errors.append(f"{name}: missing")
            continue
        digest, size = _blake2b_file(fpath)
        if size != info.get("bytes"):
            rep.errors.append(
                f"{name}: size {size} != manifest {info.get('bytes')}")
        elif digest != info.get("blake2b"):
            rep.errors.append(f"{name}: blake2b mismatch")
    # meta must also parse — a valid hash of an unparseable meta cannot
    # happen via corruption, but guard the contract anyway
    meta_p = os.path.join(path, META_FILE)
    if META_FILE in files and not rep.errors:
        try:
            with open(meta_p) as f:
                json.load(f)
        except (ValueError, OSError) as e:
            rep.errors.append(f"{META_FILE}: unparseable: {e}")
    rep.intact = not rep.errors
    return rep


def list_tags(save_dir: str) -> List[TagReport]:
    """Shallow reports (no hashing) for every tag dir, newest first by
    global-steps hint (mtime breaks ties)."""
    save_dir = os.path.abspath(save_dir)
    if not os.path.isdir(save_dir):
        return []
    reps = []
    for name in os.listdir(save_dir):
        if not _is_tag_dir(save_dir, name):
            continue
        path = os.path.join(save_dir, name)
        reps.append(TagReport(
            tag=name, path=path, intact=True,
            legacy=not os.path.isfile(os.path.join(path, MANIFEST)),
            global_steps=_tag_steps_hint(path, name)))
    def _key(r: TagReport):
        steps = r.global_steps if r.global_steps is not None else -1
        try:
            mtime = os.path.getmtime(r.path)
        except OSError:
            mtime = 0.0
        return (steps, mtime)
    reps.sort(key=_key, reverse=True)
    return reps


def newest_intact_tag(save_dir: str,
                      exclude: Sequence[str] = (),
                      assume_intact: Sequence[str] = ()) -> Optional[TagReport]:
    """Walk tags newest-first, full-verifying each, and return the first
    intact one (legacy tags count as intact-by-assumption). Tags named in
    ``assume_intact`` skip the hashing pass — used for a tag whose manifest
    was just computed from re-read persisted bytes, i.e. verified by
    construction."""
    for rep in list_tags(save_dir):
        if rep.tag in exclude:
            continue
        if rep.tag in assume_intact:
            return rep
        full = verify_tag(rep.path)
        if full.intact:
            return full
    return None


def recover_interrupted(save_dir: str) -> List[str]:
    """Heal the overwrite crash window: replacing an existing tag parks the
    old copy at ``<tag>.old`` before renaming the fully-written
    ``.tmp.<tag>`` into place, so there is an instant where the tag name
    does not exist. A crash there leaves both survivors — which the debris
    sweep would otherwise delete. Promote the complete temp copy (it must
    verify against its own manifest), else restore the parked old copy.
    Returns recovered tag names."""
    save_dir = os.path.abspath(save_dir)
    if not os.path.isdir(save_dir):
        return []
    recovered: List[str] = []
    # temp copies first: when both survive, the fully-written new copy wins
    for prefix_pass in (True, False):
        for name in os.listdir(save_dir):
            if prefix_pass:
                if not name.startswith(".tmp."):
                    continue
                tag = name[len(".tmp."):]
            else:
                if not name.endswith(".old"):
                    continue
                tag = name[:-len(".old")]
            if not tag or os.path.isdir(os.path.join(save_dir, tag)):
                continue
            if _tag_in_flight(save_dir, tag):
                continue   # a live writer owns these files, not a crash
            src = os.path.join(save_dir, name)
            if prefix_pass and not verify_tag(src).intact:
                continue   # half-written attempt: normal debris
            try:
                os.replace(src, os.path.join(save_dir, tag))
                _fsync_dir(save_dir)
                recovered.append(tag)
                logger.warning(f"checkpoint {tag}: recovered from "
                               f"interrupted overwrite ({name})")
            except OSError as e:
                logger.warning(f"checkpoint recovery of {name} failed: {e}")
    return recovered


def _latest_target(save_dir: str) -> Optional[str]:
    p = os.path.join(save_dir, "latest")
    try:
        with open(p) as f:
            return f.read().strip()
    except OSError:
        return None


def gc_tags(save_dir: str, keep_last: int,
            protect: Sequence[str] = (),
            assume_intact: Sequence[str] = ()) -> List[str]:
    """Keep the ``keep_last`` newest tags. Never deletes the tag ``latest``
    points to, anything in ``protect``, or — the invariant that makes
    retention safe under corruption — the newest tag that actually verifies
    intact, even when it has aged past the window. Also sweeps stale
    ``.tmp.*`` / ``*.old`` debris from crashed writes (after promoting any
    interrupted-overwrite survivors back to their tag). Returns deleted
    tag names."""
    save_dir = os.path.abspath(save_dir)
    recover_interrupted(save_dir)
    reps = list_tags(save_dir)
    victims = reps[keep_last:] if keep_last > 0 else []
    deleted: List[str] = []
    keep = set(protect)
    latest = _latest_target(save_dir)
    if latest:
        keep.add(latest)
    if victims:
        newest_ok = newest_intact_tag(save_dir, assume_intact=assume_intact)
        if newest_ok is not None:
            keep.add(newest_ok.tag)
    for rep in victims:
        if rep.tag in keep:
            continue
        shutil.rmtree(rep.path, ignore_errors=True)
        deleted.append(rep.tag)
    for name in os.listdir(save_dir):
        if name.startswith(".tmp."):
            owner = name[len(".tmp."):]
        elif name.endswith(".old"):
            owner = name[:-len(".old")]
        else:
            continue
        if _tag_in_flight(save_dir, owner):
            continue   # belongs to a save still running in this process
        shutil.rmtree(os.path.join(save_dir, name), ignore_errors=True)
    if deleted:
        _fsync_dir(save_dir)
    return deleted


# --------------------------------------------------------------------- #
# metrics

def _ckpt_metrics():
    from deepspeed_tpu.monitor.metrics import get_registry
    reg = get_registry()
    return {
        "save_ms": reg.histogram(
            "checkpoint/save_ms",
            "persist phase wall time per tag (serialize+fsync+commit)"),
        "snapshot_ms": reg.histogram(
            "checkpoint/snapshot_ms",
            "device->host snapshot wall time on the training thread"),
        "bytes": reg.histogram(
            "checkpoint/bytes", "committed bytes per checkpoint tag"),
        "queue_depth": reg.gauge(
            "checkpoint/queue_depth",
            "async writer jobs queued or in flight"),
        "saves": reg.counter("checkpoint/saves", "committed checkpoint tags"),
        "failures": reg.counter(
            "checkpoint/failures",
            "saves failed after exhausting the retry budget"),
    }


# --------------------------------------------------------------------- #
# the bounded background writer

class _Job:
    def __init__(self, save_dir: str, payload: CheckpointPayload):
        self.save_dir = save_dir
        self.payload = payload
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.crashed = False


class AsyncCheckpointWriter:
    """One daemon thread draining a bounded queue of checkpoint jobs.
    ``submit`` blocks when ``max_pending`` snapshots are already in flight
    (backpressure — host memory for snapshots is bounded). Failures are
    recorded (metrics + ``on_result`` callback + log), never raised on the
    training thread; ``drain`` surfaces the most recent error."""

    def __init__(self, max_pending: int = 2, retries: int = 3,
                 retry_backoff_s: float = 0.5, keep_last: int = 0,
                 on_result: Optional[Callable[[bool, Optional[int]], None]] = None):
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s
        self.keep_last = keep_last
        self.on_result = on_result
        self._q: "queue.Queue[Optional[_Job]]" = queue.Queue(
            maxsize=max(1, max_pending))
        self._in_flight = 0
        # reentrant: a SIGTERM handler draining the writer may interrupt the
        # main thread inside submit's critical section — a plain Lock would
        # deadlock the emergency save
        self._lock = threading.RLock()
        self._idle = threading.Condition(self._lock)
        self.last_error: Optional[BaseException] = None
        self.completed = 0
        self.failed = 0
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name="ckpt-writer", daemon=True)
        self._thread.start()

    # ---- producer side ---- #

    def submit(self, save_dir: str, payload: CheckpointPayload) -> _Job:
        if self._stopped:
            raise RuntimeError("checkpoint writer already stopped")
        job = _Job(os.path.abspath(save_dir), payload)
        with self._lock:
            self._in_flight += 1
        self._q.put(job)          # blocks at max_pending: bounded memory
        self._set_depth()
        return job

    def drain(self, timeout: Optional[float] = None,
              raise_on_error: bool = False) -> Optional[BaseException]:
        """Wait until every submitted job has been persisted (or failed).
        Returns the last error seen during the drained window, and raises
        it instead when ``raise_on_error``."""
        with self._idle:
            ok = self._idle.wait_for(lambda: self._in_flight == 0,
                                     timeout=timeout)
        if not ok:
            raise TimeoutError("checkpoint writer did not drain in time")
        err = self.last_error
        if err is not None and raise_on_error:
            self.last_error = None
            raise err
        return err

    def stop(self, drain: bool = True) -> None:
        if self._stopped:
            return
        if drain:
            try:
                self.drain()
            except Exception:
                pass
        self._stopped = True
        self._q.put(None)
        self._thread.join(timeout=30)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._in_flight

    def _set_depth(self) -> None:
        try:
            _ckpt_metrics()["queue_depth"].set(self.queue_depth)
        except Exception:
            pass

    # ---- the writer thread ---- #

    def _loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            mets = _ckpt_metrics()
            t0 = time.perf_counter()
            ok = False
            try:
                total = write_tag(job.save_dir, job.payload,
                                  retries=self.retries,
                                  retry_backoff_s=self.retry_backoff_s,
                                  keep_last=self.keep_last)
                mets["save_ms"].observe((time.perf_counter() - t0) * 1e3)
                mets["bytes"].observe(total)
                mets["saves"].inc()
                self.completed += 1
                ok = True
            except SimulatedCrash as e:
                # the simulated process death: leave the disk exactly as a
                # real crash would; only the harness bookkeeping survives
                job.crashed = True
                job.error = e
                self.last_error = e
            except BaseException as e:
                job.error = e
                self.last_error = e
                self.failed += 1
                mets["failures"].inc()
                logger.error(
                    f"async checkpoint {job.payload.tag} failed: {e}")
            finally:
                with self._idle:
                    self._in_flight -= 1
                    self._idle.notify_all()
                self._set_depth()
                job.done.set()
                if self.on_result is not None and not job.crashed:
                    try:
                        self.on_result(ok, job.payload.global_steps)
                    except Exception as cb_err:
                        logger.warning(
                            f"checkpoint on_result callback failed: {cb_err}")


# --------------------------------------------------------------------- #
# preemption (SIGTERM/SIGINT) grace handler

class PreemptionHandler:
    """TPU preemption / maintenance grace handling: on SIGTERM (and
    optionally SIGINT) drain the async writer, take a synchronous emergency
    save, then exit with the conventional ``128+signum`` so supervisors see
    a signal death. Re-entrant signals during the save are ignored."""

    def __init__(self, engine, save_dir: str,
                 signals: Sequence[int] = (_signal.SIGTERM, _signal.SIGINT),
                 exit_on_signal: bool = True):
        self.engine = engine
        self.save_dir = save_dir
        self.signals = tuple(signals)
        self.exit_on_signal = exit_on_signal
        self._prev: Dict[int, Any] = {}
        self._installed = False
        self._handling = False

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        for sig in self.signals:
            self._prev[sig] = _signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    def _handle(self, signum, frame) -> None:
        if self._handling:
            return
        self._handling = True
        name = _signal.Signals(signum).name
        logger.warning(
            f"{name} received: draining checkpoint writer and taking an "
            f"emergency save to {self.save_dir}")
        try:
            self.engine.emergency_save(self.save_dir)
        except Exception as e:
            logger.error(f"emergency save failed: {e}")
        finally:
            self.uninstall()
            self._handling = False
            if self.exit_on_signal:
                sys.exit(128 + signum)
