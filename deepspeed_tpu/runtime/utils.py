"""Runtime utilities: balanced partitioning, grad norms, memory reporting.

Reference parity: ``deepspeed/runtime/utils.py`` — notably the balanced
layer-partition algorithm (``partition_balanced`` / ``partition_uniform``,
reference :535-614) used by pipeline-module layer placement, the MP-aware
``clip_grad_norm_`` (:304), and ``see_memory_usage`` (:768).
"""

from __future__ import annotations

import gc
import math
from bisect import bisect_left
from typing import List, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    """Inclusive prefix sum."""
    out = []
    total = 0.0
    for w in weights:
        total += w
        out.append(total)
    return out


def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Split ``num_items`` into ``num_parts`` contiguous chunks of near-equal
    length. Returns ``num_parts + 1`` boundaries."""
    parts = [0] * (num_parts + 1)
    chunk, residual = divmod(num_items, num_parts)
    for p in range(num_parts):
        parts[p + 1] = parts[p] + chunk + (1 if p < residual else 0)
    return parts


def _feasible(weights_psum: List[float], num_parts: int, bottleneck: float) -> bool:
    """Greedy check: can we split into <= num_parts contiguous parts, each with
    weight <= bottleneck?"""
    parts = 0
    start_weight = 0.0
    n = len(weights_psum)
    i = 0
    while i < n:
        # furthest j with psum[j] - start_weight <= bottleneck
        limit = start_weight + bottleneck
        j = bisect_left(weights_psum, limit, lo=i)
        if j < n and weights_psum[j] == limit:
            j += 1
        if j == i:  # single item exceeds bottleneck
            return False
        parts += 1
        if parts > num_parts:
            return False
        start_weight = weights_psum[j - 1]
        i = j
    return True


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Contiguous partition of ``weights`` into ``num_parts`` parts minimizing
    the maximum part weight (binary search over the bottleneck + greedy
    packing). Returns ``num_parts + 1`` boundary indices.

    Reference behavior: ``deepspeed/runtime/utils.py:535`` (``partition_balanced``);
    algorithm re-derived, not ported.
    """
    n = len(weights)
    if num_parts <= 0:
        raise ValueError("num_parts must be positive")
    if n == 0:
        return [0] * (num_parts + 1)
    if num_parts >= n:
        # one item per part, trailing empty parts collapse to n
        return list(range(n + 1)) + [n] * (num_parts - n)

    psum = prefix_sum_inc([float(w) for w in weights])
    lo = max(float(w) for w in weights)
    hi = psum[-1]
    # binary search on the real-valued bottleneck to tolerance, then pack
    for _ in range(64):
        mid = (lo + hi) / 2
        if _feasible(psum, num_parts, mid):
            hi = mid
        else:
            lo = mid
    bottleneck = hi * (1 + 1e-9)

    # greedy pack at the found bottleneck, but never leave fewer items than
    # remaining parts (each later part can take at least one item)
    parts = [0]
    start_weight = 0.0
    for p in range(num_parts - 1):
        limit = start_weight + bottleneck
        j = bisect_left(psum, limit, lo=parts[-1])
        if j < n and psum[j] <= limit:
            j += 1
        j = max(j, parts[-1] + 1)            # at least one item
        j = min(j, n - (num_parts - 1 - p))  # leave >=1 item per later part
        parts.append(j)
        start_weight = psum[j - 1]
    parts.append(n)
    return parts


def global_norm(tree) -> jnp.ndarray:
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.asarray(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def clip_grad_norm_(grads, max_norm: float, norm=None):
    """Clip a grad pytree to global norm ``max_norm``; returns (clipped, norm).

    Under pjit the norm is already global (XLA inserts the cross-replica
    reduction for sharded grads) — the reference's explicit MP-group allreduce
    (``runtime/utils.py:304``) is unnecessary.
    """
    norm = global_norm(grads) if norm is None else norm
    coef = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * coef, grads), norm


def see_memory_usage(message: str, force: bool = False) -> None:
    """Log device + host memory usage (reference ``runtime/utils.py:768``)."""
    if not force:
        return
    lines = [message]
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            used = stats.get("bytes_in_use", 0) / 2**30
            peak = stats.get("peak_bytes_in_use", 0) / 2**30
            limit = stats.get("bytes_limit", 0) / 2**30
            lines.append(f"  {d}: in_use {used:.2f}GB peak {peak:.2f}GB limit {limit:.2f}GB")
    try:
        import psutil
        vm = psutil.virtual_memory()
        lines.append(f"  host: used {vm.used / 2**30:.2f}GB ({vm.percent}%)")
    except Exception:
        pass
    logger.info("\n".join(lines))
    gc.collect()


def num_params(tree) -> int:
    return sum(int(math.prod(x.shape)) if hasattr(x, "shape") else 0 for x in jax.tree.leaves(tree))
