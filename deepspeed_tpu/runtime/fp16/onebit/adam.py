"""1-bit Adam (reference ``runtime/fp16/onebit/adam.py`` ``OnebitAdam``).

Two phases:
- **warmup** (steps < freeze_step): exact Adam with full-precision gradient
  averaging (psum) — variance statistics stabilize
- **compression** (steps ≥ freeze_step): the VARIANCE IS FROZEN; only the
  momentum is communicated, through the error-compensated 1-bit compressed
  allreduce — 32× less traffic on the dp axis

Functional design for the compiled SPMD step: the optimizer is a pair of
pure functions ``init(params) → state`` and
``update(local_grads, state, params) → (new_params, new_state)`` meant to
run INSIDE ``shard_map`` over the dp axis with UN-synced local grads —
gradient averaging is the optimizer's job here, exactly like the reference
(which skips the engine allreduce and communicates inside ``step``).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray        # i32
    exp_avg: Any             # momentum pytree
    exp_avg_sq: Any          # (frozen after freeze_step) variance pytree
    worker_error: Any        # per-leaf error feedback [numel]
    server_error: Any        # per-leaf error feedback [numel / n]


class OnebitAdam:

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, freeze_step: int = 100, axis: str = "dp",
                 comm_group_size: int = 1):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.axis = axis
        self.n = comm_group_size

    def _pad(self, numel: int) -> int:
        from deepspeed_tpu.runtime.comm.compressed import pad_to
        return pad_to(numel, self.n)  # divisible by 8*n: whole packed bytes per chunk

    def init(self, params) -> OnebitAdamState:
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return OnebitAdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(zeros, params),
            exp_avg_sq=jax.tree.map(zeros, params),
            worker_error=jax.tree.map(lambda p: jnp.zeros((self._pad(p.size),), jnp.float32), params),
            server_error=jax.tree.map(lambda p: jnp.zeros((self._pad(p.size) // self.n,), jnp.float32),
                                      params),
        )

    def update(self, grads, state: OnebitAdamState, params, lr=None):
        """Run inside shard_map over ``self.axis`` with LOCAL grads."""
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        step = state.step + 1
        warm = state.step < self.freeze_step

        def leaf_update(g, m, v, we, se, p):
            g = g.astype(jnp.float32)

            def warmup(_):
                g_avg = jax.lax.pmean(g, self.axis)
                m_new = beta1 * m + (1 - beta1) * g_avg
                v_new = beta2 * v + (1 - beta2) * jnp.square(g_avg)
                return m_new, v_new, we, se

            def compressed(_):
                # momentum updated from LOCAL grad, then 1-bit-averaged
                m_local = beta1 * m + (1 - beta1) * g
                flat = m_local.ravel()
                pad = we.shape[0] - flat.shape[0]
                flat = jnp.pad(flat, (0, pad))
                m_avg, we_new, se_new = compressed_allreduce(flat, we, se, self.axis)
                m_new = m_avg[:m.size].reshape(m.shape)
                return m_new, v, we_new, se_new  # variance FROZEN

            m_new, v_new, we_new, se_new = jax.lax.cond(warm, warmup, compressed, None)

            bias1 = 1 - beta1 ** step.astype(jnp.float32)
            # the variance is frozen after freeze_step, so its bias
            # correction must freeze too — otherwise 1/sqrt(bias2) shrinks
            # the denom and the effective lr grows without bound
            eff_step = jnp.minimum(step, self.freeze_step).astype(jnp.float32)
            bias2 = 1 - beta2 ** eff_step
            denom = jnp.sqrt(v_new) / jnp.sqrt(bias2) + self.eps
            upd = (m_new / bias1) / denom
            if self.weight_decay > 0:
                upd = upd + self.weight_decay * p.astype(jnp.float32)
            p_new = p.astype(jnp.float32) - lr * upd
            return p_new.astype(p.dtype), m_new, v_new, we_new, se_new

        leaves_p, treedef = jax.tree.flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        leaves_m = treedef.flatten_up_to(state.exp_avg)
        leaves_v = treedef.flatten_up_to(state.exp_avg_sq)
        leaves_we = treedef.flatten_up_to(state.worker_error)
        leaves_se = treedef.flatten_up_to(state.server_error)

        outs = [leaf_update(g, m, v, we, se, p)
                for g, m, v, we, se, p in zip(leaves_g, leaves_m, leaves_v, leaves_we,
                                              leaves_se, leaves_p)]
        new_params = treedef.unflatten([o[0] for o in outs])
        new_state = OnebitAdamState(
            step=step,
            exp_avg=treedef.unflatten([o[1] for o in outs]),
            exp_avg_sq=treedef.unflatten([o[2] for o in outs]),
            worker_error=treedef.unflatten([o[3] for o in outs]),
            server_error=treedef.unflatten([o[4] for o in outs]),
        )
        return new_params, new_state
