"""1-bit LAMB (reference ``runtime/fp16/onebit/lamb.py`` ``OnebitLamb``):
1-bit Adam's compressed-momentum machinery plus LAMB's layerwise trust
ratio. During compression the trust ratio is clamped into the range
established during warmup (the reference's scaling_coeff freeze)."""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam, OnebitAdamState


class OnebitLambState(NamedTuple):
    adam: OnebitAdamState
    scaling_coeffs: Any  # per-leaf frozen trust-ratio bounds


class OnebitLamb(OnebitAdam):

    def __init__(self, *args, min_coeff: float = 0.01, max_coeff: float = 10.0, **kwargs):
        super().__init__(*args, **kwargs)
        self.min_coeff = min_coeff
        self.max_coeff = max_coeff

    def init(self, params) -> OnebitLambState:
        return OnebitLambState(
            adam=super().init(params),
            scaling_coeffs=jax.tree.map(lambda p: jnp.ones((), jnp.float32), params),
        )

    def update(self, grads, state: OnebitLambState, params, lr=None):
        lr = self.lr if lr is None else lr
        # reuse the (possibly compressed) Adam direction with unit lr, then
        # apply the trust ratio per layer
        adam_params, adam_state = super().update(grads, state.adam, params, lr=1.0)

        def trust(p, p_adam, coeff):
            upd = p.astype(jnp.float32) - p_adam.astype(jnp.float32)  # lr=1 step direction
            w_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(upd)
            ratio = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
            ratio = jnp.clip(ratio, self.min_coeff, self.max_coeff)
            # freeze the coefficient once compression starts
            frozen = state.adam.step >= self.freeze_step
            ratio = jnp.where(frozen, jnp.minimum(ratio, coeff * 2.0), ratio)
            new_coeff = jnp.where(frozen, coeff, ratio)
            p_new = p.astype(jnp.float32) - lr * ratio * upd
            return p_new.astype(p.dtype), new_coeff

        leaves_p, treedef = jax.tree.flatten(params)
        leaves_pa = treedef.flatten_up_to(adam_params)
        leaves_c = treedef.flatten_up_to(state.scaling_coeffs)
        outs = [trust(p, pa, c) for p, pa, c in zip(leaves_p, leaves_pa, leaves_c)]
        new_params = treedef.unflatten([o[0] for o in outs])
        new_coeffs = treedef.unflatten([o[1] for o in outs])
        return new_params, OnebitLambState(adam=adam_state, scaling_coeffs=new_coeffs)
