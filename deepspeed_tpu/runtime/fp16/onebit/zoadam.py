"""0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py`` ``ZeroOneAdam``):
adaptive variance freezing + local-step intervals — gradients are averaged
only every ``local_step`` steps (the interval doubles up to a cap), with
1-bit compression for the synchronized momentum in between."""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce


class ZeroOneAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any
    server_error: Any
    local_step_interval: jnp.ndarray  # current sync interval


class ZeroOneAdam:

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, var_freeze_step: int = 100,
                 local_step_scaler: int = 32768, local_step_clipper: int = 16,
                 cuda_aware: bool = False, comm_backend_name: str = "mesh",
                 axis: str = "dp", comm_group_size: int = 1):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.var_freeze_step = var_freeze_step
        self.local_step_clipper = local_step_clipper
        self.axis = axis
        self.n = comm_group_size

    def _pad(self, numel: int) -> int:
        from deepspeed_tpu.runtime.comm.compressed import pad_to
        return pad_to(numel, self.n)  # divisible by 8*n: whole packed bytes per chunk

    def init(self, params) -> ZeroOneAdamState:
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return ZeroOneAdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=jax.tree.map(zeros, params),
            exp_avg_sq=jax.tree.map(zeros, params),
            worker_error=jax.tree.map(lambda p: jnp.zeros((self._pad(p.size),), jnp.float32), params),
            server_error=jax.tree.map(lambda p: jnp.zeros((self._pad(p.size) // self.n,), jnp.float32),
                                      params),
            local_step_interval=jnp.ones((), jnp.int32),
        )

    def update(self, grads, state: ZeroOneAdamState, params, lr=None):
        """Run inside shard_map over ``self.axis`` with LOCAL grads."""
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        step = state.step + 1
        var_frozen = state.step >= self.var_freeze_step
        sync_now = (step % state.local_step_interval) == 0

        def leaf_update(g, m, v, we, se, p):
            g = g.astype(jnp.float32)
            # variance frozen after var_freeze_step; before that, exact avg
            g_avg = jax.lax.pmean(g, self.axis)
            v_new = jnp.where(var_frozen, v, beta2 * v + (1 - beta2) * jnp.square(g_avg))
            m_local = beta1 * m + (1 - beta1) * jnp.where(var_frozen, g, g_avg)

            def synced(_):
                flat = jnp.pad(m_local.ravel(), (0, we.shape[0] - m_local.size))
                m_avg, we_new, se_new = compressed_allreduce(flat, we, se, self.axis)
                return m_avg[:m_local.size].reshape(m_local.shape), we_new, se_new

            def local(_):
                return m_local, we, se

            do_sync = jnp.logical_and(var_frozen, sync_now)
            # before the variance freeze, momentum is already exact (g_avg)
            m_new, we_new, se_new = jax.lax.cond(do_sync, synced, local, None)

            bias1 = 1 - beta1 ** step.astype(jnp.float32)
            # bias correction frozen together with the variance (see adam.py)
            eff_step = jnp.minimum(step, self.var_freeze_step).astype(jnp.float32)
            bias2 = 1 - beta2 ** eff_step
            denom = jnp.sqrt(v_new) / jnp.sqrt(bias2) + self.eps
            upd = (m_new / bias1) / denom
            if self.weight_decay > 0:
                upd = upd + self.weight_decay * p.astype(jnp.float32)
            p_new = p.astype(jnp.float32) - lr * upd
            return p_new.astype(p.dtype), m_new, v_new, we_new, se_new

        leaves_p, treedef = jax.tree.flatten(params)
        outs = [leaf_update(g, m, v, we, se, p)
                for g, m, v, we, se, p in zip(
                    treedef.flatten_up_to(grads), treedef.flatten_up_to(state.exp_avg),
                    treedef.flatten_up_to(state.exp_avg_sq),
                    treedef.flatten_up_to(state.worker_error),
                    treedef.flatten_up_to(state.server_error), leaves_p)]

        # interval doubles after each sync round, capped (reference schedule)
        interval = jnp.where(
            jnp.logical_and(var_frozen, sync_now),
            jnp.minimum(state.local_step_interval * 2, self.local_step_clipper),
            state.local_step_interval)

        new_params = treedef.unflatten([o[0] for o in outs])
        new_state = ZeroOneAdamState(
            step=step,
            exp_avg=treedef.unflatten([o[1] for o in outs]),
            exp_avg_sq=treedef.unflatten([o[2] for o in outs]),
            worker_error=treedef.unflatten([o[3] for o in outs]),
            server_error=treedef.unflatten([o[4] for o in outs]),
            local_step_interval=interval,
        )
        return new_params, new_state
