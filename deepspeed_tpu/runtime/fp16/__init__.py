"""fp16 optimizer family (reference ``deepspeed/runtime/fp16/``). The fused/
unfused fp16 master-weight machinery lives in the engine's compiled step
(loss_scaler.py + engine TrainState); this package hosts the 1-bit
communication-compressed optimizers."""
