"""Data efficiency pipeline (reference ``runtime/data_pipeline/``):
curriculum learning, difficulty-based sampling, offline data analysis,
mmap indexed datasets, and random-LTD token dropping."""

from deepspeed_tpu.runtime.data_pipeline.config import (get_curriculum_learning,
                                                        get_data_efficiency_config,
                                                        get_data_sampling)
from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
