"""Data-efficiency (curriculum learning + random-LTD) config.

Reference parity: ``deepspeed/runtime/data_pipeline/config.py`` and
``constants.py`` — returns plain nested dicts keyed like the reference JSON
schema so user configs port over unchanged.
"""

from __future__ import annotations

import copy

DATA_EFFICIENCY = "data_efficiency"
DATA_SAMPLING = "data_sampling"
CURRICULUM_LEARNING = "curriculum_learning"
DATA_ROUTING = "data_routing"
RANDOM_LTD = "random_ltd"


DEFAULT_DATA_EFFICIENCY = {
    "enabled": False,
    "seed": 1234,
    DATA_SAMPLING: {
        "enabled": False,
        "num_epochs": 1000,
        "num_workers": 0,
        CURRICULUM_LEARNING: {
            "enabled": False,
        },
    },
    DATA_ROUTING: {
        "enabled": False,
        RANDOM_LTD: {
            "enabled": False,
        },
    },
}


from deepspeed_tpu.config.config_utils import deep_update as _deep_update


def get_data_efficiency_config(param_dict: dict) -> dict:
    return _deep_update(DEFAULT_DATA_EFFICIENCY, param_dict.get(DATA_EFFICIENCY, {}))


def get_data_sampling(param_dict: dict) -> dict:
    return get_data_efficiency_config(param_dict)[DATA_SAMPLING]


def get_curriculum_learning(param_dict: dict) -> dict:
    return get_data_sampling(param_dict)[CURRICULUM_LEARNING]


def get_data_routing(param_dict: dict) -> dict:
    return get_data_efficiency_config(param_dict)[DATA_ROUTING]


def get_random_ltd(param_dict: dict) -> dict:
    return get_data_routing(param_dict)[RANDOM_LTD]
