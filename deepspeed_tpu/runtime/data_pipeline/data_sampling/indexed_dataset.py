"""Memory-mapped indexed dataset (reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` — the
Megatron-derived ``MMapIndexedDataset``).

Two on-disk formats behind one reader:

- ``DSTPUIDX`` — this package's native layout (int64 sizes + byte offsets).
- ``MMIDIDX``  — the Megatron binary layout the reference reads/writes
  (``indexed_dataset.py:370`` ``_HDR_MAGIC = b'MMIDIDX\\x00\\x00'``, version
  ``<Q``, dtype code ``<B``, sequence count ``<Q``, document count ``<Q``,
  int32 sizes, int64 byte pointers, int64 doc_idx), so corpora preprocessed
  with Megatron/reference tooling load here unchanged.

``.bin`` is identical in both: concatenated sample payloads, zero-copy
``np.memmap`` reads — the host-side data path that feeds TPU input pipelines
without materialising the dataset in RAM.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 2  # v2 appends doc_idx; v1 (no document boundaries) still reads
_MEGATRON_MAGIC = b"MMIDIDX\x00\x00"

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}

# the reference's table (indexed_dataset.py:98-110) differs from ours in the
# float rows — 6 and 7 are BOTH float64 upstream — and extends to uint32/64
_MEGATRON_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
                    5: np.int64, 6: np.float64, 7: np.float64, 8: np.uint16,
                    9: np.uint32, 10: np.uint64}
_MEGATRON_CODES = {np.dtype(v): k for k, v in _MEGATRON_DTYPES.items()
                   if k != 7}  # float64 has two codes upstream; write 6


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item`` per sample, ``end_document`` at doc
    boundaries (preserved by both formats), then ``finalize``.

    ``fmt="dstpu"`` (default) writes the native index; ``fmt="megatron"``
    writes a reference-compatible ``MMIDIDX`` index that Megatron/DeepSpeed
    tooling can read back.
    """

    def __init__(self, out_prefix: str, dtype=np.int32, fmt: str = "dstpu"):
        if fmt not in ("dstpu", "megatron"):
            raise ValueError(f"unknown indexed-dataset format {fmt!r}")
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        self._fmt = fmt
        if fmt == "megatron" and self._dtype not in _MEGATRON_CODES:
            raise ValueError(f"dtype {self._dtype} has no megatron code")
        self._data_file = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def end_document(self) -> None:
        """Mark a document boundary after the last added sample."""
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, another_prefix: str) -> None:
        """Append another dataset's samples, preserving its document
        boundaries (megatron doc_idx semantics; v1 native datasets carry no
        boundaries and read back as one-doc-per-sample)."""
        other = MMapIndexedDataset(another_prefix)
        bounds = set(int(b) for b in other.doc_idx[1:])
        for i in range(len(other)):
            self.add_item(other[i])
            if i + 1 in bounds:
                self.end_document()

    def finalize(self) -> None:
        self._data_file.close()
        sizes = np.asarray(self._sizes, dtype=np.int64)
        offsets = np.concatenate([[0], np.cumsum(sizes)])[:-1] * self._dtype.itemsize
        if self._doc_idx[-1] != len(self._sizes):  # trailing partial document
            self._doc_idx.append(len(self._sizes))
        with open(index_file_path(self._prefix), "wb") as f:
            if self._fmt == "megatron":
                f.write(_MEGATRON_MAGIC)
                f.write(struct.pack("<QB", 1, _MEGATRON_CODES[self._dtype]))
                f.write(struct.pack("<QQ", len(sizes), len(self._doc_idx)))
                f.write(sizes.astype(np.int32).tobytes())
                f.write(offsets.astype(np.int64).tobytes())
                f.write(np.asarray(self._doc_idx, np.int64).tobytes())
            else:
                f.write(_MAGIC)
                f.write(struct.pack("<QBQ", _VERSION, _DTYPE_CODES[self._dtype], len(sizes)))
                f.write(sizes.tobytes())
                f.write(offsets.astype(np.int64).tobytes())
                f.write(struct.pack("<Q", len(self._doc_idx)))
                f.write(np.asarray(self._doc_idx, np.int64).tobytes())


class MMapIndexedDataset:
    """Zero-copy random access over a built dataset; reads both the native
    ``DSTPUIDX`` and the reference's ``MMIDIDX`` index layouts (format is
    auto-detected from the magic)."""

    def __init__(self, prefix: str):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MEGATRON_MAGIC))  # longest magic: 9 bytes
            if magic.startswith(_MEGATRON_MAGIC):
                version, dtype_code = struct.unpack("<QB", f.read(9))
                if version != 1:
                    raise ValueError(f"unsupported MMIDIDX version {version}")
                count, doc_count = struct.unpack("<QQ", f.read(16))
                if dtype_code not in _MEGATRON_DTYPES:
                    raise ValueError(f"{index_file_path(prefix)}: unknown "
                                     f"MMIDIDX dtype code {dtype_code}")
                self._dtype = np.dtype(_MEGATRON_DTYPES[dtype_code])
                self._sizes = np.frombuffer(f.read(4 * count),
                                            dtype=np.int32).astype(np.int64)
                self._offsets = np.frombuffer(f.read(8 * count), dtype=np.int64)
                self._doc_idx = np.frombuffer(f.read(8 * doc_count), dtype=np.int64)
            elif magic.startswith(_MAGIC):
                f.seek(len(_MAGIC))
                version, dtype_code, count = struct.unpack("<QBQ", f.read(17))
                if version not in (1, 2):
                    raise ValueError(f"unsupported index version {version}")
                if dtype_code not in _DTYPES:
                    raise ValueError(f"{index_file_path(prefix)}: unknown "
                                     f"DSTPUIDX dtype code {dtype_code}")
                self._dtype = np.dtype(_DTYPES[dtype_code])
                self._sizes = np.frombuffer(f.read(8 * count), dtype=np.int64)
                self._offsets = np.frombuffer(f.read(8 * count), dtype=np.int64)
                if version >= 2:
                    doc_count, = struct.unpack("<Q", f.read(8))
                    self._doc_idx = np.frombuffer(f.read(8 * doc_count),
                                                  dtype=np.int64)
                else:  # v1 carried no boundaries: one document per sample
                    self._doc_idx = np.arange(count + 1, dtype=np.int64)
            else:
                raise ValueError(f"{index_file_path(prefix)}: bad magic {magic!r}")
        self._data = np.memmap(data_file_path(prefix), dtype=self._dtype, mode="r")

    def __len__(self) -> int:
        return len(self._sizes)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    @property
    def doc_idx(self) -> np.ndarray:
        """Document boundaries as sample indices (megatron semantics: entry d
        is the first sample of document d; final entry == len(self)). Native
        datasets default to one document per sample."""
        return self._doc_idx

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        size = self._sizes[idx]
        start = self._offsets[idx] // self._dtype.itemsize
        return self._data[start:start + size]

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        sample = self[idx]
        if length is None:
            length = len(sample) - offset
        return sample[offset:offset + length]

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and os.path.exists(data_file_path(prefix))
