"""Memory-mapped indexed dataset (reference
``runtime/data_pipeline/data_sampling/indexed_dataset.py`` — the
Megatron-derived ``MMapIndexedDataset``).

Binary layout (``.bin`` = concatenated sample payloads, ``.idx`` = header +
per-sample dtype/sizes/offsets) with zero-copy ``np.memmap`` reads — the
host-side data path that feeds TPU input pipelines without materialising
the dataset in RAM.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item`` per sample, then ``finalize``."""

    def __init__(self, out_prefix: str, dtype=np.int32):
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        self._data_file = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._data_file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    def merge_file_(self, another_prefix: str) -> None:
        other = MMapIndexedDataset(another_prefix)
        for i in range(len(other)):
            self.add_item(other[i])

    def finalize(self) -> None:
        self._data_file.close()
        sizes = np.asarray(self._sizes, dtype=np.int64)
        offsets = np.concatenate([[0], np.cumsum(sizes)])[:-1] * self._dtype.itemsize
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<QBQ", _VERSION, _DTYPE_CODES[self._dtype], len(sizes)))
            f.write(sizes.tobytes())
            f.write(offsets.astype(np.int64).tobytes())


class MMapIndexedDataset:
    """Zero-copy random access over a built dataset."""

    def __init__(self, prefix: str):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(prefix)}: bad magic {magic!r}")
            version, dtype_code, count = struct.unpack("<QBQ", f.read(17))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self._dtype = np.dtype(_DTYPES[dtype_code])
            self._sizes = np.frombuffer(f.read(8 * count), dtype=np.int64)
            self._offsets = np.frombuffer(f.read(8 * count), dtype=np.int64)
        self._data = np.memmap(data_file_path(prefix), dtype=self._dtype, mode="r")

    def __len__(self) -> int:
        return len(self._sizes)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(len(self)))]
        size = self._sizes[idx]
        start = self._offsets[idx] // self._dtype.itemsize
        return self._data[start:start + size]

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        sample = self[idx]
        if length is None:
            length = len(sample) - offset
        return sample[offset:offset + length]

    @staticmethod
    def exists(prefix: str) -> bool:
        return os.path.exists(index_file_path(prefix)) and os.path.exists(data_file_path(prefix))
