"""Offline difficulty analysis (reference
``runtime/data_pipeline/data_sampling/data_analyzer.py``).

Runs user metric functions over a dataset (optionally in parallel worker
shards), writes per-sample metric values plus a difficulty→sample-ids index
— the files :class:`DeepSpeedDataSampler` consumes for curriculum sampling.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)


def _metric_value_path(save_path: str, metric_name: str) -> str:
    return os.path.join(save_path, f"{metric_name}_values")


def _metric_index_path(save_path: str, metric_name: str) -> str:
    return os.path.join(save_path, f"{metric_name}_index.json")


class DataAnalyzer:

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Sequence[Callable], save_path: str,
                 num_workers: int = 1, worker_id: int = 0,
                 batch_size: int = 1024):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size

    def _worker_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        start = self.worker_id * per
        return start, min(n, start + per)

    def run_map(self) -> None:
        """Compute metric values for this worker's shard and persist them."""
        os.makedirs(self.save_path, exist_ok=True)
        start, end = self._worker_range()
        for name, fn in zip(self.metric_names, self.metric_functions):
            values = np.asarray([int(fn(self.dataset[i])) for i in range(start, end)],
                                dtype=np.int64)
            np.save(os.path.join(self.save_path, f"{name}_worker{self.worker_id}.npy"), values)

    def run_reduce(self) -> None:
        """Merge all workers' shards into the value file + difficulty index."""
        for name in self.metric_names:
            parts = []
            for w in range(self.num_workers):
                path = os.path.join(self.save_path, f"{name}_worker{w}.npy")
                parts.append(np.load(path))
            values = np.concatenate(parts)

            builder = MMapIndexedDatasetBuilder(_metric_value_path(self.save_path, name),
                                                dtype=np.int64)
            builder.add_item(values)
            builder.finalize()

            index: Dict[int, List[int]] = {}
            for sample_id, v in enumerate(values.tolist()):
                index.setdefault(v, []).append(sample_id)
            with open(_metric_index_path(self.save_path, name), "w") as f:
                json.dump({str(k): v for k, v in sorted(index.items())}, f)

    def run(self) -> None:
        self.run_map()
        if self.worker_id == 0 and self.num_workers == 1:
            self.run_reduce()


def load_metric_values(save_path: str, metric_name: str) -> np.ndarray:
    ds = MMapIndexedDataset(_metric_value_path(save_path, metric_name))
    return np.asarray(ds[0])


def load_metric_index(save_path: str, metric_name: str) -> Dict[int, List[int]]:
    with open(_metric_index_path(save_path, metric_name)) as f:
        raw = json.load(f)
    return {int(k): v for k, v in raw.items()}
