"""Offline dataset analysis (reference
``runtime/data_pipeline/data_sampling/data_analyzer.py``).

Map-reduce over worker shards, file-mediated exactly like the reference so
workers can be separate launcher processes on different hosts sharing only
the filesystem: ``run_map`` computes this worker's shard and persists it;
``run_reduce`` (any single worker, after all maps) merges every worker's
artifacts into the final files :class:`DeepSpeedDataSampler` consumes.

Both reference metric families are supported:

- ``single_value_per_sample`` — one difficulty value per sample; reduce
  concatenates worker shards and builds the difficulty → sample-ids index
  (reference ``sample_to_metric`` + ``metric_to_sample`` files).
- ``accumulate_value_over_samples`` — a running vector accumulated across
  the whole dataset (e.g. token-frequency histograms for vocabulary
  curriculum); reduce sums the worker partials.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.data_sampling.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)

SINGLE_VALUE = "single_value_per_sample"
ACCUMULATE = "accumulate_value_over_samples"


def _metric_value_path(save_path: str, metric_name: str) -> str:
    return os.path.join(save_path, f"{metric_name}_values")


def _metric_index_path(save_path: str, metric_name: str) -> str:
    return os.path.join(save_path, f"{metric_name}_index.json")


class DataAnalyzer:
    """Analyze ``dataset`` with ``metric_functions`` over ``num_workers``
    file-coordinated shards.

    ``metric_types[i]`` selects the family for metric ``i`` (default
    ``single_value_per_sample`` for every metric, the reference's default
    curriculum shape).
    """

    def __init__(self, dataset, metric_names: Sequence[str],
                 metric_functions: Sequence[Callable], save_path: str,
                 num_workers: int = 1, worker_id: int = 0,
                 batch_size: int = 1024,
                 metric_types: Optional[Sequence[str]] = None):
        self.dataset = dataset
        self.metric_names = list(metric_names)
        self.metric_functions = list(metric_functions)
        self.metric_types = (list(metric_types) if metric_types is not None
                             else [SINGLE_VALUE] * len(self.metric_names))
        if len(self.metric_types) != len(self.metric_names):
            raise ValueError("metric_types length != metric_names length")
        for t in self.metric_types:
            if t not in (SINGLE_VALUE, ACCUMULATE):
                raise ValueError(f"unknown metric type {t!r}")
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id
        self.batch_size = batch_size

    def _worker_range(self):
        n = len(self.dataset)
        per = (n + self.num_workers - 1) // self.num_workers
        start = self.worker_id * per
        return start, min(n, start + per)

    # ----------------------------- map ----------------------------- #

    def run_map(self) -> None:
        """Compute metric values for this worker's shard and persist them."""
        os.makedirs(self.save_path, exist_ok=True)
        start, end = self._worker_range()
        for name, fn, mtype in zip(self.metric_names, self.metric_functions,
                                   self.metric_types):
            if mtype == SINGLE_VALUE:
                out = np.asarray([int(fn(self.dataset[i]))
                                  for i in range(start, end)], dtype=np.int64)
            else:  # ACCUMULATE: sum of per-sample vectors over the shard
                acc = None
                for i in range(start, end):
                    v = np.asarray(fn(self.dataset[i]), dtype=np.int64)
                    acc = v.copy() if acc is None else acc + v
                out = acc if acc is not None else np.zeros(0, np.int64)
            np.save(os.path.join(self.save_path,
                                 f"{name}_worker{self.worker_id}.npy"), out)

    # ---------------------------- reduce ---------------------------- #

    def run_reduce(self) -> None:
        """Merge all workers' shards into the final metric files."""
        for name, mtype in zip(self.metric_names, self.metric_types):
            parts = []
            for w in range(self.num_workers):
                path = os.path.join(self.save_path, f"{name}_worker{w}.npy")
                parts.append(np.load(path))

            if mtype == SINGLE_VALUE:
                values = np.concatenate(parts)
            else:
                width = max((p.shape[0] for p in parts), default=0)
                values = np.zeros(width, np.int64)
                for p in parts:
                    values[:p.shape[0]] += p

            builder = MMapIndexedDatasetBuilder(
                _metric_value_path(self.save_path, name), dtype=np.int64)
            builder.add_item(values)
            builder.finalize()

            if mtype == SINGLE_VALUE:
                index: Dict[int, List[int]] = {}
                for sample_id, v in enumerate(values.tolist()):
                    index.setdefault(v, []).append(sample_id)
                with open(_metric_index_path(self.save_path, name), "w") as f:
                    json.dump({str(k): v for k, v in sorted(index.items())}, f)

    def run(self) -> None:
        self.run_map()
        if self.worker_id == 0 and self.num_workers == 1:
            self.run_reduce()


def load_metric_values(save_path: str, metric_name: str) -> np.ndarray:
    ds = MMapIndexedDataset(_metric_value_path(save_path, metric_name))
    return np.asarray(ds[0])


def load_metric_index(save_path: str, metric_name: str) -> Dict[int, List[int]]:
    with open(_metric_index_path(save_path, metric_name)) as f:
        raw = json.load(f)
    return {int(k): v for k, v in raw.items()}


def get_metric_value_percentiles(save_path: str, metric_name: str,
                                 percentiles: Sequence[float] = (10, 50, 90)):
    """Metric-value percentiles over the analyzed dataset (reference
    ``get_metric_value_percentiles`` — used to pick curriculum difficulty
    boundaries from the observed distribution)."""
    values = load_metric_values(save_path, metric_name)
    return {float(p): float(np.percentile(values, p)) for p in percentiles}
