"""Curriculum-aware distributed batch sampler (reference
``runtime/data_pipeline/data_sampling/data_sampler.py``
``DeepSpeedDataSampler``).

Each global step, samples whose difficulty ≤ the curriculum scheduler's
current difficulty are eligible; the sampler draws a deterministic
(seeded, epoch-reshuffled) global batch and yields THIS data-parallel
rank's slice of micro-batch indices. Works with the difficulty files
produced by :class:`DataAnalyzer`, or a plain difficulty array.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler


class DeepSpeedDataSampler:

    def __init__(self, total_samples: int, *, micro_batch_size: int,
                 data_parallel_rank: int, data_parallel_size: int,
                 gradient_accumulation_steps: int = 1,
                 curriculum_scheduler: Optional[CurriculumScheduler] = None,
                 difficulties: Optional[Sequence[int]] = None,
                 drop_last: bool = True, seed: int = 1234):
        self.total_samples = total_samples
        self.micro_batch_size = micro_batch_size
        self.dp_rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.gas = gradient_accumulation_steps
        self.global_batch_size = micro_batch_size * data_parallel_size * gradient_accumulation_steps
        self.curriculum = curriculum_scheduler
        self.difficulties = None if difficulties is None else np.asarray(difficulties)
        self.drop_last = drop_last
        self.seed = seed
        self.consumed_samples = 0
        self.global_steps = 0
        self.np_rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self.total_samples // self.global_batch_size if self.drop_last else \
            (self.total_samples + self.global_batch_size - 1) // self.global_batch_size

    def _eligible_indices(self) -> np.ndarray:
        if self.curriculum is None or self.difficulties is None:
            return np.arange(self.total_samples)
        difficulty = self.curriculum.update_difficulty(self.global_steps)
        eligible = np.nonzero(self.difficulties <= difficulty)[0]
        if len(eligible) < self.global_batch_size:
            # too few easy samples yet: fall back to the easiest global batch
            order = np.argsort(self.difficulties, kind="stable")
            eligible = order[:self.global_batch_size]
        return eligible

    def state_dict(self) -> Dict:
        return {
            "consumed_samples": self.consumed_samples,
            "global_steps": self.global_steps,
            "seed": self.seed,
            "curriculum_state": self.curriculum.get_state() if self.curriculum else None,
        }

    def load_state_dict(self, sd: Dict) -> None:
        self.consumed_samples = sd["consumed_samples"]
        self.global_steps = sd["global_steps"]
        self.seed = sd.get("seed", self.seed)
        if self.curriculum is not None and sd.get("curriculum_state"):
            self.curriculum.set_state(sd["curriculum_state"])

    def __iter__(self) -> Iterator[List[int]]:
        # resume-aware: a checkpoint-restored sampler only yields the
        # REMAINING global batches of the epoch
        done = self.consumed_samples // self.global_batch_size
        for _ in range(max(0, len(self) - done)):
            eligible = self._eligible_indices()
            rng = np.random.default_rng(self.seed + self.global_steps)
            batch = rng.choice(eligible, size=self.global_batch_size,
                               replace=len(eligible) < self.global_batch_size)
            self.global_steps += 1
            self.consumed_samples += self.global_batch_size
            # this rank's slice, one micro-batch at a time
            for micro in range(self.gas):
                lo = micro * self.micro_batch_size * self.dp_size
                chunk = batch[lo:lo + self.micro_batch_size * self.dp_size]
                mine = chunk[self.dp_rank::self.dp_size]
                yield mine.tolist()
