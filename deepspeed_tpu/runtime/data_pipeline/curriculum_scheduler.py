"""Curriculum learning scheduler (reference
``runtime/data_pipeline/curriculum_scheduler.py``).

Maps global step → difficulty (e.g. sequence length) with the reference's
schedule types: ``fixed_linear``, ``fixed_root``, ``fixed_discrete``, and
``custom`` (user callable). Difficulties advance in ``difficulty_step``
quanta — keep it a multiple of 8 on TPU so curriculum seqlens stay
tile-aligned (the reference recommends multiples of 8 for tensor cores).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:

    def __init__(self, config: Dict):
        self.state: Dict = {}
        for key in ("curriculum_type", "min_difficulty", "max_difficulty", "schedule_type"):
            if key not in config and config.get("schedule_type") != CUSTOM:
                if key == "curriculum_type" and "curriculum_type" not in config:
                    config["curriculum_type"] = "seqlen"
                elif key not in config:
                    raise ValueError(f"Curriculum learning requires the config '{key}'")
        if config.get("schedule_type") == CUSTOM:
            # custom schedules may omit the bounds (the callable is in charge)
            self.state["min_difficulty"] = config.get("min_difficulty", 0)
            self.state["max_difficulty"] = config.get("max_difficulty", float("inf"))
        else:
            self.state["min_difficulty"] = config["min_difficulty"]
            self.state["max_difficulty"] = config["max_difficulty"]
        self.state["current_difficulty"] = self.state["min_difficulty"]
        self.state["schedule_type"] = config["schedule_type"]
        self.custom_get_difficulty: Optional[Callable[[int], int]] = None

        schedule_config = config.get("schedule_config", {})
        if self.state["schedule_type"] in (FIXED_LINEAR, FIXED_ROOT):
            for key in ("total_curriculum_step", "difficulty_step"):
                if key not in schedule_config:
                    raise ValueError(f"schedule_config requires '{key}'")
            if schedule_config["difficulty_step"] % 8 != 0:
                from deepspeed_tpu.utils.logging import logger
                logger.warning("difficulty_step not a multiple of 8: curriculum seqlens "
                               "will not be MXU-tile aligned")
            if self.state["schedule_type"] == FIXED_ROOT and "root_degree" not in schedule_config:
                raise ValueError("fixed_root schedule requires 'root_degree'")
        elif self.state["schedule_type"] == FIXED_DISCRETE:
            for key in ("difficulty", "max_step"):
                if key not in schedule_config:
                    raise ValueError(f"schedule_config requires '{key}'")
            if len(schedule_config["max_step"]) != len(schedule_config["difficulty"]) - 1:
                raise ValueError("fixed_discrete needs len(max_step) == len(difficulty) - 1")
        elif self.state["schedule_type"] != CUSTOM:
            raise ValueError(f"Unknown curriculum schedule {self.state['schedule_type']}")
        self.state["schedule"] = schedule_config

    # ------------------------------------------------------------------ #

    def get_current_difficulty(self) -> int:
        return self.state["current_difficulty"]

    def set_current_difficulty(self, difficulty: int) -> None:
        self.state["current_difficulty"] = difficulty

    def set_custom_get_difficulty(self, fn: Callable[[int], int]) -> None:
        self.custom_get_difficulty = fn

    def get_state(self) -> Dict:
        return self.state

    def set_state(self, state: Dict) -> None:
        self.state = state

    # ------------------------------------------------------------------ #

    def _fixed_linear(self, global_steps: int) -> int:
        s = self.state["schedule"]
        span = self.state["max_difficulty"] - self.state["min_difficulty"]
        next_diff = self.state["min_difficulty"] + span * min(
            1.0, global_steps / s["total_curriculum_step"])
        return self._quantize(next_diff, s["difficulty_step"])

    def _fixed_root(self, global_steps: int) -> int:
        s = self.state["schedule"]
        frac = min(1.0, global_steps / s["total_curriculum_step"])
        span = self.state["max_difficulty"] - self.state["min_difficulty"]
        next_diff = self.state["min_difficulty"] + span * (frac ** (1.0 / s["root_degree"]))
        return self._quantize(next_diff, s["difficulty_step"])

    def _fixed_discrete(self, global_steps: int) -> int:
        s = self.state["schedule"]
        for i, boundary in enumerate(s["max_step"]):
            if global_steps <= boundary:
                return s["difficulty"][i]
        return s["difficulty"][-1]

    def _quantize(self, difficulty: float, step: int) -> int:
        q = int((difficulty + step - 1) // step * step) if step > 1 else int(math.ceil(difficulty))
        return min(q, self.state["max_difficulty"])

    def get_difficulty(self, global_steps: int) -> int:
        kind = self.state["schedule_type"]
        if kind == FIXED_LINEAR:
            return self._fixed_linear(global_steps)
        if kind == FIXED_ROOT:
            return self._fixed_root(global_steps)
        if kind == FIXED_DISCRETE:
            return self._fixed_discrete(global_steps)
        if kind == CUSTOM:
            if self.custom_get_difficulty is None:
                raise ValueError("custom schedule requires set_custom_get_difficulty()")
            return self.custom_get_difficulty(global_steps)
        raise ValueError(f"Unknown schedule {kind}")

    def update_difficulty(self, global_steps: int) -> int:
        if self.state["current_difficulty"] < self.state["max_difficulty"]:
            self.state["current_difficulty"] = self.get_difficulty(global_steps)
        return self.state["current_difficulty"]
