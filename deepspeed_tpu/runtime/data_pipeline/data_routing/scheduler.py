"""Random-LTD schedule (reference
``runtime/data_pipeline/data_routing/scheduler.py``): how many tokens each
random-LTD layer keeps at a given global step, ramping linearly from
``start_ratio``·S to the full sequence over ``total_layer_tokens`` steps.
"""

from __future__ import annotations

from typing import Dict


class RandomLTDScheduler:

    def __init__(self, config: Dict):
        # schema mirrors the reference's random_ltd config block
        self.total_layers = config.get("random_ltd_layer_num", 0)
        self.layer_ids = config.get("random_ltd_layer_id", [])
        self.global_batch_size = config.get("global_batch_size", 1)
        sched = config.get("random_ltd_schedule", config.get("schedule", {}))
        self.min_value = sched.get("min_value", 128)
        self.max_value = sched.get("max_value", 1024)
        self.schedule_type = sched.get("schedule_type", "fixed_linear")
        schedule_config = sched.get("schedule_config", {})
        self.total_steps = schedule_config.get("total_curriculum_step",
                                               schedule_config.get("require_steps", 1000))
        self.seq_step = schedule_config.get("seq_per_step", 8)
        self.current_seq = self.min_value
        self.global_steps = 0

    def get_current_seq(self) -> int:
        return self.current_seq

    def update_seq(self, global_steps: int) -> int:
        self.global_steps = global_steps
        if self.current_seq < self.max_value:
            frac = min(1.0, global_steps / max(1, self.total_steps))
            raw = self.min_value + (self.max_value - self.min_value) * frac
            q = int(raw // self.seq_step * self.seq_step)
            self.current_seq = max(self.min_value, min(q, self.max_value))
        return self.current_seq

    def state_dict(self) -> Dict:
        return {"current_seq": self.current_seq, "global_steps": self.global_steps}

    def load_state_dict(self, sd: Dict) -> None:
        self.current_seq = sd["current_seq"]
        self.global_steps = sd["global_steps"]
