"""Random layerwise token dropping — random-LTD (reference
``runtime/data_pipeline/data_routing/basic_layer.py`` + the CUDA token
sort/gather/scatter kernels in ``csrc/random_ltd/``).

TPU-native: the comparison-free token sort + gather/scatter become
``jax.random.permutation`` + ``jnp.take``/scatter — static shapes per
(seq_len, keep_count) pair so everything stays jittable. The wrapper drops
tokens before a layer and scatters the layer's outputs back into the full
sequence (the skipped tokens pass through the residual stream unchanged).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp


def token_sample(rng, seq_len: int, keep: int):
    """Sorted random subset of ``keep`` token indices (reference
    ``token_sort.cu``: comparison-free sort so kept tokens stay in order)."""
    perm = jax.random.permutation(rng, seq_len)
    return jnp.sort(perm[:keep])


def gather_tokens(x, indices):
    """x [B, S, D] → [B, keep, D] (reference ``gather_scatter.cu``)."""
    return jnp.take(x, indices, axis=1)


def scatter_tokens(full, part, indices):
    """Scatter ``part`` [B, keep, D] back over ``full`` [B, S, D]."""
    return full.at[:, indices, :].set(part)


def slice_attention_mask(mask_bias, indices):
    """Key-side additive mask [B, S] → [B, keep] (reference
    ``slice_attn_masks.cu``)."""
    if mask_bias is None:
        return None
    return jnp.take(mask_bias, indices, axis=1)


class RandomLayerTokenDrop:
    """Wrap a transformer layer so it runs on a random token subset.

    ``layer_fn(x_subset, mask_subset, *args) -> y_subset``; dropped tokens
    ride the residual stream untouched.
    """

    def __init__(self, layer_fn: Callable):
        self.layer_fn = layer_fn

    def __call__(self, x, rng, keep: int, mask_bias=None, *args):
        B, S, D = x.shape
        if keep >= S:
            return self.layer_fn(x, mask_bias, *args)
        idx = token_sample(rng, S, keep)
        sub = gather_tokens(x, idx)
        sub_mask = slice_attention_mask(mask_bias, idx)
        out = self.layer_fn(sub, sub_mask, *args)
        return scatter_tokens(x, out, idx)
