from deepspeed_tpu.runtime.data_pipeline.data_routing.basic_layer import (RandomLayerTokenDrop,
                                                                          gather_tokens,
                                                                          scatter_tokens,
                                                                          token_sample)
from deepspeed_tpu.runtime.data_pipeline.data_routing.scheduler import RandomLTDScheduler
