"""Error-compensated 1-bit compressed allreduce (reference
``runtime/comm/nccl.py:53`` ``NcclBackend.compressed_allreduce`` and the
cupy/MPI variant ``mpi.py:131``).

The algorithm (NeurIPS'21 1-bit Adam) in mesh-collective form, run inside
``shard_map`` over the dp axis:

1. worker compensates its local tensor with its error feedback, compresses
   to (packed sign bits, one f32 scale), and updates the worker error
2. each rank acts as "server" for its 1/n chunk: the packed sign chunks
   arrive via an all-to-all (the reference's igather), are unpacked, scaled
   per source rank, averaged, compensated with the server error and
   re-compressed to (packed signs, scale)
3. the twice-compressed chunks are all-gathered — every rank ends with the
   same full tensor

The WIRE FORMAT is genuinely 1 bit per element: sign bits ride packed in
``uint8`` through the collectives (the reference packs via cupy
``packbits``), so the per-step traffic is ~numel/4 bytes instead of the
dense allreduce's 4*numel — the 1-bit family's entire point. Both error
states are carried functionally (returned, not mutated).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def pad_to(numel: int, n: int) -> int:
    """Padded length for a group of ``n``: divisible by 8*n so sign bits
    pack into whole bytes per chunk."""
    q = 8 * n
    return -(-numel // q) * q


def _pack_signs(x) -> jnp.ndarray:
    """[m] float -> [m/8] uint8 sign bitmap (bit set = non-negative)."""
    bits = (x >= 0).astype(jnp.uint8).reshape(-1, 8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :]
    return jnp.sum(bits * weights, axis=1, dtype=jnp.uint8)


def _unpack_signs(b) -> jnp.ndarray:
    """[k] uint8 -> [8k] f32 in {-1, +1}."""
    bits = (b[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1
    return bits.astype(jnp.float32).reshape(-1) * 2.0 - 1.0


def _sign_scale(x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Decompressed view of the scaled-sign compression (for error
    feedback): sign(x) * mean(|x|) (reference nccl.py:70-90)."""
    scale = jnp.mean(jnp.abs(x))
    signs = jnp.where(x >= 0, 1.0, -1.0).astype(x.dtype)
    return signs * scale, scale


def compressed_allreduce(tensor, worker_error, server_error, axis: str = "dp"):
    """Per-shard body (inside shard_map over ``axis``).

    tensor: LOCAL flat [numel] fp32 (this worker's unsynced value, e.g. its
    momentum update), numel divisible by 8*n; worker_error/server_error:
    error-feedback states ([numel] and [numel / n]). Returns (averaged
    tensor, new_worker_error, new_server_error).
    """
    from deepspeed_tpu.comm import bound_axis_size
    n = bound_axis_size(axis)
    numel = tensor.shape[0]
    if numel % (8 * n) != 0:
        raise ValueError(f"compressed_allreduce needs numel ({numel}) divisible by "
                         f"8*group ({8 * n}); pad with pad_to()")
    seg = numel // n

    # 1. worker compression with error feedback
    compensated = tensor + worker_error
    decompressed, scale = _sign_scale(compensated)
    new_worker_error = compensated - decompressed

    # 2. server stage: ship my packed sign chunks to their servers
    # (all-to-all of numel/8 BYTES + n scales — not numel f32s)
    packed = _pack_signs(compensated).reshape(n, seg // 8)
    recv = jax.lax.all_to_all(packed, axis, split_axis=0, concat_axis=0)
    scales = jax.lax.all_gather(scale, axis)                     # [n] f32
    chunk = jnp.mean(jax.vmap(_unpack_signs)(recv) * scales[:, None], axis=0)

    server_comp = chunk + server_error
    server_decompressed, server_scale = _sign_scale(server_comp)
    new_server_error = server_comp - server_decompressed

    # 3. allgather the twice-compressed chunks (packed bytes + scales)
    out_packed = jax.lax.all_gather(_pack_signs(server_comp), axis, axis=0, tiled=True)
    out_scales = jax.lax.all_gather(server_scale, axis)          # [n] f32
    out = _unpack_signs(out_packed) * jnp.repeat(out_scales, seg)
    return out, new_worker_error, new_server_error


class CompressedBackend:
    """Object surface mirroring the reference backend classes."""

    def __init__(self, axis: str = "dp"):
        self.axis = axis

    def compressed_allreduce(self, tensor, worker_error, server_error, local_rank=None):
        return compressed_allreduce(tensor, worker_error, server_error, self.axis)
