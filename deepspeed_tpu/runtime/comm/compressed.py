"""Error-compensated 1-bit compressed allreduce (reference
``runtime/comm/nccl.py:53`` ``NcclBackend.compressed_allreduce`` and the
cupy/MPI variant ``mpi.py:131``).

The algorithm (NeurIPS'21 1-bit Adam) in mesh-collective form, run inside
``shard_map`` over the dp axis:

1. worker compensates its local tensor with its error feedback, compresses
   to (sign, per-worker scale), and updates the worker error
2. each rank acts as "server" for its 1/n chunk: the sign*scale averages
   arrive via a reduce-scatter, get compensated with the server error and
   re-compressed to (sign, per-chunk scale)
3. the twice-compressed chunks are all-gathered — every rank ends with the
   same full tensor

The wire math (what gets reduced/gathered is exactly the ±scale tensors) is
identical to the reference; on TPU the collectives ride ICI. Both error
states are carried functionally (returned, not mutated).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _sign_scale(x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Compress to sign(x) * mean(|x|) (the reference's scaled-sign:
    nccl.py:70-90). Returns (compressed, scale)."""
    scale = jnp.mean(jnp.abs(x))
    signs = jnp.where(x >= 0, 1.0, -1.0).astype(x.dtype)
    return signs * scale, scale


def compressed_allreduce(tensor, worker_error, server_error, axis: str = "dp"):
    """Per-shard body (inside shard_map over ``axis``).

    tensor: LOCAL flat [numel] fp32 (this worker's unsynced value, e.g. its
    momentum update); worker_error/server_error: error-feedback states
    ([numel] and [numel / n]). Returns (averaged tensor, new_worker_error,
    new_server_error).
    """
    n = jax.lax.axis_size(axis)
    numel = tensor.shape[0]
    if numel % n != 0:
        raise ValueError(f"compressed_allreduce needs numel ({numel}) divisible by group ({n})")

    # 1. worker compression with error feedback
    compensated = tensor + worker_error
    compressed, _ = _sign_scale(compensated)
    new_worker_error = compensated - compressed

    # 2. server stage: average my chunk across workers (reduce-scatter ≙ the
    # reference's igather + local mean), compensate, re-compress
    chunk = jax.lax.psum_scatter(compressed, axis, scatter_dimension=0, tiled=True) / n
    server_comp = chunk + server_error
    server_compressed, _ = _sign_scale(server_comp)
    new_server_error = server_comp - server_compressed

    # 3. allgather the twice-compressed chunks
    out = jax.lax.all_gather(server_compressed, axis, axis=0, tiled=True)
    return out, new_worker_error, new_server_error


class CompressedBackend:
    """Object surface mirroring the reference backend classes."""

    def __init__(self, axis: str = "dp"):
        self.axis = axis

    def compressed_allreduce(self, tensor, worker_error, server_error, local_rank=None):
        return compressed_allreduce(tensor, worker_error, server_error, self.axis)
