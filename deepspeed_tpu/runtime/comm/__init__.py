"""Compressed communication backends (reference ``deepspeed/runtime/comm/``)."""

from deepspeed_tpu.runtime.comm.compressed import CompressedBackend, compressed_allreduce
