"""Core async NVMe tensor swapper.

Reference parity: ``deepspeed/runtime/swap_tensor/async_swapper.py``
(``AsyncTensorSwapper``) + the aligned pinned-buffer management from
``partitioned_param_swapper.py:371`` — a keyed store of host tensors streamed
to/from fast local storage through the native aio engine, with a reusable
pool of aligned buffers so steady-state swapping allocates nothing.
"""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle, aligned_array, padded_numel
from deepspeed_tpu.utils.logging import logger


class AsyncTensorSwapper:
    """Swap named host tensors out to ``swap_dir`` and back, asynchronously.

    ``swap_out``/``swap_in`` submit I/O on the native thread pool;
    :meth:`wait` (or any sync_ variant) barriers. Buffers are aligned and
    padded so transfers ride O_DIRECT.
    """

    def __init__(self, swap_dir: str, aio_handle: Optional[AsyncIOHandle] = None,
                 block_size: int = 1 << 20, thread_count: int = 8):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = aio_handle or AsyncIOHandle(block_size=block_size, thread_count=thread_count)
        # key -> (numel, dtype_str)
        self._meta: Dict[str, Tuple[int, str]] = {}
        # free aligned buffers by (padded_numel, dtype_str)
        self._pool: Dict[Tuple[int, str], list] = defaultdict(list)
        # buffers pinned until the inflight I/O that uses them completes
        self._inflight_buffers: list = []
        self.swap_out_bytes = 0
        self.swap_in_bytes = 0

    def _path(self, key: str) -> str:
        return os.path.join(self.swap_dir, f"{key}.swp")

    def _get_buffer(self, numel: int, dtype) -> np.ndarray:
        dtype = np.dtype(dtype)
        pkey = (padded_numel(numel, dtype), dtype.str)
        if self._pool[pkey]:
            return self._pool[pkey].pop()
        return aligned_array(numel, dtype)

    def release_buffer(self, buf: np.ndarray) -> None:
        self._pool[(buf.size, buf.dtype.str)].append(buf)

    # ------------------------------------------------------------------ #
    def swap_out(self, key: str, tensor: np.ndarray, async_op: bool = False) -> None:
        """Write ``tensor`` to storage under ``key``. The data is staged into
        an aligned buffer, so ``tensor`` may be reused immediately."""
        dtype = tensor.dtype
        numel = tensor.size
        buf = self._get_buffer(numel, dtype)
        buf[:numel] = tensor.ravel()
        self._meta[key] = (numel, dtype.str)
        self.aio.async_pwrite(buf, self._path(key))
        self.swap_out_bytes += buf.nbytes
        self._inflight_buffers.append(buf)
        if not async_op:
            self.wait()

    def swap_in(self, key: str, out: Optional[np.ndarray] = None,
                async_op: bool = False) -> np.ndarray:
        """Read ``key`` back. Returns the (padded) aligned buffer; the logical
        tensor is ``result[:numel]``. With ``async_op`` the caller must
        :meth:`wait` before touching the data."""
        if key not in self._meta:
            raise KeyError(f"no swapped tensor under key '{key}'")
        numel, dtype_str = self._meta[key]
        buf = out if out is not None else self._get_buffer(numel, np.dtype(dtype_str))
        self.aio.async_pread(buf, self._path(key))
        self.swap_in_bytes += buf.nbytes
        if not async_op:
            self.wait()
        return buf

    def write_back(self, key: str, buf: np.ndarray, async_op: bool = True) -> None:
        """Write an (aligned, previously swapped-in) buffer back under its key
        without re-staging; the buffer is pooled once the write completes."""
        if key not in self._meta:
            raise KeyError(f"no swapped tensor under key '{key}'")
        self.aio.async_pwrite(buf, self._path(key))
        self.swap_out_bytes += buf.nbytes
        self._inflight_buffers.append(buf)
        if not async_op:
            self.wait()

    def numel(self, key: str) -> int:
        return self._meta[key][0]

    def contains(self, key: str) -> bool:
        return key in self._meta

    def wait(self) -> None:
        self.aio.wait()
        # staged swap-out buffers can now be pooled for reuse
        for buf in self._inflight_buffers:
            self.release_buffer(buf)
        self._inflight_buffers.clear()

    def remove(self, key: str) -> None:
        meta = self._meta.pop(key, None)
        if meta is not None:
            try:
                os.unlink(self._path(key))
            except OSError:  # pragma: no cover
                logger.warning(f"could not remove swap file for {key}")
