"""NVMe swapping of parameter partitions (ZeRO-Infinity param offload).

Reference parity: ``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:35``
(``AsyncPartitionedParameterSwapper``) — bf16 parameter partitions stream
between NVMe and host staging buffers; prefetch hides read latency behind
compute on the layers still resident.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper


class AsyncPartitionedParameterSwapper:
    def __init__(self, swap_dir: str, aio_config: Optional[dict] = None):
        aio_config = aio_config or {}
        self.swapper = AsyncTensorSwapper(
            swap_dir,
            block_size=aio_config.get("block_size", 1 << 20),
            thread_count=aio_config.get("thread_count", 8),
        )
        self._available: Dict[str, np.ndarray] = {}   # key -> padded host buffer
        self._prefetching: Dict[str, np.ndarray] = {}

    # ------------------------------------------------------------------ #
    def swap_out_and_release(self, key: str, tensor: np.ndarray) -> None:
        """Persist a param partition to NVMe and drop its host buffer."""
        self.swapper.swap_out(key, tensor, async_op=True)
        self._available.pop(key, None)

    def prefetch(self, key: str) -> None:
        """Kick off an async read; :meth:`get` will pick it up."""
        if key in self._available or key in self._prefetching:
            return
        self._prefetching[key] = self.swapper.swap_in(key, async_op=True)

    def get(self, key: str) -> np.ndarray:
        """Return the logical tensor for ``key``, waiting on (or issuing) its
        read as needed."""
        if key not in self._available:
            if key not in self._prefetching:
                self.prefetch(key)
            self.swapper.wait()
            for k, buf in self._prefetching.items():
                self._available[k] = buf
            self._prefetching.clear()
        return self._available[key][:self.swapper.numel(key)]

    def release(self, key: str) -> None:
        buf = self._available.pop(key, None)
        if buf is not None:
            self.swapper.release_buffer(buf)

    def available_keys(self) -> List[str]:
        return sorted(self._available)

    def wait(self) -> None:
        self.swapper.wait()
        for k, buf in self._prefetching.items():
            self._available[k] = buf
        self._prefetching.clear()
