"""NVMe swapping of optimizer state around the host optimizer step.

Reference parity: ``deepspeed/runtime/swap_tensor/optimizer_utils.py:96``
(``OptimizerSwapper``), ``partitioned_optimizer_swapper.py`` and the
double-buffered ``pipelined_optimizer_swapper.py`` — fp32 master params and
Adam moments live on NVMe; each sub-group is swapped in, stepped with the
native cpu_adam, and swapped back out, with the next sub-group's read
overlapped behind the current step (the reference's pipelined variant).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper


class PartitionedOptimizerSwapper:
    """Keeps per-partition optimizer tensors (fp32 master + states) on NVMe.

    ``step_all`` drives the swap-in → host-step → swap-out pipeline over every
    registered partition with one partition of read-ahead.
    """

    def __init__(self, swap_dir: str, aio_config: Optional[dict] = None,
                 state_keys=("master", "exp_avg", "exp_avg_sq")):
        aio_config = aio_config or {}
        self.STATE_KEYS = tuple(state_keys)
        self.swapper = AsyncTensorSwapper(
            swap_dir,
            block_size=aio_config.get("block_size", 1 << 20),
            thread_count=aio_config.get("thread_count", 8),
        )
        self._numels: Dict[str, int] = {}

    # ------------------------------------------------------------------ #
    def register_partition(self, key: str, master_fp32: np.ndarray) -> None:
        """Initial placement: write master weights + zero moments to NVMe."""
        n = master_fp32.size
        self._numels[key] = n
        self.swapper.swap_out(f"{key}.master", master_fp32.astype(np.float32, copy=False),
                              async_op=True)
        zeros = np.zeros(n, np.float32)
        for s in self.STATE_KEYS:
            if s != "master":
                self.swapper.swap_out(f"{key}.{s}", zeros, async_op=True)
        self.swapper.wait()

    def partitions(self) -> List[str]:
        return sorted(self._numels)

    def _swap_in_states(self, key: str, async_op: bool) -> Dict[str, np.ndarray]:
        return {s: self.swapper.swap_in(f"{key}.{s}", async_op=async_op)
                for s in self.STATE_KEYS}

    def step_all(self, step_fn: Callable[[str, int, Dict[str, np.ndarray]], None]) -> None:
        """``step_fn(key, numel, states)`` updates ``states`` in place; states
        are padded aligned buffers, logical data is ``states[s][:numel]``.
        Reads for partition i+1 overlap the step of partition i."""
        keys = self.partitions()
        if not keys:
            return
        current = self._swap_in_states(keys[0], async_op=False)
        for i, key in enumerate(keys):
            nxt = None
            if i + 1 < len(keys):
                nxt = self._swap_in_states(keys[i + 1], async_op=True)
            step_fn(key, self._numels[key], current)
            # write back the updated states; the barrier also completes the
            # prefetched reads for the next partition
            for s in self.STATE_KEYS:
                self.swapper.write_back(f"{key}.{s}", current[s])
            self.swapper.wait()
            if nxt is not None:
                current = nxt

    def read_state(self, key: str, state: str = "master") -> np.ndarray:
        buf = self.swapper.swap_in(f"{key}.{state}")
        out = buf[:self._numels[key]].copy()
        self.swapper.release_buffer(buf)
        return out

    def write_state(self, key: str, state: str, value: np.ndarray) -> None:
        self.swapper.swap_out(f"{key}.{state}", np.ascontiguousarray(value, np.float32))

    def read_master(self, key: str) -> np.ndarray:
        return self.read_state(key, "master")
