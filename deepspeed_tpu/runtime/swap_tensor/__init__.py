from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper
from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import PartitionedOptimizerSwapper
from deepspeed_tpu.runtime.swap_tensor.partitioned_param_swapper import AsyncPartitionedParameterSwapper

__all__ = ["AsyncTensorSwapper", "PartitionedOptimizerSwapper", "AsyncPartitionedParameterSwapper"]
