"""Quantize-on-load for inference checkpoints.

Reference parity: ``deepspeed/runtime/weight_quantizer.py``
(``WeightQuantization`` — int8-quantizes the attention/MLP weights as
checkpoints load for inference, with EXTRA grouping for the 4×-sized MLP
matrices) consumed by the ``SDLoaderFactory`` loaders' ``quantize`` flags.

TPU design: quantization happens AFTER name-mapping, on the zoo-layout
param tree (``[in, out]`` / stacked ``[L, in, out]``) — quantizing the raw
torch-layout state dict would group scales along the wrong axis once the
ingestion transpose runs. Weights become
:class:`deepspeed_tpu.ops.quant.Quantized8` nodes (int8 payload + per-group
f32 scales) that dequantize fused into the consuming matmul.
"""

from __future__ import annotations

from typing import Any, Dict

from deepspeed_tpu.ops.quant import quantize_int8

# zoo matmul leaves (under "layers"), mirroring ops.quant._QUANTIZABLE
_ATTN_KEYS = ("wq", "wk", "wv", "wo")
_MLP_KEYS = ("w_gate", "w_up", "w_down", "res_w_up", "res_w_down")


class WeightQuantization:
    """Quantize a zoo param tree's matmul weights on load.

    ``mlp_extra_grouping`` doubles the group count for the MLP matrices
    (they are ~4× larger than the attention projections, so finer scales
    cost the same relative overhead — reference ``is_mlp`` heuristic,
    keyed here by the tree position instead of shape-ratio guessing,
    which misfires on TP shards).
    """

    def __init__(self, mlp_extra_grouping: bool = True):
        self.mlp_extra_grouping = mlp_extra_grouping

    def quantize_params(self, params: Dict[str, Any], quantize_bits: int = 8,
                        groups: int = 64,
                        include_head: bool = False) -> Dict[str, Any]:
        if quantize_bits != 8:
            raise NotImplementedError(
                f"quantize-on-load supports 8 bits (got {quantize_bits}); "
                "use runtime.quantize (MoQ) or compression for other widths")

        def walk(tree, under_layers):
            if not isinstance(tree, dict):
                return tree
            out = {}
            for k, v in tree.items():
                if under_layers and not isinstance(v, dict) and \
                        k in _ATTN_KEYS + _MLP_KEYS:
                    g = groups * 2 if (self.mlp_extra_grouping
                                       and k in _MLP_KEYS) else groups
                    out[k] = quantize_int8(v, g)
                else:
                    out[k] = walk(v, under_layers or k == "layers")
            return out

        out = walk(params, False)
        if include_head and "lm_head" in out:
            out["lm_head"] = quantize_int8(out["lm_head"], groups)
        return out
