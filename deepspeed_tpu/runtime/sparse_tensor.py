"""Row-sparse gradients and their DP reduction.

Reference parity: ``deepspeed/runtime/sparse_tensor.py:10`` (``SparseTensor``
wrapping torch sparse embedding grads) + the engine's sparse allreduce
(``runtime/engine.py:2302-2372`` — all_gather of indices/values across DP
instead of a dense-vocab allreduce).

TPU design: embedding grads under jit are dense, but for a huge vocab only
the rows of the batch's tokens are nonzero. ``SparseTensor`` is a pytree of
``(indices [nnz], values [nnz, row], dense_shape)`` with STATIC nnz (the
token count of the batch — jit-friendly; duplicates are allowed and
scatter-ADD on densify, exactly like torch's uncoalesced sparse tensors).
``sparse_all_reduce`` gathers indices+values over the dp axis — wire cost
``O(world · nnz · row)`` instead of ``O(vocab · row)``, the same trade the
reference makes.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class SparseTensor:
    """Row-sparse 2-D tensor: ``dense[indices[i]] += values[i]``."""
    indices: jax.Array                    # [nnz] int32 row ids (dup ok)
    values: jax.Array                     # [nnz, row_dim]
    dense_shape: Tuple[int, int] = dataclasses.field(metadata=dict(static=True))

    @staticmethod
    def from_embedding_grad(token_ids, row_grads, vocab_size: int) -> "SparseTensor":
        """Batch tokens ``[N]`` + their grad rows ``[N, D]`` → sparse grad of
        the ``[vocab, D]`` embedding (reference: torch sparse grads from
        ``nn.Embedding(sparse=True)``)."""
        token_ids = token_ids.reshape(-1).astype(jnp.int32)
        row_grads = row_grads.reshape(token_ids.shape[0], -1)
        return SparseTensor(token_ids, row_grads,
                            (vocab_size, row_grads.shape[1]))

    def to_dense(self) -> jax.Array:
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def to_coo_tensor(self):
        """Reference-named alias (``sparse_tensor.py`` ``to_coo_tensor``)."""
        return self.indices, self.values, self.dense_shape

    @property
    def nnz(self) -> int:
        return self.indices.shape[0]


def sparse_all_reduce(st: SparseTensor, axis: str, average: bool = True) -> SparseTensor:
    """DP reduction of a row-sparse grad INSIDE ``shard_map`` over ``axis``:
    all ranks gather each other's (indices, values) — the result is the
    (uncoalesced) sum of every rank's contribution. Wire volume is
    ``world · nnz · row`` versus ``vocab · row`` for a dense allreduce —
    the reference's sparse_allreduce_bucket trade (``engine.py:2302``).
    """
    idx = jax.lax.all_gather(st.indices, axis, tiled=True)
    vals = jax.lax.all_gather(st.values, axis, tiled=True)
    if average:
        world = jax.lax.psum(1, axis)
        vals = vals / world
    return SparseTensor(idx, vals, st.dense_shape)
