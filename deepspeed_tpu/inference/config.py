"""Inference config.

Reference parity: ``deepspeed/inference/config.py`` — ``DeepSpeedInferenceConfig``
(dtype, tensor-parallel degree, MoE, quantization, max_out_tokens,
kernel-injection toggles) plus the quantization sub-configs.

TPU mapping: ``replace_with_kernel_inject`` swaps HF/flax layers for the
fused Pallas inference blocks; ``enable_cuda_graph`` has no TPU analogue —
``jax.jit`` + donated KV-cache buffers already gives a captured graph — so it
is accepted and ignored (warn once).
"""

from __future__ import annotations

from enum import Enum
from typing import Any, Dict, Optional, Union

from pydantic import Field

from deepspeed_tpu.config.config_utils import ConfigModel
from deepspeed_tpu.monitor.config import TelemetryConfig
from deepspeed_tpu.utils.logging import warn_once


class DtypeEnum(str, Enum):
    fp32 = "fp32"
    fp16 = "fp16"
    bf16 = "bf16"
    int8 = "int8"

    @classmethod
    def from_any(cls, value) -> "DtypeEnum":
        if isinstance(value, cls):
            return value
        aliases = {
            "float32": "fp32", "float": "fp32", "fp32": "fp32",
            "float16": "fp16", "half": "fp16", "fp16": "fp16",
            "bfloat16": "bf16", "bf16": "bf16",
            "int8": "int8",
        }
        name = str(value).replace("torch.", "").replace("jnp.", "")
        if name not in aliases:
            raise ValueError(f"Unsupported dtype: {value}")
        return cls(aliases[name])

    @property
    def jnp(self):
        import jax.numpy as jnp
        return {
            DtypeEnum.fp32: jnp.float32,
            DtypeEnum.fp16: jnp.float16,
            DtypeEnum.bf16: jnp.bfloat16,
            DtypeEnum.int8: jnp.int8,
        }[self]


class MoETypeEnum(str, Enum):
    residual = "residual"
    standard = "standard"


class DeepSpeedTPConfig(ConfigModel):
    """Tensor-parallel config ("tensor_parallel" section)."""
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(ConfigModel):
    """MoE inference config ("moe" section)."""
    enabled: bool = True
    ep_size: int = 1
    moe_experts: list = Field([1], alias="num_experts")
    type: MoETypeEnum = MoETypeEnum.standard
    ep_mp_group: Optional[Any] = None
    ep_group: Optional[Any] = None


class QuantTypeEnum(str, Enum):
    asym = "asymmetric"
    sym = "symmetric"


class BaseQuantConfig(ConfigModel):
    enabled: bool = True
    num_bits: int = 8
    q_type: QuantTypeEnum = QuantTypeEnum.sym
    q_groups: int = 1


class WeightQuantConfig(BaseQuantConfig):
    enabled: bool = True
    quantized_initialization: Dict = {}
    post_init_quant: Dict = {}


class ActivationQuantConfig(BaseQuantConfig):
    enabled: bool = True


class QKVQuantConfig(ConfigModel):
    enabled: bool = True


class QuantizationConfig(ConfigModel):
    enabled: bool = True
    activation: ActivationQuantConfig = Field(default_factory=ActivationQuantConfig)
    weight: WeightQuantConfig = Field(default_factory=WeightQuantConfig)
    qkv: QKVQuantConfig = Field(default_factory=QKVQuantConfig)


class SpeculativeConfig(ConfigModel):
    """Speculative decoding ("serving.speculative" sub-section).

    ``mode="ngram"`` turns on draft-free self-speculation on the paged
    path: a host-side n-gram proposer (``inference/spec.py``) matches the
    tail of each request's prompt + generated tokens against earlier
    occurrences and proposes up to ``k`` continuation tokens, which one
    fused verify step (``forward_paged_verify``) checks at all ``k + 1``
    positions at once — greedy argmax acceptance keeps speculation
    token-identical to plain greedy decode while emitting (accepted + 1)
    tokens per fused step. Requests with no match fall back to
    single-token decode; sampled generation (``temperature > 0``)
    disables speculation for the call (acceptance is argmax-exact).
    ``mode="auto"`` is RESERVED for a future draft-model speculator and
    resolves to "off" today.
    """
    mode: str = "off"       # off | ngram | auto (auto reserved: off today)
    k: int = 4              # max candidate tokens proposed per request/step
    min_match: int = 2      # shortest tail n-gram the proposer may match
    max_match: int = 4      # longest tail n-gram tried (longest first)


class KvHostConfig(ConfigModel):
    """Tiered KV cache ("serving.kv_host" sub-section).

    ``enabled=True`` attaches a host-memory tier
    (``inference/kv_host_pool.py``) behind the paged block allocator:
    instead of destroying a cold prefix-cache block under allocation
    pressure, the allocator *demotes* it — an async D2H copy of the
    block's ``[L, bs, KV, Hd]`` k/v slices keyed by its blake2b hash
    chain — and a later admission whose prefix walks onto a demoted
    chain re-materializes it H2D into fresh device blocks instead of
    recomputing the prefill. Host RAM is ~10x HBM, so effective cache
    capacity (hence hit rate and TTFT at scale) grows accordingly.
    Requires prefix caching on the paged path; greedy token identity is
    unchanged (a fetched block is a bit-identical copy of what recompute
    would produce).

    ``max_host_blocks`` bounds the tier with its own LRU; 0 = auto (4x
    the device pool's allocatable blocks). ``spill="off"`` keeps the
    tier read-only — existing demoted chains still serve hits, but
    reclaim destroys (no new demotions). Injected D2H/H2D faults
    (``utils/fault_injection``) degrade to destroy-on-reclaim with a
    warning and the ``serving/kv_host_errors`` counter; the serving
    loop never wedges.
    """
    enabled: bool = False
    max_host_blocks: int = 0   # 0 = auto: 4x device pool capacity
    spill: str = "auto"        # auto | off (off = fetch-only, no demotion)


class ReplicaConfig(ConfigModel):
    """Replica scale-out ("serving.replicas" sub-section) — the ``dp``
    serving axis.

    ``dp`` > 1 stands up N engine replicas (one shared weight pytree,
    one shared host KV tier) behind the deterministic
    :class:`~deepspeed_tpu.inference.router.ReplicaRouter`: session-
    affinity hashing pins multi-turn traffic onto the replica holding
    its prefix cache, fresh sessions take a queue-depth/burn-rate-aware
    least-loaded tiebreak, and a replica tripping its crash-loop breaker
    drains in flight to siblings token-identically. ``roles`` tags each
    replica ``any`` | ``prefill`` | ``decode``; any ``prefill`` entry
    enables disaggregated prefill/decode — the prefill replica commits
    prompt blocks and ships them through the content-addressed
    ``KvHostPool`` (the host tier is the KV transport), the decode
    replica re-materializes them H2D instead of re-prefilling.
    ``affinity="off"`` disables session hashing; ``handoff="off"``
    disables the disaggregated path while keeping the role tags for
    routing. Prefer more replicas when throughput-bound with a model
    that fits one slice; prefer larger ``tp`` when the model (or its KV
    working set) does not fit."""
    dp: int = 1                 # serving replicas behind the router
    roles: list = Field(default_factory=list)   # per-replica role tags,
    # padded with "any"; any "prefill" entry enables the handoff path
    affinity: str = "session"   # session | off — session-key hashing
    handoff: str = "auto"       # auto | off — disaggregated prefill path


class ServingFaultConfig(ConfigModel):
    """Serving-plane fault tolerance ("serving.fault" sub-section).

    Governs how the always-on loop (``inference/serve.py``) contains
    engine-step failures — the serving mirror of the training side's
    crash-safe checkpointing:

    - a **per-request** fault (raised before the step's donated pools were
      consumed — e.g. a poison request crashing host-side prep, an injected
      ``fail_step(phase="pre")``) re-queues the faulting action's
      request(s) through the recompute-preemption machinery with
      exponential backoff in LOGICAL scheduler steps
      (``retry_backoff_steps * 2**(retry-1)``); after
      ``max_request_retries`` retries the request **quarantines** — retired
      with ``req.error`` while the loop keeps serving everyone else;
    - an **engine-fatal** fault (anything that died with the donated pools
      already consumed mid-step) triggers a crash-safe engine restart: the
      pool workspace, allocator and fused-step jits are rebuilt and every
      in-flight request is re-admitted from prompt + generated tokens —
      exactly the recovery path recompute-preemption already proves
      correct — at most ``max_engine_restarts`` times (each preceded by
      ``restart_backoff_s * 2**(restart-1)`` of wall backoff); exhausted,
      the **crash-loop breaker** opens: in-flight requests fail, the loop
      parks, ``/healthz`` reads 503, and ``drain()``/``shutdown()`` still
      work;
    - ``shed_queue_depth`` > 0 turns on **load shedding**: whenever the
      waiting queue exceeds the bound the loop sheds the scheduling
      policy's ``select_shed_victim`` picks (lowest priority first, newest
      arrival on ties — deterministic) until it fits, retiring each as
      ``shed`` (HTTP 429).

    Containment is deterministic given a request trace + injection
    schedule; every decision emits flight-recorder events (``serve.fault``
    / ``serve.restart`` / ``req.requeue`` / ``req.timeout`` / ``req.shed``)
    and counts into ``serving/step_faults{kind=}``,
    ``serving/engine_restarts``, ``serving/request_retries``,
    ``serving/timeouts`` and ``serving/shed_requests``.
    """
    max_request_retries: int = 3   # retries before a request quarantines
    retry_backoff_steps: int = 2   # logical-step backoff base (x2 per retry)
    max_engine_restarts: int = 2   # engine rebuilds before the breaker opens
    restart_backoff_s: float = 0.0  # wall backoff base between restarts
    shed_queue_depth: int = 0      # shed waiting requests above this (0=off)


class ServingConfig(ConfigModel):
    """Continuous-batching serving config ("serving" section).

    Governs ``InferenceEngine.generate_batch``: the paged KV cache (block
    pools + per-request block tables) and the iteration-level scheduler.
    ``paged="auto"`` uses the paged path whenever the model supports it
    (zoo causal LMs with a paged forward; weight-streaming and MoE engines
    fall back), ``"on"`` requires it (loud error otherwise), ``"off"``
    serves each request through the static ``generate`` path sequentially.

    ``prefix_caching`` enables vLLM-style automatic prefix caching: full
    KV blocks are content-addressed by a rolling hash chain and shared
    across requests (and across ``generate_batch`` calls) with ref-count
    bumps — a request whose prompt starts with a cached prefix skips that
    prefill compute entirely. ``auto`` = on wherever the paged path is
    active; ``off`` restores the one-owner-per-block behavior.

    ``prefill_chunk_tokens`` > 0 splits prefill into chunks of at most
    that many tokens (compile buckets are 128-aligned, so keep it a
    multiple of 128) and interleaves one chunk with each fused decode
    step — running decodes keep making progress instead of stalling for a
    whole long prompt. 0 = whole-prompt prefill (the default).

    Several serving knobs — the prefill chunk size, speculative ``k``,
    the policy's ``admission_*`` bounds, the shed depth, and host-tier
    spill — double as the adaptive controller's actuation surface
    (``monitor/controller.py``, ``dscli serve --adaptive``): their config
    values are the BASELINE the controller tightens away from under SLO
    burn and steps back to under sustained headroom. Pin one static with
    ``telemetry.ctl.knobs.<name>: off``.

    ``speculative`` configures n-gram self-speculation (verified
    multi-token decode steps) — see :class:`SpeculativeConfig`.

    ``policy`` selects the scheduling policy for the serving loop
    (``inference/policy.py``): ``"fifo"`` (default — the pinned behavior
    every release has had), ``"priority"`` (strict priority classes on
    each request's ``priority``), or ``"sla"`` (TTFT-slack-aware
    admission and preemption). A dict form passes constructor kwargs,
    e.g. ``{"name": "sla", "default_ttft_budget": 64,
    "admission_max_queue": 128, "admission_min_free_blocks": 2}`` — the
    ``admission_*`` knobs are the async front-end's admission control
    (submissions refused under queue/pool pressure instead of queueing
    unboundedly). All policies are deterministic given a request trace.

    ``tp`` > 0 shards the serving engine over a ``tp`` mesh axis (tensor
    parallelism): model params lay out column/row-sharded (the model's
    ``tp_specs`` or the ``auto_tp`` heuristics) and the KV block pools
    split on the KV-head dim, so one model spans ``tp`` chips and pool
    bytes per chip drop to 1/tp. Block tables, the allocator and the
    scheduler stay replicated — per-shard block indices are identical.
    0 (the default) follows ``tensor_parallel.tp_size``; setting both to
    different values is a loud error. KV heads that don't divide ``tp``
    replicate the pools (rate-limited warning, never a crash).
    """
    block_size: int = 128          # tokens per KV block (128 = kernel path;
    # smaller blocks pack tighter but decode through the gather fallback)
    max_num_blocks: int = 0        # pool blocks per layer; 0 = auto-size so
    # max_running requests can reach the model's max_seq (no eviction)
    max_running: int = 8           # fused-decode width / running request cap
    paged: str = "auto"            # auto | on | off
    tp: int = 0                    # serving tensor-parallel degree; 0 =
    # follow tensor_parallel.tp_size
    prefix_caching: str = "auto"   # auto | on | off (auto = on when paged)
    prefill_chunk_tokens: int = 0  # 0 = whole-prompt; else chunk size
    kv_host: KvHostConfig = Field(default_factory=KvHostConfig)
    # tiered KV cache: spill cold prefix-cache blocks to a host-RAM pool
    # (see KvHostConfig)
    replicas: ReplicaConfig = Field(default_factory=ReplicaConfig)
    # dp serving axis: N replicas behind the deterministic affinity
    # router, optional prefill/decode role split (see ReplicaConfig)
    speculative: SpeculativeConfig = Field(
        default_factory=SpeculativeConfig)
    fault: ServingFaultConfig = Field(default_factory=ServingFaultConfig)
    # serving-plane fault tolerance: step-fault containment, crash-safe
    # engine restarts, load shedding (see ServingFaultConfig)
    policy: Union[str, Dict[str, Any]] = "fifo"   # fifo | priority | sla,
    # or {"name": ..., **kwargs} (see inference/policy.py); the serving
    # loop's scheduling policy — generate_batch always runs FIFO


class InferenceCheckpointConfig(ConfigModel):
    checkpoint_dir: Optional[str] = None
    save_mp_checkpoint_path: Optional[str] = None
    base_dir: Optional[str] = None


class DeepSpeedInferenceConfig(ConfigModel):
    """Master inference config (``deepspeed_tpu.init_inference`` kwarg set)."""

    replace_with_kernel_inject: bool = Field(False, alias="kernel_inject")
    dtype: DtypeEnum = DtypeEnum.fp16
    tensor_parallel: DeepSpeedTPConfig = Field(default_factory=DeepSpeedTPConfig, alias="tp")
    enable_cuda_graph: bool = False  # accepted for parity; jit is the TPU analogue
    zero: Dict = {}
    triangular_masking: bool = Field(True, alias="tm")
    moe: Union[bool, DeepSpeedMoEConfig] = Field(default_factory=DeepSpeedMoEConfig)
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    checkpoint: Optional[Union[str, Dict]] = None
    base_dir: str = ""
    set_empty_params: bool = False
    save_mp_checkpoint_path: Optional[str] = None
    checkpoint_config: InferenceCheckpointConfig = Field(default_factory=InferenceCheckpointConfig, alias="ckpt_config")
    serving: ServingConfig = Field(default_factory=ServingConfig)
    # serving telemetry (TTFT/TPOT histograms, queue depth, KV utilization,
    # preemption counters + the compile watchdog); accepts a dict, a bool,
    # or "on"/"off" like the training config's section
    telemetry: TelemetryConfig = Field(default_factory=TelemetryConfig)
    return_tuple: bool = True
    training_mp_size: int = 1
    replace_method: str = Field("auto", json_schema_extra={"deprecated": True})
    injection_policy: Optional[Dict] = Field(None, alias="injection_dict")
    injection_policy_tuple: Optional[tuple] = None
    config: Optional[Dict] = Field(None, alias="args")
    max_out_tokens: int = Field(1024, alias="max_tokens")
    min_out_tokens: int = Field(1, alias="min_tokens")
    transposed_mode: bool = Field(False, alias="transposed_mode")
    mp_size: int = Field(1, json_schema_extra={"deprecated": True, "new_param": "tensor_parallel.tp_size"})
    mpu: Optional[Any] = Field(None, json_schema_extra={"deprecated": True, "new_param": "tensor_parallel.mpu"})
    ep_size: int = Field(1, json_schema_extra={"deprecated": True, "new_param": "moe.ep_size"})
    ep_group: Optional[Any] = Field(None, alias="expert_group",
                                    json_schema_extra={"deprecated": True, "new_param": "moe.ep_group"})
    ep_mp_group: Optional[Any] = Field(None, alias="expert_mp_group",
                                       json_schema_extra={"deprecated": True, "new_param": "moe.ep_mp_group"})
    moe_experts: list = Field([1], json_schema_extra={"deprecated": True, "new_param": "moe.moe_experts"})
    moe_type: MoETypeEnum = Field(MoETypeEnum.standard,
                                  json_schema_extra={"deprecated": True, "new_param": "moe.type"})

    def __init__(self, **data):
        if data.get("enable_cuda_graph"):
            warn_once("enable_cuda_graph has no TPU analogue; jax.jit already captures the graph. Ignoring.")
        if "dtype" in data and data["dtype"] is not None:
            data["dtype"] = DtypeEnum.from_any(data["dtype"])
        if "telemetry" in data and not isinstance(data["telemetry"],
                                                  TelemetryConfig):
            # dicts too: the sub-blocks (health/events) accept bool and
            # "on"/"off" shorthands only get_telemetry_config understands
            from deepspeed_tpu.monitor.config import get_telemetry_config
            data["telemetry"] = get_telemetry_config(
                {"telemetry": data["telemetry"]})
        super().__init__(**data)
