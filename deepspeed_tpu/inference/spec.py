"""Draft-free self-speculation for the paged serving engine: n-gram
prompt/generation lookup.

Speculative decoding (Leviathan et al., "Fast Inference from Transformers
via Speculative Decoding") amortises the per-step dispatch + kernel cost
of autoregressive decode: a cheap *proposer* guesses the next ``k``
tokens, one fused **verify** step computes the model's logits at all
``k + 1`` positions (``models/transformer.py::forward_paged_verify``),
and greedy acceptance keeps the longest candidate prefix the model agrees
with plus one free token from the first mismatch — by construction
token-identical to plain greedy decode, at (accepted + 1) tokens per
fused step instead of 1.

This module is the draft-FREE proposer (prompt-lookup / lookahead
n-gram family): the candidate continuation is read straight out of the
request's own prompt + generated history. No draft model, no extra
weights, no device work — a pure host-side tail match. It is strong
exactly where the serving engine already wins: repetitive and
shared-prefix workloads (templated prompts, extraction/summarisation
over quoted context, greedy loops) where the continuation has literally
been seen before. On non-repetitive text it simply finds no match and
the scheduler falls back to single-token decode per request — speculation
never changes tokens, only step count.

``serving.speculative: {mode: "ngram", k, min_match}`` turns it on
(``inference/config.py``); the scheduler owns one proposer per serve
call and stashes candidates on each request before a ``("verify", reqs)``
action (``inference/scheduler.py``).
"""

from __future__ import annotations

import numpy as np


class NgramProposer:
    """Tail n-gram lookup over a request's token history.

    ``propose(seq, k)`` matches the LONGEST tail n-gram of ``seq`` (from
    ``max_match`` down to ``min_match`` tokens) against its most recent
    earlier occurrence in ``seq`` and returns up to ``k`` tokens that
    followed that occurrence — the speculated continuation. Empty when no
    tail n-gram repeats (the caller decodes one token as usual).

    Determinism: a pure function of the token sequence — longest match
    first, most recent occurrence on ties — so identical request streams
    speculate identically (the scheduler's determinism pin extends to
    speculation). Matching is O(len(seq) x max_match) numpy per call; the
    sequences the paged engine serves are bounded by ``max_seq``, so this
    stays noise next to a fused decode step.
    """

    def __init__(self, min_match: int = 2, max_match: int = 4):
        if min_match < 1:
            raise ValueError(f"min_match={min_match} must be >= 1")
        if max_match < min_match:
            raise ValueError(f"max_match={max_match} must be >= "
                             f"min_match={min_match}")
        self.min_match = min_match
        self.max_match = max_match

    def propose(self, seq, k: int) -> np.ndarray:
        """Up to ``k`` candidate continuation tokens for ``seq`` (1-D
        int32, the request's prompt + generated history). [] when ``k``
        < 1, the sequence is too short, or no tail n-gram recurs."""
        empty = np.zeros((0,), np.int32)
        if k < 1:
            return empty
        seq = np.asarray(seq, np.int32).reshape(-1)
        L = seq.size
        for n in range(min(self.max_match, L - 1), self.min_match - 1, -1):
            tail = seq[L - n:]
            # windows over seq[:-1]: starts 0..L-1-n, so the tail's own
            # occurrence (start L-n) is excluded — overlapping earlier
            # matches stay in (that's what extends periodic text)
            windows = np.lib.stride_tricks.sliding_window_view(seq[:L - 1], n)
            hits = np.nonzero((windows == tail).all(axis=1))[0]
            if hits.size == 0:
                continue
            start = int(hits[-1]) + n      # most recent occurrence's end
            cands = seq[start:start + k]
            if cands.size:
                return np.ascontiguousarray(cands, np.int32)
        return empty
