"""Always-on async serving front-end over the paged engine.

``generate_batch`` is a CLOSED loop: the request set is fixed up front and
the call returns when the last one retires. This module opens it:
:class:`AsyncServingEngine` runs the same serving session
(``engine.open_serve_session`` — same scheduler, same pools, exactly the
same compiled programs, pinned by the ``serving_async_steady`` contract)
on a dedicated serving thread, and accepts :meth:`add_request` from ANY
thread at ANY time. Each submission returns a :class:`RequestHandle` that
streams token bursts back as they are emitted — speculation's verified
multi-token steps arrive as multi-token bursts — and terminates with a
status (``finished`` / ``cancelled`` / ``error`` / ``rejected`` /
``timeout``).

Fault tolerance (``serving.fault``): an engine-step exception no longer
kills the loop — per-request faults re-queue the faulting requests
through recompute-preemption with logical-step backoff (quarantine after
``max_request_retries``), engine-fatal faults (the donated pools died
mid-step) trigger a crash-safe rebuild of pools + jits with every
in-flight request re-admitted, bounded by ``max_engine_restarts`` before
the crash-loop breaker parks the loop (``/healthz`` 503; drain still
works). Requests may carry deadlines (wall-clock ``deadline_ms`` /
logical ``deadline_steps``) and the loop sheds lowest-priority queued
work above ``shed_queue_depth``. All of it is deterministic given a
request trace + injection schedule (``utils/fault_injection.fail_step``)
— the serving chaos suite (``tests/unit/test_serving_chaos.py``) pins
token identity through every fault class.

Threading model (one sentence): the serving thread OWNS the engine's jit
dispatch — submissions and cancellations are commands on a lock-guarded
intake deque the loop drains between engine steps, so the scheduler and
the donated pool buffers are only ever touched single-threaded. The loop
idles on a condition variable when nothing is queued or running (an idle
server burns no CPU and no device cycles).

Determinism: the scheduler and its policies (``inference/policy.py``)
make every decision from trace state (arrival order, priorities, the
logical step clock) — given the same interleaving of submissions,
cancellations and steps, admission / preemption / retirement sequences
and greedy tokens replay identically. Tests drive that interleaving
synchronously (``start=False`` + :meth:`AsyncServingEngine.step`); the
background thread runs the very same step function.

On top sits an OpenAI-style HTTP endpoint — ``POST /v1/completions``
with ``"stream": true`` server-sent events — exposed as ``dscli serve``
(:func:`serve_main`). Prompts are token-id lists unless a tokenizer
callable is supplied; completions carry ``token_ids`` (and text when a
detokenizer is supplied).
"""

from __future__ import annotations

import json
import math
import queue
import signal as _signal
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

#: terminal handle statuses
FINISHED, CANCELLED, ERROR, REJECTED, TIMEOUT = (
    "finished", "cancelled", "error", "rejected", "timeout")


class RequestFailed(RuntimeError):
    """The serving loop retired this request without completing it
    (rejected, quarantined after step-fault retries, deadline timeout,
    pool misconfiguration, loop crash)."""


class RequestHandle:
    """One submitted request's streaming surface. Produced by
    :meth:`AsyncServingEngine.add_request`; all methods are safe from any
    thread. ``status`` moves ``pending -> queued/running -> one of
    finished | cancelled | error | rejected | timeout``."""

    def __init__(self, owner: "AsyncServingEngine", prompt: np.ndarray,
                 max_new: int, eos: Optional[int], priority: int,
                 ttft_budget: Optional[int],
                 deadline_ms: Optional[float] = None,
                 deadline_steps: Optional[int] = None,
                 trace: Optional[str] = None,
                 parent: Optional[int] = None):
        self._owner = owner
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.priority = priority
        self.ttft_budget = ttft_budget
        self.deadline_ms = deadline_ms
        self.deadline_steps = deadline_steps
        self.trace = trace       # causal trace id (router-minted); carried
        self.parent = parent     # into req.enqueue for fleet trace merges
        self.rid: Optional[int] = None     # filled once the loop enqueues it
        self.status = "pending"
        self.error: Optional[str] = None
        self.retry_after: Optional[float] = None   # backpressure hint (s),
        # set on admission-control rejections (HTTP 429 Retry-After)
        self._tokens: List[int] = []
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._submit_perf = time.perf_counter()
        self._submit_ns = time.monotonic_ns()

    # ---- serving-thread side ---- #

    def _push(self, burst: List[int]) -> None:
        self._tokens.extend(burst)
        self._q.put(("tokens", burst))

    def _finish(self, status: str, error: Optional[str] = None) -> None:
        if self._done.is_set():
            return
        self.status = status
        self.error = error
        self._done.set()
        self._q.put(("done", status, error))

    # ---- consumer side ---- #

    @property
    def generated(self) -> List[int]:
        """Tokens streamed so far (a snapshot copy)."""
        return list(self._tokens)

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> None:
        """Ask the loop to cancel this request (idempotent; a request that
        already retired keeps its terminal status)."""
        self._owner._submit_cancel(self)

    def stream(self, timeout: Optional[float] = None):
        """Iterate token bursts in emission order: each item is a
        ``list[int]`` — one token per fused decode step, several per
        accepted speculative verify step. StopIteration on any terminal
        status except ``error``, which raises :class:`RequestFailed`;
        ``timeout`` (per burst) raises ``queue.Empty``."""
        while True:
            item = self._q.get(timeout=timeout)
            if item[0] == "tokens":
                yield item[1]
                continue
            _, status, error = item
            if status == ERROR:
                raise RequestFailed(error or "request failed")
            return

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until terminal; the full sequence (prompt + generated —
        possibly partial for a cancelled request) as 1-D int32. Raises
        :class:`RequestFailed` on ``error``/``rejected``/``timeout``
        status."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request {self.rid} still in flight after "
                               f"{timeout}s")
        if self.status in (ERROR, REJECTED, TIMEOUT):
            raise RequestFailed(
                f"request {self.rid} {self.status}: {self.error}")
        if not self._tokens:
            return self.prompt.copy()
        return np.concatenate(
            [self.prompt, np.asarray(self._tokens, np.int32)])


class AsyncServingEngine:
    """The persistent serving loop: a thread-safe front-end over ONE
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine` serving
    session.

    ``policy`` overrides ``engine.config.serving.policy`` (a name, a
    ``{"name": ..., **kwargs}`` dict, or a
    :class:`~deepspeed_tpu.inference.policy.SchedulingPolicy` instance).
    ``start=False`` skips the background thread — the embedder (tests,
    trace replay) drives :meth:`step` itself for a fully deterministic
    interleaving of arrivals and engine steps.

    Lifecycle: :meth:`drain` stops intake and serves out the backlog;
    :meth:`shutdown` drains (or aborts: ``drain=False`` cancels whatever
    is in flight), stops the thread, and hands the pool workspace back to
    the engine so a later ``generate_batch`` / loop re-hits the prefix
    cache. Also a context manager (``with`` = ``shutdown(drain=True)``).
    """

    def __init__(self, engine, *, max_new_tokens: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 eos_token_id: Optional[int] = None, policy=None,
                 start: bool = True):
        from deepspeed_tpu.inference.policy import get_policy
        self.engine = engine
        if policy is None:
            policy = getattr(engine.config.serving, "policy", "fifo")
        self.policy = get_policy(policy)
        max_new = (max_new_tokens if max_new_tokens is not None
                   else engine.config.max_out_tokens)
        with engine._mesh_scope():
            self._session = engine.open_serve_session(
                max_new=max_new, temperature=temperature, top_k=top_k,
                seed=seed, eos_token_id=eos_token_id, policy=self.policy,
                on_tokens=self._on_tokens, on_finish=self._on_finish,
                # results flow through on_finish; an always-on loop must
                # not accumulate every retired Request forever
                retain_finished=False)
        self._handles: Dict[int, RequestHandle] = {}     # rid -> handle
        self._cv = threading.Condition()
        self._intake: deque = deque()      # ("submit"|"cancel", handle)
        self._draining = False
        self._stop_now = False
        self._stopped = False
        self._finalized = False
        self._n_submitted = 0
        self.error: Optional[BaseException] = None
        # ---- adaptive controller (monitor/controller.py) ---- #
        # knob -> last applied action payload: the loop-local replica of
        # the decision ledger, re-applied after an engine restart so the
        # recovered engine comes back in the SAME posture it crashed in
        self._ctl_values: Dict[str, Dict] = {}
        self._shed_override = 0            # 0 = follow serving.fault config
        # ---- fault tolerance (serving.fault) ---- #
        self._fault_cfg = engine.config.serving.fault
        self.restarts = 0                  # engine-fatal recoveries so far
        self._unattributed_faults = 0      # consecutive no-op containments
        self._crash_loop = False           # breaker: restarts exhausted —
        # the loop parks, /healthz reads 503, drain()/shutdown() still work
        self._tpot_ema_s = 0.05            # recent per-token WALL rate (the
        # Retry-After backpressure hint's base): measured over emitted-
        # token windows, not per-row callback gaps — a fused step fires W
        # near-simultaneous callbacks, and a gap EMA would under-weight
        # the one real step-time sample W-fold
        self._rate_t0: Optional[float] = None   # window start (None = idle)
        self._rate_tokens = 0              # tokens emitted in the window
        self._t0 = time.monotonic_ns()
        ev = engine._events
        if ev is not None:
            ev.emit("serve.begin", t_ns=self._t0, requests=0)
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._run,
                                            name="ds-serve-loop", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------------ #
    # front-end (any thread)

    def add_request(self, prompt, max_new_tokens: Optional[int] = None,
                    eos_token_id: Optional[int] = None, priority: int = 0,
                    ttft_budget: Optional[int] = None,
                    deadline_ms: Optional[float] = None,
                    deadline_steps: Optional[int] = None,
                    session: Optional[str] = None,
                    trace: Optional[str] = None,
                    parent: Optional[int] = None) -> RequestHandle:
        """Submit one request; returns immediately with its streaming
        handle. Raises RuntimeError once the loop is draining/stopped or
        its crash-loop breaker is open. Admission control (the policy's
        queue/pool-pressure bounds) is applied on the serving thread — a
        refused submission terminates the handle with status
        ``"rejected"`` instead of raising here. ``deadline_ms`` (wall
        clock from submission) / ``deadline_steps`` (scheduler's logical
        clock) retire the request as ``"timeout"`` on expiry.
        ``session`` is the replica router's affinity key
        (``inference/router.py``) — accepted here for surface parity
        and ignored: one engine is trivially affine. ``trace`` /
        ``parent`` are the causal trace context (trace id + parent rid)
        stamped onto the request's ``req.enqueue`` event so
        ``export_fleet_trace`` can stitch cross-replica handoffs."""
        del session
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        h = RequestHandle(self, prompt,
                          max_new=(max_new_tokens if max_new_tokens
                                   is not None else self._session.max_new),
                          eos=(eos_token_id if eos_token_id is not None
                               else self._session.eos_token_id),
                          priority=int(priority), ttft_budget=ttft_budget,
                          deadline_ms=(None if deadline_ms is None
                                       else float(deadline_ms)),
                          deadline_steps=(None if deadline_steps is None
                                          else int(deadline_steps)),
                          trace=(None if trace is None else str(trace)),
                          parent=(None if parent is None else int(parent)))
        with self._cv:
            if self._crash_loop:
                raise RuntimeError(
                    "serving loop is parked in its crash-loop breaker "
                    "(engine restarts exhausted); /healthz reads 503")
            if self._draining or self._stop_now or self._stopped:
                raise RuntimeError(
                    "serving loop is draining/stopped; no new requests")
            self._intake.append(("submit", h))
            self._n_submitted += 1
            self._cv.notify_all()
        return h

    def _submit_cancel(self, h: RequestHandle) -> None:
        with self._cv:
            if self._stopped:
                return               # finalize already terminated every handle
            self._intake.append(("cancel", h))
            self._cv.notify_all()

    def request_demote(self, prompt) -> threading.Event:
        """Ask the serving thread to force-demote ``prompt``'s committed
        FULL blocks into the host KV tier (the prefill→decode handoff's
        push half — see ``inference/router.py``). Returns an event set
        once the demotion ran: the router submits the decode-side request
        only after it fires, so the blocks are host-resident before the
        decode replica's admission probe walks the tiers. Routed through
        the command intake because demotion touches allocator state and
        dispatches the spill jit — serving-thread-only by the session
        contract. On a stopped/parked loop the event is set immediately
        (nothing demotes; the decode side falls back to recompute)."""
        arr = np.asarray(prompt, np.int32).reshape(-1)
        done = threading.Event()
        with self._cv:
            if self._stopped or self._crash_loop:
                done.set()
                return done
            self._intake.append(("demote", (arr, done)))
            self._cv.notify_all()
        return done

    def apply_knobs(self, actions) -> None:
        """Queue adaptive-controller knob movements for application on
        the serving thread (the :class:`~deepspeed_tpu.monitor.
        controller.AdaptiveController`'s ``apply_fn``). Mutation happens
        in :meth:`_step_once` BETWEEN engine steps — the donated pools
        and the jit dispatch stay single-threaded — and each applied
        movement lands in the ledger as ``ctl.apply`` (``ctl.revert``
        when a relax returns the knob to its config baseline). Accepts
        :class:`KnobAction` objects or their payload dicts; silently
        dropped on a stopped or crash-looping loop (the posture of a
        dead engine is moot)."""
        payloads = [a.to_payload() if hasattr(a, "to_payload") else dict(a)
                    for a in actions]
        if not payloads:
            return
        with self._cv:
            if self._stopped or self._crash_loop:
                return
            self._intake.append(("knobs", payloads))
            self._cv.notify_all()

    def health_state(self):
        """``(status_code, body)`` for ``GET /healthz`` — extracted from
        the HTTP handler so a :class:`~deepspeed_tpu.inference.router.
        ReplicaRouter` can present the identical surface (its aggregate
        reads 503 only when NO serving-capable replica remains). Load
        balancers key on the STATUS CODE: a stopped, crashed, or
        crash-looping loop must read unhealthy, not 200-with-caveats —
        the body is the human/status-page detail."""
        dead = self._stopped or self.error is not None
        sched = self._session.sched
        state = ("stopped" if dead else
                 "crash_loop" if self._crash_loop else
                 "draining" if self._draining else "serving")
        body = {"state": state,
                "stopped": self._stopped,
                "queue_depth": len(sched.waiting),
                "running": len(sched.running),
                "restarts": self.restarts,
                "uptime_ticks": sched.step_seq}
        if self._ctl_values:
            # adaptive posture: knob -> applied value (why is in the
            # decision ledger / ctl/last_action gauges)
            body["ctl_knobs"] = {k: a.get("value")
                                 for k, a in sorted(self._ctl_values.items())}
        return (503 if (dead or self._crash_loop) else 200), body

    def drain(self) -> None:
        """Stop intake; the loop keeps stepping until everything in
        flight has retired. Non-blocking — pair with :meth:`join` or
        :meth:`shutdown`."""
        ev = self.engine._events
        with self._cv:
            if not self._draining:
                self._draining = True
                if ev is not None:
                    sched = self._session.sched
                    ev.emit("serve.drain", waiting=len(sched.waiting),
                            running=len(sched.running),
                            pending=len(self._intake))
            self._cv.notify_all()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the loop thread to exit (after :meth:`drain` /
        :meth:`shutdown`). True when it did."""
        if self._thread is None:
            return self._stopped
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the loop. ``drain=True`` serves out the backlog first;
        ``drain=False`` cancels everything still in flight. Re-raises a
        loop crash (the handles it failed carry the same message)."""
        if drain:
            self.drain()
        else:
            with self._cv:
                self._stop_now = True
                self._draining = True
                self._cv.notify_all()
        if self._thread is not None:
            if not self.join(timeout):
                raise TimeoutError("serving loop did not stop in "
                                   f"{timeout}s")
        else:
            # synchronous mode: run the drain out (or abort) inline
            if drain:
                while self.step():
                    pass
            self._finalize()
        if self.error is not None:
            raise RequestFailed(
                f"serving loop crashed: {self.error!r}") from self.error

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)

    # ------------------------------------------------------------------ #
    # serving thread

    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    while (not self._intake and not self._stop_now
                           and not self._draining
                           and self._session.sched.all_done()):
                        self._cv.wait()      # idle: nothing queued/running
                if not self._step_once():
                    break
        except BaseException as e:  # noqa: BLE001 — loop must fail handles
            self.error = e
        finally:
            self._finalize()

    def step(self) -> bool:
        """Synchronous single step (``start=False`` mode): drain the
        intake, then run at most one engine step. Returns False when the
        loop would exit (drained) or idle (nothing runnable)."""
        if self._thread is not None:
            raise RuntimeError("step() is for start=False sessions; the "
                               "background thread owns this loop")
        if self._stopped:
            return False
        try:
            alive = self._step_once()
            if not alive:
                return False
            # "alive but idle" reads as False for a synchronous driver
            return (not self._session.sched.all_done()
                    or bool(self._intake))
        except BaseException as e:  # noqa: BLE001
            self.error = e
            self._finalize()
            raise

    def _step_once(self) -> bool:
        """One loop iteration: commands, load shedding, exit checks, one
        engine step with fault containment. Returns False when the loop
        should exit."""
        with self._cv:
            cmds = list(self._intake)
            self._intake.clear()
        for kind, h in cmds:
            if kind == "submit":
                self._process_submit(h)
            elif kind == "demote":
                self._process_demote(h)
            elif kind == "knobs":
                self._process_knobs(h)
            else:
                self._process_cancel(h)
        if self._stop_now:
            return False
        if self._crash_loop:
            # breaker open: nothing can run — park (the cv-wait predicate
            # holds, everything in flight was failed) until drain/shutdown
            return not self._draining
        self._shed_overload()
        if self._session.sched.all_done():
            # going idle: a rate window spanning the idle gap would read
            # as an enormous per-token latency and poison the hint's EMA
            self._rate_t0 = None
            return not self._draining
        from deepspeed_tpu.inference.scheduler import PoolExhausted
        try:
            with self.engine._mesh_scope():
                self._session.step()
            # a healthy step makes "consecutive" mean consecutive: rare
            # transient unattributed blips separated by normal traffic
            # must never accumulate their way into a restart/breaker
            self._unattributed_faults = 0
        except PoolExhausted as e:
            # one request outgrew the pool with nothing left to evict: the
            # closed loop fails the whole call, but an always-on server
            # must not die for everyone — retire the culprit with an error
            # (its handle reads status "error") and keep serving
            self._session.sched.fail_request(e.req, str(e))
            self._session._flush_finished()
        except Exception as e:  # noqa: BLE001 — the containment boundary:
            # SimulatedCrash (BaseException) and everything non-Exception
            # still kill the loop, exactly like the checkpoint writer
            self._contain(e)
        return True

    def _retry_after_hint(self) -> float:
        """Backpressure hint for 429 rejections (admission control, load
        shedding): roughly when a queue slot should open — queue depth x
        recent per-token wall rate x tokens per request, clamped to
        [1s, 120s]. The EMA measures the gap between consecutive bursts
        across ALL rows of the fused batch (W callbacks fire per decode
        step), so it already amortizes batch width — dividing by W again
        would understate the wait by ~W and defeat the backpressure."""
        depth = max(len(self._session.sched.waiting), 1)
        per_req_s = self._tpot_ema_s * self._session.max_new
        return min(max(depth * per_req_s, 1.0), 120.0)

    def _process_knobs(self, payloads) -> None:
        """Apply queued controller actions on the serving thread (the
        only thread allowed to touch the session, scheduler, allocator
        and policy) and ledger each one as ``ctl.apply``/``ctl.revert``."""
        ev = self.engine._events
        for a in payloads:
            name, value = a.get("knob"), a.get("value")
            if name is None or value is None:
                continue
            if not self._apply_one_knob(str(name), int(value)):
                continue                   # unknown knob: ledger nothing
            self._ctl_values[str(name)] = dict(a)
            if ev is not None:
                kind = ("ctl.revert" if a.get("direction") == "relax"
                        and a.get("at_baseline") else "ctl.apply")
                ev.emit(kind, knob=name, value=int(value),
                        prev=a.get("prev"), tick=a.get("tick"),
                        reason=a.get("reason"))

    def _apply_one_knob(self, name: str, value: int) -> bool:
        """One knob mutation. Every target is plain host state read by
        the NEXT step's scheduling/dispatch decisions — ladder rungs are
        chosen (``knobs_from_serving``) so each value lands inside the
        compile buckets the warm engine already owns, which is what the
        ``serving_adaptive_steady`` contract pins."""
        sess = self._session
        sched = sess.sched
        if name == "prefill_chunk":
            # both homes: the scheduler decides WHETHER to chunk, the
            # session sizes each chunk step
            sess.chunk_tokens = value
            sched.chunk_tokens = value
            return True
        if name == "spec_k":
            if sched.spec_proposer is None:
                return False
            # the verify program pads to the FIXED window set at session
            # open, so any k <= the configured k is compile-free
            sched.spec_k = value
            return True
        if name == "max_queue":
            self.policy.admission_max_queue = value
            return True
        if name == "min_free_blocks":
            self.policy.admission_min_free_blocks = value
            return True
        if name == "shed_depth":
            self._shed_override = value
            return True
        if name == "kv_spill":
            spill = getattr(sess, "_spill_block", None)
            if spill is None:
                return False
            sess._kv_spill = bool(value)
            sched.allocator.set_spill(spill if value else None)
            return True
        return False

    def _shed_overload(self) -> None:
        """Load shedding: with ``serving.fault.shed_queue_depth`` set,
        drop policy-selected queued requests (lowest priority first,
        deterministic) until the waiting queue fits the bound — graceful
        degradation instead of unbounded queue growth under pressure.
        A controller-tightened ``shed_depth`` overrides the config bound
        until the controller relaxes it back to baseline."""
        bound = (self._shed_override if self._shed_override > 0
                 else int(self._fault_cfg.shed_queue_depth))
        if bound <= 0:
            return
        sched = self._session.sched
        while len(sched.waiting) > bound:
            idx = self.policy.select_shed_victim(sched)
            if idx is None or not 0 <= idx < len(sched.waiting):
                break
            sched.shed_request(sched.waiting[idx])
        self._session._flush_finished()

    def _contain(self, exc: Exception) -> None:
        """Step-fault containment: per-request faults were already
        re-queued/quarantined by the session; an engine-fatal fault (the
        donated pools died mid-step) triggers a crash-safe restart —
        bounded by ``serving.fault.max_engine_restarts`` with exponential
        wall backoff — and, exhausted, opens the crash-loop breaker. An
        UNATTRIBUTED fault (no action to re-queue — e.g. a broken
        scheduling policy raising inside ``next_action``) is deterministic
        recurrence territory no per-request budget can bound: after
        ``max_request_retries`` consecutive occurrences it escalates to
        the restart path (and from there, the breaker) instead of letting
        the loop hot-spin on it forever."""
        try:
            outcome = self._session.contain_fault(exc)
        except Exception as inner:  # noqa: BLE001 — containment itself died
            self.error = inner
            raise
        if outcome == "request":
            self._unattributed_faults = 0
            return
        if outcome == "unattributed":
            self._unattributed_faults += 1
            if self._unattributed_faults \
                    <= int(self._fault_cfg.max_request_retries):
                return
            # fall through: escalate like an engine-fatal fault
        if self.restarts >= int(self._fault_cfg.max_engine_restarts):
            self._trip_breaker(exc)
            return
        backoff = float(self._fault_cfg.restart_backoff_s)
        if backoff > 0:
            time.sleep(min(backoff * (1 << self.restarts), 60.0))
        try:
            with self.engine._mesh_scope():
                self._session.restart_engine()
        except Exception as rebuild_exc:  # noqa: BLE001 — a recovery that
            # cannot even rebuild its pools is a crash loop, not a retry
            self._trip_breaker(rebuild_exc)
            return
        # recorded only AFTER the rebuild succeeded: restarts/healthz and
        # the serve.restart event count PERFORMED recoveries, never an
        # attempt that itself crashed into the breaker
        self.restarts += 1
        self._unattributed_faults = 0
        ev = self.engine._events
        if ev is not None:
            ev.emit("serve.restart", restart=self.restarts,
                    error=f"{type(exc).__name__}: {exc}")
        tel = self._session.sched.telemetry
        if tel is not None:
            tel.engine_restarts.inc()
        # crash-safety for the adaptive posture: the rebuild re-derives
        # engine state from config, so every controller action applied
        # before the fault is re-applied FROM THE LEDGER replica — the
        # recovered engine serves in the posture it crashed in, and the
        # re-applications are themselves ledgered (restart=True)
        for name, a in sorted(self._ctl_values.items()):
            if not self._apply_one_knob(name, int(a["value"])):
                continue
            if ev is not None:
                ev.emit("ctl.apply", knob=name, value=int(a["value"]),
                        prev=a.get("prev"), tick=a.get("tick"),
                        reason=a.get("reason"), restart=True)

    def _trip_breaker(self, exc: Exception) -> None:
        self._crash_loop = True
        msg = (f"crash-loop breaker open after "
               f"{int(self._fault_cfg.max_engine_restarts)} engine "
               f"restart(s): {type(exc).__name__}: {exc}")
        sched = self._session.sched
        sched.allocator.set_spill(None)    # no demotions off dead pools
        for r in list(sched.waiting) + list(sched.running):
            try:
                sched.fail_request(r, msg)
            except Exception:  # noqa: BLE001 — best-effort teardown: one
                # request's skewed bookkeeping must not strand the REST of
                # the handles un-terminated (their clients block forever)
                continue
        self._session._flush_finished()

    def _process_submit(self, h: RequestHandle) -> None:
        sched = self._session.sched
        if self._crash_loop:
            h._finish(REJECTED, "serving loop is parked in its crash-loop "
                                "breaker (engine restarts exhausted)")
            return
        if self._draining:
            # the drain/submit race's loser: the submission passed
            # add_request's flag check before drain() set it, but reached
            # the loop after — serving it would let a submission stream
            # extend "draining" forever, so it rejects instead (pinned)
            h._finish(REJECTED, "serving loop is draining; request "
                                "arrived after intake stopped")
            return
        if h.deadline_ms is not None and \
                (time.perf_counter() - h._submit_perf) * 1e3 > h.deadline_ms:
            # intake deadline check: already late before admission — retire
            # as timeout without burning a queue slot on it. Counter AND
            # event both fire (rid-less: the request never reached the
            # scheduler) so /metrics and the trace cannot disagree.
            if sched.telemetry is not None:
                sched.telemetry.timeouts.inc()
            ev = self.engine._events
            if ev is not None:
                ev.emit("req.timeout", generated=0,
                        error="deadline expired before admission")
            h._finish(TIMEOUT, f"deadline of {h.deadline_ms:.0f} ms expired "
                               "before the request reached the scheduler")
            return
        if not self.policy.admit_ok(sched, int(h.prompt.size)):
            if sched.telemetry is not None:
                sched.telemetry.rejected_requests.inc()
            h.retry_after = self._retry_after_hint()
            h._finish(REJECTED, "admission control refused the request "
                                "(queue bound / KV pool pressure)")
            return
        try:
            req = self._session.add(h.prompt, max_new=h.max_new, eos=h.eos,
                                    priority=h.priority,
                                    ttft_budget=h.ttft_budget,
                                    t_submit=h._submit_perf,
                                    deadline_ms=h.deadline_ms,
                                    deadline_steps=h.deadline_steps,
                                    trace=h.trace, parent=h.parent)
        except (ValueError, TypeError) as e:
            # oversized prompt / never-admittable: reject THIS handle, the
            # loop itself stays healthy
            h._finish(REJECTED, str(e))
            return
        h.rid = req.rid
        h.status = "queued"
        self._handles[req.rid] = h
        ev = self.engine._events
        if ev is not None:
            # after add_request (the rid is the scheduler's), stamped with
            # the caller-side submission time: ring order is emit order,
            # timestamps tell the true story (the validator does not
            # require monotone ts for exactly this reason)
            ev.emit("req.submit", rid=req.rid, t_ns=h._submit_ns,
                    prompt_tokens=int(h.prompt.size), priority=h.priority)

    def _process_demote(self, cmd) -> None:
        """The ``request_demote`` command body: force-demote the prompt's
        committed FULL blocks into the host tier under the mesh scope
        (the spill jit dispatches here). The completion event is set in a
        ``finally`` — a demotion failure must not strand the router's
        handoff wait; the decode side simply recomputes whatever did not
        make it host-side."""
        arr, done = cmd
        try:
            if not self._crash_loop:
                with self.engine._mesh_scope():
                    self._session.demote_prompt(arr)
        except Exception:  # noqa: BLE001 — handoff is best-effort
            pass
        finally:
            done.set()

    def _process_cancel(self, h: RequestHandle) -> None:
        if h.done():
            return
        if h.rid is None:
            # submitted and cancelled inside one intake batch: the submit
            # was processed first (deque order), so rid is set unless the
            # submit was rejected — either way nothing is scheduled now
            h._finish(CANCELLED)
            return
        req = self._req_by_rid(h.rid)
        if req is not None:
            self._session.cancel(req)   # _on_finish terminates the handle
        else:
            h._finish(CANCELLED)

    def _req_by_rid(self, rid: int):
        sched = self._session.sched
        for r in list(sched.waiting) + sched.running:
            if r.rid == rid:
                return r
        return None

    # session callbacks (serving thread)

    def _on_tokens(self, req, tokens: List[int]) -> None:
        now = time.perf_counter()
        if self._rate_t0 is None:
            self._rate_t0, self._rate_tokens = now, 0
        self._rate_tokens += len(tokens)
        if self._rate_tokens >= 32 and now > self._rate_t0:
            # one wall-rate sample per ~32 emitted tokens: elapsed/tokens
            # is the batch-amortized per-token rate the Retry-After hint
            # needs, immune to the per-row callback clustering of a
            # fused step
            rate = (now - self._rate_t0) / self._rate_tokens
            self._tpot_ema_s += 0.3 * (min(rate, 10.0) - self._tpot_ema_s)
            self._rate_t0, self._rate_tokens = now, 0
        h = self._handles.get(req.rid)
        if h is not None:
            if h.status == "queued":
                h.status = "running"
            h._push(tokens)

    def _on_finish(self, req) -> None:
        h = self._handles.pop(req.rid, None)
        if h is None:
            return
        if req.cancelled:
            h._finish(CANCELLED)
        elif getattr(req, "timed_out", False):
            h._finish(TIMEOUT, req.error)
        elif getattr(req, "shed", False):
            h.retry_after = self._retry_after_hint()
            h._finish(REJECTED, req.error)
        elif req.error is not None:
            h._finish(ERROR, req.error)
        else:
            h._finish(FINISHED)

    def _finalize(self) -> None:
        """Terminal bookkeeping (idempotent): fail/cancel whatever is
        still in flight, close the session (rid uniqueness), and on a
        clean exit emit ``serve.end`` + hand the pools back."""
        if self._finalized:
            return
        self._finalized = True
        with self._cv:
            self._stopped = True
            leftovers = list(self._intake)
            self._intake.clear()
            self._cv.notify_all()
        msg = (f"serving loop terminated: {self.error!r}"
               if self.error is not None else None)
        for kind, h in leftovers:
            if kind == "submit":
                h._finish(REJECTED, msg or "serving loop stopped")
            elif kind == "demote":
                h[1].set()       # never strand a handoff wait
        if self.error is None and not self._session._closed:
            # aborting shutdown: retire everything still scheduled THROUGH
            # the scheduler so its KV blocks free and the persistent
            # allocator stays leak-free for the next session (on_finish
            # terminates each handle as "cancelled")
            sched = self._session.sched
            for r in list(sched.waiting) + list(sched.running):
                try:
                    self._session.cancel(r)
                except Exception:  # noqa: BLE001 — best-effort teardown
                    break
        for h in list(self._handles.values()):
            if self.error is not None:
                h._finish(ERROR, msg)
            else:
                h._finish(CANCELLED, "serving loop shut down")
        self._handles.clear()
        try:
            self._session.close()
            if self.error is None:
                ev = self.engine._events
                if ev is not None:
                    ev.emit("serve.end", t_ns=self._t0,
                            dur_ns=time.monotonic_ns() - self._t0,
                            requests=self._n_submitted)
                self._session.end()
        except Exception as e:  # noqa: BLE001 — shutdown must not raise
            if self.error is None:
                self.error = e


class ServeSignalHandler:
    """``dscli serve``'s graceful SIGTERM/SIGINT — the serving mirror of
    the checkpoint side's ``PreemptionHandler``: on the first signal, stop
    intake (new submissions 503) and unblock ``serve_forever`` so the main
    path can drain in-flight requests within a bounded grace period and
    exit ``128 + signum`` (supervisors see a conventional signal death).
    Re-entrant signals during the drain are ignored; previous handlers are
    restored on :meth:`uninstall` (the PR-6 handler-restore pattern).
    Install is a no-op off the main thread (signal handlers are
    main-thread-only — in-process test servers drive :meth:`trigger`
    directly)."""

    def __init__(self, server, serving: "AsyncServingEngine",
                 signals=(_signal.SIGTERM, _signal.SIGINT)):
        self.server = server
        self.serving = serving
        self.signals = tuple(signals)
        self.signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}
        self._installed = False

    def install(self) -> "ServeSignalHandler":
        if self._installed or \
                threading.current_thread() is not threading.main_thread():
            return self
        for sig in self.signals:
            self._prev[sig] = _signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    def _handle(self, signum, frame) -> None:
        self.trigger(signum)

    def trigger(self, signum: int) -> None:
        """The handler body (callable directly by tests): first signal
        wins — stop intake, then shut the HTTP server down from another
        thread (``server.shutdown`` deadlocks the ``serve_forever``
        thread) so the caller's drain-and-exit path runs."""
        if self.signum is not None:
            return
        self.signum = int(signum)
        try:
            name = _signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        print(f"dscli serve: {name} received — stopping intake, draining "
              "in-flight requests", flush=True)
        try:
            self.serving.drain()       # new submissions now raise -> 503
        except Exception:  # noqa: BLE001 — the exit path must proceed
            pass
        threading.Thread(target=self.server.shutdown, daemon=True).start()


# ---------------------------------------------------------------------- #
# OpenAI-style HTTP front door (``dscli serve``)


def _sse(chunk: Dict[str, Any]) -> bytes:
    return b"data: " + json.dumps(chunk).encode() + b"\n\n"


def build_http_server(serving: AsyncServingEngine, host: str = "127.0.0.1",
                      port: int = 8000,
                      tokenizer: Optional[Callable[[str], List[int]]] = None,
                      detokenizer: Optional[Callable[[List[int]], str]]
                      = None):
    """An ``http.server`` speaking the OpenAI completions shape over the
    async engine. ``POST /v1/completions`` accepts::

        {"prompt": [token ids] | "text" (needs a tokenizer),
         "max_tokens": 16, "stream": false, "priority": 0,
         "ttft_budget": null, "eos_token_id": null,
         "session": null}  # replica-router affinity key (multi-turn
                           # clients pass a stable id)

    Non-streaming responses return one ``text_completion`` object whose
    choice carries ``token_ids`` (and ``text`` when a detokenizer is
    wired). ``"stream": true`` responds ``text/event-stream``: one SSE
    ``data:`` chunk per emitted burst — speculation's multi-token bursts
    arrive as multi-id chunks — a final chunk with ``finish_reason``, then
    ``data: [DONE]``. ``GET /healthz`` reports loop liveness. Returns the
    (threaded) server; run ``serve_forever()`` on it — every connection
    handler thread only touches the thread-safe handle API."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    def _ids(body):
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            if tokenizer is None:
                raise ValueError("string prompts need a tokenizer; POST "
                                 "token ids: {\"prompt\": [464, 3290, ...]}")
            prompt = tokenizer(prompt)
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            raise ValueError("prompt must be a non-empty list of token ids")
        return prompt

    def _text(ids: List[int]) -> str:
        return detokenizer(ids) if detokenizer is not None else ""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):   # quiet: dscli owns the console
            pass

        def _json(self, code: int, obj: Dict[str, Any]) -> None:
            payload = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            if self.path == "/healthz":
                # delegated to health_state(): one liveness rule shared by
                # the single-engine loop and the replica router's
                # aggregate (503 only when nothing can serve)
                code, body = serving.health_state()
                self._json(code, body)
            elif self.path == "/metrics":
                # Prometheus exposition of the process registry — the
                # scrape-and-alert plane's front door (one shared
                # rendering path with the standalone exporter; exemplars
                # only under negotiated OpenMetrics). Same liveness rule
                # as /healthz: a stopped loop's stale numbers must not
                # scrape as healthy 200s.
                dead = serving._stopped or serving.error is not None
                if dead:
                    self._json(503, {"error": "serving loop stopped"})
                    return
                from deepspeed_tpu.monitor.exporter import (
                    render_exposition, wants_openmetrics)
                text, ctype = render_exposition(
                    openmetrics=wants_openmetrics(
                        self.headers.get("Accept")))
                payload = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/v1/completions":
                self._json(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                ids = _ids(body)
                # every body field coerced INSIDE the 400 path: a garbage
                # max_tokens/priority/ttft_budget is the client's error,
                # never a handler traceback (or, worse, a value smuggled
                # into the scheduling policy's math on the loop thread)
                max_tokens = int(body.get("max_tokens", 16))
                if max_tokens < 1:
                    raise ValueError("max_tokens must be >= 1")
                priority = int(body.get("priority", 0))
                ttft_budget = body.get("ttft_budget")
                if ttft_budget is not None:
                    ttft_budget = int(ttft_budget)
                deadline_ms = body.get("deadline_ms")
                if deadline_ms is not None:
                    deadline_ms = float(deadline_ms)
                    if deadline_ms <= 0:
                        raise ValueError("deadline_ms must be > 0")
                eos = body.get("eos_token_id")
                if eos is not None:
                    eos = int(eos)
                sess = body.get("session")
                if sess is not None:
                    sess = str(sess)
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)})
                return
            try:
                h = serving.add_request(
                    ids, max_new_tokens=max_tokens, priority=priority,
                    ttft_budget=ttft_budget, deadline_ms=deadline_ms,
                    eos_token_id=eos, session=sess)
            except RuntimeError as e:   # draining/stopped/crash-loop
                self._json(503, {"error": str(e)})
                return
            rid_name = f"cmpl-{id(h):x}"
            if body.get("stream"):
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.close_connection = True
                self.end_headers()
                try:
                    try:
                        for burst in h.stream():
                            self.wfile.write(_sse({
                                "id": rid_name,
                                "object": "text_completion",
                                "choices": [{"index": 0,
                                             "text": _text(burst),
                                             "token_ids": burst,
                                             "finish_reason": None}]}))
                            self.wfile.flush()
                        finish = {"finished": "stop"}.get(h.status, h.status)
                    except RequestFailed as e:
                        self.wfile.write(_sse({
                            "id": rid_name, "object": "text_completion",
                            "error": str(e)}))
                        finish = "error"
                    self.wfile.write(_sse({
                        "id": rid_name, "object": "text_completion",
                        "choices": [{"index": 0, "text": "",
                                     "token_ids": [],
                                     "finish_reason": finish}]}))
                    self.wfile.write(b"data: [DONE]\n\n")
                except OSError:
                    # client went away mid-stream: cancel the request so
                    # it stops burning decode steps and KV blocks — an
                    # abandoned stream must not decode to max_new
                    h.cancel()
                return
            try:
                h.result()
            except RequestFailed as e:
                if h.status == TIMEOUT:
                    # deadline expiry is a gateway-timeout, not our fault
                    self._json(504, {"error": str(e)})
                elif h.status == REJECTED and h.retry_after is not None:
                    # admission control / load shedding: backpressure the
                    # client with a Retry-After derived from queue depth x
                    # recent TPOT (the 429 contract retry loops key on)
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(429)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Retry-After",
                                     str(int(math.ceil(h.retry_after))))
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                else:
                    self._json(409 if h.status == REJECTED else 500,
                               {"error": str(e)})
                return
            gen = h.generated
            self._json(200, {
                "id": rid_name, "object": "text_completion",
                "model": type(serving.engine.module).__name__,
                "choices": [{"index": 0, "text": _text(gen),
                             "token_ids": gen,
                             "finish_reason": "stop"
                             if h.status == FINISHED else h.status}],
                "usage": {"prompt_tokens": len(ids),
                          "completion_tokens": len(gen),
                          "total_tokens": len(ids) + len(gen)}})

    class Server(ThreadingHTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    return Server((host, port), Handler)


def serve_main(argv=None, model=None, params=None,
               ready_cb: Optional[Callable] = None) -> int:
    """``dscli serve`` — stand up the always-on loop behind the HTTP
    endpoint. ``model``/``params``/``ready_cb`` are injection points for
    in-process tests (``ready_cb(server, serving)`` fires once the socket
    is bound; shut the server down from there)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="dscli serve",
        description="OpenAI-style completions endpoint over the paged "
                    "continuous-batching engine (token-id prompts)")
    parser.add_argument("--model", default="gpt2:125m",
                        help="model zoo preset, e.g. gpt2:125m, llama:tiny")
    parser.add_argument("--checkpoint", default=None,
                        help="HF checkpoint dir/file to load weights from "
                             "(default: random init — smoke serving)")
    parser.add_argument("--dtype", default="bf16")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000,
                        help="TCP port (0 = ephemeral, printed once bound)")
    parser.add_argument("--max-new", type=int, default=128,
                        help="default max_tokens when a request omits it")
    parser.add_argument("--policy", default=None,
                        help="scheduling policy: fifo | priority | sla "
                             "(default: config serving.policy)")
    parser.add_argument("--block-size", type=int, default=128)
    parser.add_argument("--max-running", type=int, default=8)
    parser.add_argument("--max-blocks", type=int, default=0)
    parser.add_argument("--replicas", type=int, default=1,
                        help="dp serving axis: N engine replicas behind "
                             "the deterministic affinity router (shared "
                             "weights, shared host KV tier)")
    parser.add_argument("--replica-roles", default="",
                        help="comma list of per-replica roles (any | "
                             "prefill | decode), e.g. 'prefill,decode' "
                             "enables disaggregated prefill/decode over "
                             "the host KV tier (default: all 'any')")
    parser.add_argument("--spec", default="off",
                        help="speculative decoding: off | ngram")
    parser.add_argument("--telemetry", action="store_true",
                        help="enable telemetry + flight recorder (the "
                             "serving trace / dscli health surfaces)")
    parser.add_argument("--sample-jsonl", default=None, metavar="PATH",
                        help="start the background metrics sampler, "
                             "appending registry snapshots to this "
                             "rotated JSONL (dscli top / dscli health "
                             "source); implies --telemetry")
    parser.add_argument("--sample-interval", type=float, default=1.0,
                        help="sampler cadence in seconds (default 1)")
    parser.add_argument("--slo-ttft-ms", type=float, default=0.0,
                        help="p99 TTFT objective in ms (0 = off): burn-"
                             "rate breaches fire slo.breach events and "
                             "slo/breaches counters; implies the sampler")
    parser.add_argument("--slo-tpot-ms", type=float, default=0.0,
                        help="p99 TPOT objective in ms (0 = off)")
    parser.add_argument("--adaptive", action="store_true",
                        help="close the loop: the SLO-burn-rate autopilot "
                             "(monitor/controller.py) moves serving knobs "
                             "under burn and steps them back under "
                             "headroom, with every decision ledgered as "
                             "ctl.* events; implies the sampler plane "
                             "(single-replica only)")
    parser.add_argument("--grace", type=float, default=30.0,
                        help="SIGTERM/SIGINT drain grace period in "
                             "seconds: intake stops immediately (503), "
                             "in-flight requests get this long to finish, "
                             "then the process exits 128+signum")
    args = parser.parse_args(argv)

    import deepspeed_tpu

    if model is None:
        from deepspeed_tpu.models.presets import get_model
        name, _, size = args.model.partition(":")
        model = get_model(name, *([size] if size else []))
    serving_cfg = {"block_size": args.block_size,
                   "max_running": args.max_running,
                   "max_num_blocks": args.max_blocks,
                   "speculative": {"mode": args.spec}}
    if args.policy is not None:
        serving_cfg["policy"] = args.policy
    slo_on = bool(args.slo_ttft_ms or args.slo_tpot_ms)
    want_plane = bool(args.sample_jsonl or slo_on or args.adaptive)
    kwargs: Dict[str, Any] = {"dtype": args.dtype, "serving": serving_cfg}
    if args.telemetry or want_plane:
        kwargs["telemetry"] = {"events": True}
    if args.checkpoint:
        kwargs["checkpoint"] = args.checkpoint
    engine = deepspeed_tpu.init_inference(model, params=params, **kwargs)

    n_rep = max(int(args.replicas), 1)
    if args.adaptive and n_rep > 1:
        # the controller folds ONE engine's pressure signals and mutates
        # ONE serving loop; a fleet needs one controller per replica
        # (ROADMAP item — run replicas static for now)
        print("dscli serve: --adaptive supports a single replica; "
              "running the fleet with static config", flush=True)

    sampler = None
    slo = None
    if want_plane:
        # the SLO engine evaluates on the sampler's ticks; any of the
        # flags stands the sampling plane up (ring-only without
        # --sample-jsonl)
        from deepspeed_tpu.monitor.slo import (SloEngine, parse_objectives,
                                               serving_objectives)
        if slo_on:
            slo = SloEngine(
                parse_objectives(serving_objectives(
                    ttft_p99_ms=args.slo_ttft_ms or None,
                    tpot_p99_ms=args.slo_tpot_ms or None)),
                events=engine._events)
    if n_rep > 1:
        # dp serving axis: N engines share one weight pytree and one host
        # KV tier (the prefill->decode transport), each behind its own
        # always-on loop; the router fronts them all
        from deepspeed_tpu.inference.router import ReplicaRouter
        pool = engine.ensure_host_kv_pool()
        engines = [engine]
        for _ in range(n_rep - 1):
            e = deepspeed_tpu.init_inference(model, params=engine.params,
                                             **kwargs)
            if pool is not None:
                e.adopt_host_kv_pool(pool)
            engines.append(e)
        roles = [r.strip() for r in args.replica_roles.split(",")
                 if r.strip()]
        serving = ReplicaRouter(
            [AsyncServingEngine(e, max_new_tokens=args.max_new)
             for e in engines],
            roles=roles or None)
    else:
        serving = AsyncServingEngine(engine, max_new_tokens=args.max_new)
    if want_plane:
        # sampler construction waits for the serving loop: the adaptive
        # controller's apply_fn is the loop's knob intake
        from deepspeed_tpu.monitor.sampler import MetricsSampler
        ctl = None
        if args.adaptive and n_rep == 1:
            from deepspeed_tpu.monitor.controller import (
                AdaptiveController, knobs_from_serving)
            knobs = knobs_from_serving(engine.config.serving,
                                       policy=serving.policy)
            if knobs:
                ctl = AdaptiveController(knobs, events=engine._events,
                                         apply_fn=serving.apply_knobs)
            else:
                print("dscli serve: --adaptive found no movable knobs "
                      "(chunking/spec/admission/shed all off); running "
                      "static", flush=True)
        sampler = MetricsSampler(interval_s=args.sample_interval,
                                 path=args.sample_jsonl, slo=slo,
                                 ctl=ctl).start()
    server = build_http_server(serving, host=args.host, port=args.port)
    host, port = server.server_address[:2]
    print(f"dscli serve: {args.model} listening on "
          f"http://{host}:{port}/v1/completions "
          f"(policy={serving.policy.name}, replicas={n_rep}, "
          f"max_running={args.max_running}; metrics at /metrics)",
          flush=True)
    if ready_cb is not None:
        ready_cb(server, serving)
    # graceful preemption: SIGTERM/SIGINT stop intake and unblock
    # serve_forever; the finally below drains within --grace seconds and
    # the process exits 128+signum (installation is a no-op off the main
    # thread — in-process tests reach the handler via the attribute and
    # drive trigger() directly)
    handler = ServeSignalHandler(server, serving).install()
    serving._signal_handler = handler
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        handler.signum = handler.signum or _signal.SIGINT
    finally:
        server.server_close()
        try:
            try:
                serving.shutdown(drain=True, timeout=args.grace)
            except TimeoutError:
                # grace exhausted: abort — cancel what's left rather than
                # overstay the supervisor's kill window
                print(f"dscli serve: drain grace of {args.grace:.0f}s "
                      "exhausted; cancelling in-flight requests",
                      flush=True)
                serving.shutdown(drain=False, timeout=10)
        except Exception as e:  # noqa: BLE001 — exit path
            print(f"dscli serve: shutdown error: {e}")
            return 1
        finally:
            handler.uninstall()
            if sampler is not None:
                sampler.stop()
    if handler.signum is not None:
        return 128 + int(handler.signum)
    return 0
