"""Automatic tensor-parallel sharding for arbitrary parameter pytrees.

Reference parity: ``deepspeed/module_inject/auto_tp.py`` + ``replace_wo_policy``
(``replace_module.py:357``) — the policy-free path that inspects the module
graph to decide which linears to row/column-shard and where the all-reduce
goes. The SPMD analogue inspects parameter names/shapes and emits
PartitionSpecs; XLA places the collectives.

Heuristics (Megatron layout):
- names containing q/k/v/query/key/value/up/gate/fc1/w_up/wi → column shard
  (last dim over ``tp``)
- names containing o_proj/out/down/fc2/w_down/wo/dense_4h → row shard
  (first non-batch dim over ``tp``) — XLA inserts the psum after it
- embeddings → vocab shard; norms/biases of row-sharded layers → replicate
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import PartitionSpec as P

COLUMN_PAT = ("wq", "wk", "wv", "q_proj", "k_proj", "v_proj", "query", "key", "value", "w_up", "up_proj", "w_gate",
              "gate_proj", "fc1", "wi", "c_fc", "dense_h_to_4h")
ROW_PAT = ("wo", "o_proj", "out_proj", "w_down", "down_proj", "fc2", "wo_proj", "c_proj", "dense_4h_to_h",
           "attention.dense")
EMBED_PAT = ("embed", "wte", "word_embeddings", "tok_embeddings")


def _spec_for(path: str, shape) -> P:
    ndim = len(shape)
    lower = path.lower()
    if ndim < 2:
        return P(*([None] * ndim))
    if any(p in lower for p in EMBED_PAT):
        return P(*(["tp"] + [None] * (ndim - 1)))
    if any(p in lower for p in COLUMN_PAT):
        spec = [None] * ndim
        spec[-1] = "tp"
        return P(*spec)
    if any(p in lower for p in ROW_PAT):
        spec = [None] * ndim
        spec[-2] = "tp"
        return P(*spec)
    return P(*([None] * ndim))


def auto_tp_specs(params) -> Any:
    """PartitionSpec pytree congruent with ``params`` chosen by name."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree.structure(params)
    specs = []
    for keypath, leaf in flat:
        path = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in keypath)
        specs.append(_spec_for(path, getattr(leaf, "shape", ())))
    return jax.tree.unflatten(treedef, specs)
