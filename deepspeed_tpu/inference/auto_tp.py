"""Automatic tensor-parallel sharding for arbitrary parameter pytrees.

Reference parity: ``deepspeed/module_inject/auto_tp.py`` + ``replace_wo_policy``
(``replace_module.py:357``) — the policy-free path that inspects the module
graph to decide which linears to row/column-shard and where the all-reduce
goes. The SPMD analogue inspects parameter names/shapes and emits
PartitionSpecs; XLA places the collectives.

Heuristics (Megatron layout):
- names containing q/k/v/query/key/value/up/gate/fc1/w_up/wi → column shard
  (last dim over ``tp``); their 1-D biases shard the same way
- names containing o_proj/out/down/fc2/w_down/wo/dense_4h → row shard
  (first non-batch dim over ``tp``) — XLA inserts the psum after it; their
  biases replicate (added once, after the reduce)
- embeddings → vocab shard; norms and other 1-D leaves → replicate

Every pattern rule is guarded by a divisibility check when the tensor-
parallel degree is known: a dim that ``tp`` does not divide replicates
(with a rate-limited warning naming the param) instead of crashing — the
engine serves correctly either way, just without the memory split on that
tensor.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.utils.logging import warn_once

COLUMN_PAT = ("wq", "wk", "wv", "q_proj", "k_proj", "v_proj", "query", "key", "value", "w_up", "up_proj", "w_gate",
              "gate_proj", "fc1", "wi", "c_fc", "dense_h_to_4h")
ROW_PAT = ("wo", "o_proj", "out_proj", "w_down", "down_proj", "fc2", "wo_proj", "c_proj", "dense_4h_to_h",
           "attention.dense")
EMBED_PAT = ("embed", "wte", "word_embeddings", "tok_embeddings")


def _guard(path: str, shape, dim: int, tp: Optional[int]) -> bool:
    """Whether sharding ``shape[dim]`` over ``tp`` ways is legal. ``tp``
    None/0 = unknown degree (spec emission only): always allowed — the
    downstream placement (``sanitize_tp_spec``) re-checks against the
    actual mesh. A known, non-dividing degree warns once per param."""
    if not tp or tp <= 1:
        return True
    if shape[dim] % tp == 0:
        return True
    warn_once(f"auto_tp: {path} dim {dim} (size {shape[dim]}) is not "
              f"divisible by tp={tp}; replicating this tensor (it gets no "
              "memory split or compute speedup from the tp axis)")
    return False


def _spec_for(path: str, shape, tp: Optional[int] = None) -> P:
    ndim = len(shape)
    lower = path.lower()
    if ndim == 0:
        return P()
    if ndim >= 2 and any(p in lower for p in EMBED_PAT):
        if _guard(path, shape, 0, tp):
            return P(*(["tp"] + [None] * (ndim - 1)))
        return P(*([None] * ndim))
    # row patterns first: several row names contain column substrings
    # ("out_proj" contains neither, but e.g. "wo" is a prefix of nothing
    # column-side; checking row first keeps "attention.dense" row-sharded
    # even though "dense" alone matches nothing) — and row BIASES replicate
    # (the bias is added once, after the tp all-reduce)
    if any(p in lower for p in ROW_PAT):
        if ndim < 2:
            return P(*([None] * ndim))
        if _guard(path, shape, ndim - 2, tp):
            spec = [None] * ndim
            spec[-2] = "tp"
            return P(*spec)
        return P(*([None] * ndim))
    if any(p in lower for p in COLUMN_PAT):
        # column shard the output dim — for 1-D biases that IS the last
        # (only) dim, so a column layer's bias shards with its weight
        if _guard(path, shape, ndim - 1, tp):
            spec = [None] * ndim
            spec[-1] = "tp"
            return P(*spec)
        return P(*([None] * ndim))
    return P(*([None] * ndim))


def _leaf_path(keypath) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in keypath)


def auto_tp_specs(params, tp: Optional[int] = None) -> Any:
    """PartitionSpec pytree congruent with ``params`` chosen by name.

    ``tp`` (the tensor-parallel degree, when known) arms the divisibility
    guards: any pattern rule whose target dim ``tp`` does not divide emits
    a replicated spec with a rate-limited warning instead of a spec the
    mesh placement would have to silently drop (or worse, crash on)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree.structure(params)
    specs = []
    for keypath, leaf in flat:
        specs.append(_spec_for(_leaf_path(keypath),
                               getattr(leaf, "shape", ()), tp))
    return jax.tree.unflatten(treedef, specs)


def validate_tp_specs(params, specs, mesh) -> Any:
    """Sanitize a TP spec tree (model-provided ``tp_specs`` or
    :func:`auto_tp_specs`) against the actual mesh before param placement:
    axis entries absent from the mesh, or whose axis size does not divide
    the dim, fall back to replication on that dim — with a rate-limited
    warning naming the param, so a silent no-split is at least a loud
    no-split. The single divisibility gate the inference engine routes
    EVERY param layout through."""
    from deepspeed_tpu.runtime.zero.partition import sanitize_tp_spec

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    if len(spec_leaves) != len(flat):
        # non-congruent trees are the placement layer's problem (ZeRO rules
        # match specs by tree path); validate only the congruent case
        return specs
    out = []
    for (keypath, leaf), spec in zip(flat, spec_leaves):
        shape = getattr(leaf, "shape", ())
        clean = sanitize_tp_spec(mesh, shape, spec)
        if clean is not None and tuple(clean) != tuple(spec):
            warn_once(
                f"tp specs: {_leaf_path(keypath)} spec {tuple(spec)} does "
                f"not fit shape {tuple(shape)} on mesh "
                f"{dict(mesh.shape)}; replicating the non-dividing dims")
        out.append(clean if clean is not None else spec)
    return jax.tree.unflatten(jax.tree.structure(
        specs, is_leaf=lambda x: isinstance(x, P)), out)
