"""Pluggable scheduling policies for the continuous-batching scheduler.

The scheduler (``inference/scheduler.py``) owns the serving state machine
— admission, chunked prefill / fused decode interleave, retirement,
recompute-preemption — and delegates exactly three decisions to a policy
object:

- :meth:`SchedulingPolicy.select_admission` — WHICH waiting request the
  next admission attempt tries (FIFO: the queue head);
- :meth:`SchedulingPolicy.select_victim` — WHICH running request a
  pool-pressure preemption evicts (FIFO: the latest-admitted);
- :meth:`SchedulingPolicy.admit_ok` — whether a NEW submission is
  accepted at all (admission control: the async front-end consults this
  before enqueueing; a rejection bumps ``serving/rejected_requests`` and
  terminates the request's handle with status ``"rejected"`` instead of
  letting an unbounded queue build under pool pressure);
- :meth:`SchedulingPolicy.select_shed_victim` — WHICH waiting request
  load shedding drops when the always-on loop's queue exceeds
  ``serving.fault.shed_queue_depth`` (default: the lowest-priority
  waiting request, newest arrival on ties — graceful degradation sheds
  the least important, least invested work first).

Determinism contract: every decision is a pure function of scheduler
state that is itself determined by the request trace — arrival order
(``admit_seq`` / queue position), declared ``priority`` / ``ttft_budget``
integers, and the scheduler's LOGICAL step counter (``step_seq``, one
tick per compute action). No wall-clock input: identical request traces
schedule identically across runs and across machines, exactly like the
FIFO pins the serving tests have carried since PR 2. Policies that add
no information (no priorities, no budgets) degrade to FIFO's choices by
construction — their tie-breaks ARE the FIFO rules — which is what lets
the replay tests assert cross-policy agreement on plain traces.

Admission control is shared by every policy (base-class knobs):
``admission_max_queue`` bounds the waiting queue, and
``admission_min_free_blocks`` refuses submissions while the allocator's
free pool (free list + reclaimable cold blocks) is below a floor — both
0 (off) by default, so ``generate_batch``'s closed-loop behavior is
untouched.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Union


class SchedulingPolicy:
    """Base policy = the FIFO rules the scheduler has always used.

    Subclasses override the selection hooks; the admission-control knobs
    live here so every policy composes with them. ``admission_max_queue``
    and ``admission_min_free_blocks`` are plain mutable ints by contract:
    the adaptive controller (``monitor/controller.py``) tightens and
    relaxes them at runtime on the serving thread, between engine
    steps."""

    name = "fifo"

    def __init__(self, admission_max_queue: int = 0,
                 admission_min_free_blocks: int = 0):
        if admission_max_queue < 0 or admission_min_free_blocks < 0:
            raise ValueError("admission control knobs must be >= 0 (0 = off)")
        self.admission_max_queue = int(admission_max_queue)
        self.admission_min_free_blocks = int(admission_min_free_blocks)

    # ---- admission control (submission time) ---- #

    def admit_ok(self, sched, prompt_tokens: int) -> bool:
        """Accept or refuse a NEW submission given current pressure.
        Deterministic in scheduler/allocator state. The closed-loop
        ``generate_batch`` path never consults this (its request set is
        fixed up front); the async front-end calls it per submission."""
        if self.admission_max_queue and \
                len(sched.waiting) >= self.admission_max_queue:
            return False
        if self.admission_min_free_blocks and \
                sched.allocator.num_free < self.admission_min_free_blocks:
            return False
        return True

    # ---- scheduling decisions ---- #

    def select_admission(self, sched) -> int:
        """Index into ``sched.waiting`` of the request the next admission
        attempt should try. FIFO: the head."""
        return 0

    def select_victim(self, sched, requester):
        """The running request a pool-pressure preemption evicts.
        FIFO: the latest-admitted (``running[-1]``) — it has the least
        sunk compute and re-queues at the front."""
        return sched.running[-1]

    def select_shed_victim(self, sched) -> Optional[int]:
        """Index into ``sched.waiting`` of the request load shedding
        drops next, or None to refuse (shedding stops). Default: the
        lowest ``priority`` class; within it the NEWEST arrival (``>=``
        over queue order keeps the latest) — under overload the oldest
        waiting work of each class is the closest to being served, so the
        newest goes first. Deterministic in queue state."""
        victim, vp = None, None
        for i, r in enumerate(sched.waiting):
            p = int(getattr(r, "priority", 0))
            if vp is None or p <= vp:
                victim, vp = i, p
        return victim


class FifoPolicy(SchedulingPolicy):
    """The default: explicit name for the base-class FIFO rules."""
    name = "fifo"


class PriorityPolicy(SchedulingPolicy):
    """Strict priority classes. Admission picks the highest-``priority``
    waiting request (ties: earliest submitted — queue order); preemption
    evicts the lowest-priority running request (ties: latest-admitted,
    the FIFO rule). Requests default to priority 0, so a trace with no
    priorities schedules exactly like FIFO."""

    name = "priority"

    def select_admission(self, sched) -> int:
        best, best_p = 0, None
        for i, r in enumerate(sched.waiting):
            p = int(getattr(r, "priority", 0))
            if best_p is None or p > best_p:   # strict >: earliest wins ties
                best, best_p = i, p
        return best

    def select_victim(self, sched, requester):
        victim = sched.running[-1]
        vp = int(getattr(victim, "priority", 0))
        # scan admission-ordered: <= keeps the LATEST-admitted among the
        # lowest class (the FIFO tie-break)
        for r in sched.running:
            if int(getattr(r, "priority", 0)) <= vp:
                victim, vp = r, int(getattr(r, "priority", 0))
        return victim


class SlaPolicy(SchedulingPolicy):
    """SLA-aware scheduling on TTFT slack.

    Each request may declare ``ttft_budget`` — how many scheduler steps
    (the logical ``step_seq`` clock, NOT wall time: replay-deterministic)
    it can wait past its arrival before its first token is late. Slack =
    ``(arrival_step + budget) - step_seq``; a request that has already
    emitted its first token has met its TTFT forever (+inf slack), and a
    request with no budget declares no SLA (+inf as well).

    Preemption evicts the request with the MOST slack — it can best
    afford the recompute delay — instead of FIFO's latest-admitted (ties:
    latest-admitted, so budget-free traces match FIFO exactly). Admission
    is earliest-deadline-first: the waiting request with the LEAST slack
    admits next (ties: queue order = FIFO)."""

    name = "sla"

    def __init__(self, default_ttft_budget: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.default_ttft_budget = default_ttft_budget

    def _slack(self, sched, r) -> float:
        if r.t_first_token is not None:
            return math.inf              # TTFT already met: preferred victim
        budget = r.ttft_budget if r.ttft_budget is not None \
            else self.default_ttft_budget
        if budget is None:
            return math.inf              # no SLA declared
        return (r.arrival_step + int(budget)) - sched.step_seq

    def select_admission(self, sched) -> int:
        best, best_s = 0, None
        for i, r in enumerate(sched.waiting):
            s = self._slack(sched, r)
            if best_s is None or s < best_s:   # strict <: earliest wins ties
                best, best_s = i, s
        return best

    def select_victim(self, sched, requester):
        victim, vs = None, None
        for r in sched.running:        # admission order; >= keeps the latest
            s = self._slack(sched, r)
            if vs is None or s >= vs:
                victim, vs = r, s
        return victim


POLICIES: Dict[str, type] = {p.name: p for p in
                             (FifoPolicy, PriorityPolicy, SlaPolicy)}


def get_policy(spec: Union[None, str, Dict[str, Any], SchedulingPolicy]
               ) -> SchedulingPolicy:
    """Resolve a policy from its config form: an instance (passed
    through), a name (``"fifo" | "priority" | "sla"``), a dict
    (``{"name": ..., **kwargs}`` — kwargs go to the constructor, e.g.
    ``default_ttft_budget`` / ``admission_max_queue`` /
    ``admission_min_free_blocks``), or None (FIFO)."""
    if spec is None:
        return FifoPolicy()
    if isinstance(spec, SchedulingPolicy):
        return spec
    if isinstance(spec, str):
        name, kwargs = spec, {}
    elif isinstance(spec, dict):
        kwargs = {k: v for k, v in spec.items() if k != "name"}
        name = str(spec.get("name", "fifo"))
    else:
        raise ValueError(f"unsupported policy spec {spec!r}")
    cls = POLICIES.get(name)
    if cls is None:
        raise ValueError(f"unknown scheduling policy {name!r} "
                         f"(expected one of {sorted(POLICIES)})")
    try:
        return cls(**kwargs)
    except TypeError as e:
        raise ValueError(f"bad arguments for policy {name!r}: {e}") from None
