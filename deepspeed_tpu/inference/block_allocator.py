"""KV block allocator for the paged serving path.

The host-side twin of the device pools built by
``models/transformer.py::init_paged_kv_cache``: the pools are
``[n_layer, num_blocks, block_size, KV, Hd]`` arrays, and this allocator
hands out pool block ids to requests and reclaims them when requests retire
or are preempted. The analogue of vLLM's ``BlockAllocator`` — no
reference-counted copy-on-write here (no beam search / prefix sharing yet),
so a block belongs to exactly one request.

Determinism: the free list is FIFO (freed blocks go to the back, allocation
pops from the front, initial order ascending), so identical request streams
produce identical block placements — the scheduler tests pin this.

Block 0 is RESERVED as the dummy block: prompt-bucket padding slots and
inactive decode rows scatter their junk k/v there, and nothing ever reads
it (the attention masks stop at each request's position). Routing junk to a
dedicated block keeps out-of-range scatter clipping from corrupting a live
block.
"""

from __future__ import annotations

from collections import deque
from typing import List, Optional

DUMMY_BLOCK = 0


class BlockAllocator:
    """FIFO free-list allocator over ``num_blocks`` pool blocks of
    ``block_size`` tokens; block 0 (``DUMMY_BLOCK``) is never handed out."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(f"num_blocks={num_blocks}: need at least one "
                             "allocatable block besides the reserved dummy")
        if block_size < 1:
            raise ValueError(f"block_size={block_size} must be positive")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free = deque(range(1, num_blocks))
        # companion set: O(1) double-free detection (the deque alone would
        # make every retirement O(blocks_freed × num_free))
        self._free_set = set(self._free)

    @property
    def num_free(self) -> int:
        return len(self._free)

    def blocks_for_tokens(self, num_tokens: int) -> int:
        """Blocks needed to hold ``num_tokens`` cached tokens."""
        return -(-max(num_tokens, 0) // self.block_size)

    def allocate(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` blocks from the free list, or None (all-or-nothing)
        when fewer than ``n`` are free."""
        if n > len(self._free):
            return None
        got = [self._free.popleft() for _ in range(n)]
        self._free_set.difference_update(got)
        return got

    def free(self, blocks: List[int]) -> None:
        """Return blocks to the back of the free list."""
        for b in blocks:
            if b == DUMMY_BLOCK:
                raise ValueError("attempted to free the reserved dummy block")
            if not (0 < b < self.num_blocks):
                raise ValueError(f"block id {b} out of range")
            if b in self._free_set:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)
            self._free_set.add(b)
