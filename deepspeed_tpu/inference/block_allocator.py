"""KV block allocator for the paged serving path.

The host-side twin of the device pools built by
``models/transformer.py::init_paged_kv_cache``: the pools are
``[n_layer, num_blocks, block_size, KV, Hd]`` arrays, and this allocator
hands out pool block ids to requests and reclaims them when requests retire
or are preempted. The analogue of vLLM's ``BlockAllocator``, including its
automatic prefix caching: blocks are REFERENCE-COUNTED, and with
``prefix_cache=True`` every FULL block is content-addressed by a rolling
hash chain ``key_j = H(key_{j-1}, tokens_j)`` so a new request whose prompt
starts with an already-cached token prefix reuses those blocks with a
ref-count bump — zero prefill compute for the shared part.

Lifecycle of a block (prefix_cache on)::

    free list --allocate--> ref>=1 --free to ref 0--+--> registered? cold LRU
        ^                      ^                    |        |        |
        |                      +----- acquire ------+--------+   reclaimed
        +------------------------- (unregistered) ----- under pressure

- ``allocate`` pops the FIFO free list first; when it runs dry it reclaims
  from the COLD list oldest-first (LRU), un-registering the reclaimed
  block's hash entry. All-or-nothing, deterministic.
- ``free`` drops one reference; at zero the block parks on the cold list
  (content intact, future prefix hits resurrect it via ``acquire``) if it
  was registered, else returns to the free list.
- Partial trailing blocks are never registered, so they are never shared;
  a request that would start writing inside a shared block must
  copy-on-write it first (the scheduler's COW split — see
  ``scheduler.py``).

Determinism: the free list is FIFO (freed blocks go to the back, allocation
pops from the front, initial order ascending), the cold list is reclaimed
strictly LRU, and hash-table registration is first-writer-wins — identical
request streams produce identical block placements (the scheduler tests
pin this).

Block 0 is RESERVED as the dummy block: prompt-bucket padding slots and
inactive decode rows scatter their junk k/v there, and nothing ever reads
it (the attention masks stop at each request's position). Routing junk to a
dedicated block keeps out-of-range scatter clipping from corrupting a live
block.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

DUMMY_BLOCK = 0

# root of every hash chain (the "parent" of a sequence's first block)
ROOT_KEY = b""


class BlockAllocator:
    """Reference-counted FIFO allocator over ``num_blocks`` pool blocks of
    ``block_size`` tokens; block 0 (``DUMMY_BLOCK``) is never handed out.
    With ``prefix_cache=True``, full blocks are content-addressed and
    freed-but-cached blocks are kept COLD for reuse until allocation
    pressure reclaims them LRU-first."""

    def __init__(self, num_blocks: int, block_size: int,
                 prefix_cache: bool = False):
        if num_blocks < 2:
            raise ValueError(f"num_blocks={num_blocks}: need at least one "
                             "allocatable block besides the reserved dummy")
        if block_size < 1:
            raise ValueError(f"block_size={block_size} must be positive")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.prefix_cache = prefix_cache
        self._free = deque(range(1, num_blocks))
        # companion set: O(1) membership for the free-list invariant checks
        self._free_set = set(self._free)
        self._ref: Dict[int, int] = {}          # block -> live references
        self._num_used = 0                       # blocks with ref > 0
        # content-addressed cache state (only populated when prefix_cache)
        self._cold: "OrderedDict[int, bytes]" = OrderedDict()  # LRU: old first
        self._table: Dict[bytes, int] = {}       # chain key -> block id
        self._key_of: Dict[int, bytes] = {}      # registered block -> its key
        # tiered KV cache (inference/kv_host_pool.py): when a host pool is
        # attached, reclaiming a cold block DEMOTES it — the spill hook
        # (engine-bound: it owns the pools and the D2H gather program)
        # copies the block's content host-side under its chain key before
        # the block id is reused — and the tiered match walk below finds
        # demoted chains for re-materialization on admission
        self.host_pool = None
        self._spill_fn = None       # (block, key) -> bool; session-scoped

    # ------------------------------------------------------------------ #
    # capacity accounting

    @property
    def num_free(self) -> int:
        """Blocks allocatable right now (free list + reclaimable cold)."""
        return self.num_blocks - 1 - self._num_used

    @property
    def num_free_list(self) -> int:
        """Blocks on the plain free list ONLY — allocating this many never
        reclaims a cold cached block (no prefix-cache registration is
        destroyed). Opportunistic consumers (the speculative verify window)
        bound themselves here so best-effort capacity never cannibalizes
        the cache that mandatory allocation would have hit."""
        return len(self._free)

    @property
    def num_used(self) -> int:
        """Blocks referenced by at least one live request."""
        return self._num_used

    @property
    def num_cold(self) -> int:
        """Freed-but-cached blocks (content retained for prefix hits)."""
        return len(self._cold)

    @property
    def capacity(self) -> int:
        """Total allocatable blocks (everything but the reserved dummy)."""
        return self.num_blocks - 1

    def blocks_for_tokens(self, num_tokens: int) -> int:
        """Blocks needed to hold ``num_tokens`` cached tokens."""
        return -(-max(num_tokens, 0) // self.block_size)

    def ref_count(self, block: int) -> int:
        return self._ref.get(block, 0)

    def leak_report(self) -> Dict[int, int]:
        """Blocks still referenced — empty once every request retired
        (the test-suite teardown assertion; cold blocks are NOT leaks)."""
        return {b: r for b, r in self._ref.items() if r > 0}

    # ------------------------------------------------------------------ #
    # allocate / free / acquire

    def allocate(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` blocks (ref-count 1 each), or None (all-or-nothing)
        when fewer than ``n`` are available. The FIFO free list is drained
        first; under pressure the cold list is reclaimed LRU-first, each
        reclaimed block losing its cache registration."""
        if n > len(self._free) + len(self._cold):
            return None
        got: List[int] = []
        for _ in range(n):
            if self._free:
                b = self._free.popleft()
                self._free_set.discard(b)
            else:
                b, key = self._cold.popitem(last=False)   # LRU eviction
                if self._spill_fn is not None:
                    # demote instead of destroy: the hook D2H-copies the
                    # block's content into the host pool under its chain
                    # key (dispatched BEFORE the new owner's writes, so
                    # stream order reads the pre-overwrite content); hook
                    # failures degrade to today's destroy-on-reclaim and
                    # never surface here
                    self._spill_fn(b, key)
                del self._table[key]
                del self._key_of[b]
            self._ref[b] = 1
            self._num_used += 1
            got.append(b)
        return got

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per block; zero-ref registered blocks park on
        the cold list (MRU end), unregistered ones rejoin the free list."""
        for b in blocks:
            if b == DUMMY_BLOCK:
                raise ValueError("attempted to free the reserved dummy block")
            if not (0 < b < self.num_blocks):
                raise ValueError(f"block id {b} out of range")
            r = self._ref.get(b, 0)
            if r <= 0:
                raise ValueError(f"double free of block {b}")
            r -= 1
            self._ref[b] = r
            if r == 0:
                self._num_used -= 1
                key = self._key_of.get(b)
                if key is not None:
                    self._cold[b] = key           # most-recently-used end
                else:
                    self._free.append(b)
                    self._free_set.add(b)

    def acquire(self, blocks: List[int]) -> None:
        """Bump the reference count of already-placed blocks (a prefix-cache
        hit). Cold blocks are resurrected (removed from the LRU list)."""
        for b in blocks:
            r = self._ref.get(b, 0)
            if r == 0:
                if b not in self._cold:
                    raise ValueError(
                        f"acquire of block {b} which is neither live nor "
                        "cold (stale prefix-cache hit?)")
                del self._cold[b]
                self._num_used += 1
            self._ref[b] = r + 1

    # ------------------------------------------------------------------ #
    # content-addressed prefix cache

    @staticmethod
    def chain_key(parent: bytes, tokens) -> bytes:
        """Rolling hash of (parent-block key, this block's tokens): the
        content address of a full block. blake2b-128 over exact bytes —
        deterministic across processes, collision odds negligible."""
        h = hashlib.blake2b(digest_size=16)
        h.update(parent)
        h.update(np.ascontiguousarray(tokens, dtype=np.int32).tobytes())
        return h.digest()

    def match_prefix(self, tokens) -> Tuple[List[int], List[bytes]]:
        """Longest chain of cached FULL blocks matching the front of
        ``tokens``. Read-only (no ref-count changes — callers ``acquire``
        the hit only once the rest of the admission succeeds). Returns
        ([block ids], [chain keys])."""
        if not self.prefix_cache:
            return [], []
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        blocks: List[int] = []
        keys: List[bytes] = []
        parent = ROOT_KEY
        for j in range(tokens.size // bs):
            key = self.chain_key(parent, tokens[j * bs:(j + 1) * bs])
            b = self._table.get(key)
            if b is None:
                break
            blocks.append(b)
            keys.append(key)
            parent = key
        return blocks, keys

    def register(self, block: int, key: bytes) -> bool:
        """Publish a FULL block under its chain key so future admissions can
        hit it. First-writer-wins: a key already registered (two requests
        racing the same prefix) keeps the existing mapping and this block
        stays private. Returns True when the registration took."""
        if not self.prefix_cache or block == DUMMY_BLOCK:
            return False
        if key in self._table or block in self._key_of:
            return False
        self._table[key] = block
        self._key_of[block] = key
        if self.host_pool is not None:
            # a device registration supersedes any host copy of the same
            # content (a recompute landed the identical bytes on device) —
            # a chain key lives in at most one tier. Safe against the
            # speculative optimistic-register-then-rollback flow: under
            # greedy-only speculation a rolled-back candidate chain can
            # only collide with a demoted COMMITTED key if the model
            # would re-commit those exact tokens — in which case verify
            # accepts them and no rollback happens (revisit if sampled
            # speculation ever registers candidate-keyed blocks).
            self.host_pool.discard(key)
        return True

    # ------------------------------------------------------------------ #
    # tiered KV cache (host-RAM spill pool)

    def attach_host_pool(self, host_pool) -> None:
        """Attach (or detach with None) the host-memory tier. Attaching
        makes the tiered match walk probe demoted chains; demotion itself
        additionally needs a spill hook (:meth:`set_spill`)."""
        self.host_pool = host_pool if self.prefix_cache else None

    def set_spill(self, spill_fn) -> None:
        """Install the session-scoped demotion hook ``(block, key) ->
        bool``. The hook is engine-bound (it reads the live pools and runs
        the jitted per-block gather), must never raise, and is cleared at
        session close — a stale hook would capture freed pool buffers."""
        self._spill_fn = spill_fn if self.host_pool is not None else None

    def match_prefix_tiered(self, tokens) -> Tuple[List[Tuple], List[bytes]]:
        """Longest chain of cached FULL blocks matching the front of
        ``tokens`` across BOTH tiers: each chain position resolves to
        ``("dev", block_id)`` (device-registered) or ``("host", key)``
        (demoted to the host pool), stopping at the first key in neither.
        Read-only — no ref counts, no host LRU reordering. With no host
        pool attached this degenerates to :meth:`match_prefix`."""
        if not self.prefix_cache:
            return [], []
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        entries: List[Tuple] = []
        keys: List[bytes] = []
        parent = ROOT_KEY
        for j in range(tokens.size // bs):
            key = self.chain_key(parent, tokens[j * bs:(j + 1) * bs])
            b = self._table.get(key)
            if b is not None:
                entries.append(("dev", b))
            elif self.host_pool is not None and self.host_pool.contains(key):
                entries.append(("host", key))
            else:
                break
            keys.append(key)
            parent = key
        return entries, keys

    def demote_chain(self, tokens) -> int:
        """Force-demote the COLD cached FULL blocks of ``tokens``'s hash
        chain into the host tier — the prefill→decode KV handoff's push
        half (``inference/router.py``): after a prefill replica commits a
        prompt's blocks, demoting them publishes the content in the
        SHARED host pool, where a decode replica's tiered admission walk
        finds it and re-materializes H2D (the PR-12 fetch path — the host
        tier is the transport, no new wire format).

        Per matched chain position: a block still referenced by a live
        request is left on device untouched (it is serving traffic here —
        and unregistering it would violate the one-tier-per-key
        invariant), a key already host-resident just extends the walk,
        and a cold block is spilled via the session hook then freed +
        unregistered (device copy gone, host copy authoritative). A spill
        hook failure keeps the device copy — demotion is best-effort
        cache movement, never data loss. Returns the number of blocks
        demoted."""
        if (not self.prefix_cache or self.host_pool is None
                or self._spill_fn is None):
            return 0
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        parent = ROOT_KEY
        demoted = 0
        for j in range(tokens.size // bs):
            key = self.chain_key(parent, tokens[j * bs:(j + 1) * bs])
            b = self._table.get(key)
            if b is None:
                if self.host_pool.contains(key):
                    parent = key
                    continue          # already demoted: keep walking
                break                 # key in neither tier: chain ends
            parent = key
            if b not in self._cold:
                continue              # hot: a live request holds it
            if not self._spill_fn(b, key):
                continue              # spill failed: keep the device copy
            del self._cold[b]
            del self._table[key]
            del self._key_of[b]
            self._free.append(b)
            self._free_set.add(b)
            demoted += 1
        return demoted

    def host_consistency(self) -> List[str]:
        """Tier-discipline violations (empty = consistent): the host
        pool's own invariants plus the cross-tier rule that a chain key
        lives in at most one tier. The conftest ``_no_kv_block_leaks``
        fixture asserts this on every drained scheduler — demoted blocks
        are cache copies, never leaks."""
        if self.host_pool is None:
            return []
        probs = self.host_pool.consistency_report()
        for key in self.host_pool.keys():
            if key in self._table:
                probs.append(
                    f"chain key {key.hex()[:12]} registered on device "
                    f"(block {self._table[key]}) AND resident in the host "
                    "pool — a key must live in exactly one tier")
        return probs

    def unregister_if_owner(self, block: int, key: bytes) -> bool:
        """Withdraw ``block``'s registration under ``key`` — the rollback
        half of speculative decoding: a block that filled DURING a verify
        window was registered with candidate tokens in its hash chain, and
        when those candidates are rejected its tail slots will be
        overwritten by the real continuation, so the key would describe
        content that no longer exists. First-writer-wins is preserved: when
        ``key`` maps to a DIFFERENT block (another request registered the
        same content first, so this block's ``register`` never took — that
        owner's content IS committed) the mapping is left untouched.
        Returns True when the registration was removed.

        Callers normally roll back while still holding a reference to the
        block; a zero-ref block parked COLD under this key loses its only
        address, so it is moved back to the free list (nothing can ever
        resurrect it)."""
        if not self.prefix_cache:
            return False
        if self._table.get(key) != block or self._key_of.get(block) != key:
            return False
        del self._table[key]
        del self._key_of[block]
        if block in self._cold:
            del self._cold[block]
            self._free.append(block)
            self._free_set.add(block)
        return True
