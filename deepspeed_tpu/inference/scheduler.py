"""Continuous-batching scheduler (Orca iteration-level scheduling + vLLM
eviction/prefix-caching + Sarathi-style chunked prefill, host side).

The engine drives one *step* at a time: :meth:`next_action` returns one of

- ``("prefill", request)`` — admit the FIFO queue head into freshly
  allocated blocks and run its whole prompt (the legacy path: no prefix
  hit, chunking off);
- ``("prefill_chunk", request)`` — run the next ``chunk_tokens`` tokens of
  a mid-prefill request against its already-cached blocks (used for the
  tail after a prefix-cache hit and for chunked prefill, which interleaves
  with decode steps instead of stalling every running decode for a whole
  long prompt);
- ``("decode", running)`` — one fused decode step over every running
  request that finished prefilling;
- ``("verify", running)`` — the speculative form of the decode step
  (``spec_k > 0`` with an n-gram proposer): each request carries up to
  ``spec_k`` proposed candidate tokens in ``req.spec_tokens`` and one
  fused verify step checks all of them at once, emitting the accepted
  prefix plus one token — requests with no match ride along with an
  empty window (single-token decode inside the same program), and a
  step where NO request found a match degrades to plain ``decode``.

Finished requests retire between steps (their blocks return to the pool)
and queued requests take their slots, so a convoying long request never
stalls the batch the way the static ``generate`` loop does.

**Speculative decoding** (``spec_k``/``spec_proposer``): before a decode
turn, each decode-ready request's prompt + generated history is handed to
the proposer (``inference/spec.py``) and the candidates' KV slots are
secured up front — window growth only draws on the free pool (free list +
reclaimable cold blocks) and TRUNCATES the window when it runs dry, never
preempting: speculation must not evict work plain decode would have kept,
so eviction behavior is identical with speculation on or off. After the
engine's greedy acceptance, :meth:`record_verify` commits the accepted
tokens and ROLLS BACK the rest: ``pos`` rewinds past the rejected
candidates (their k/v stays in the pools beyond ``pos`` — never read,
overwritten as decode advances) and any block that crossed its fill
boundary inside the rejected span is unregistered from the prefix cache
via ``unregister_if_owner`` — unless a first writer already owned the
hash, in which case that owner's (committed) content keeps the mapping.

Request lifecycle::

    QUEUED --admit(probe cache, alloc tail)--> RUNNING[prefilling]
       ^                                           |  chunks until pos==target
       |                                       RUNNING --eos/max_new--> FINISHED
       +--------- preempt (free ALL blocks) -------+

**Automatic prefix caching** (``prefix_caching=True``): admission probes
the allocator's content-addressed cache with the request's token prefix.
Matching FULL blocks are reused with a ref-count bump (zero prefill
compute) and only the tail is allocated + prefilled, with the request's
``pos`` starting past the cached tokens. When the ENTIRE prefix is cached
the hit is capped at ``target - 1`` tokens — logits for the last token
must still be computed to sample the continuation — which lands the
restart mid-block inside a shared block: that block is copied-on-write
(``cow_pending``: the engine device-copies it into a private block before
the tail chunk runs) because partial blocks are never shared. As a
request's blocks fill — during prefill chunks AND as decode crosses block
boundaries — they are registered back into the cache, so repeated system
prompts, multi-turn continuations, and even a preempted request's own
re-admission hit.

**Tiered KV cache** (``serving.kv_host``): with a host pool attached to
the allocator, admission's cache probe walks BOTH tiers
(``match_prefix_tiered``) — device hits acquire as always, host hits
(cold blocks demoted to host RAM instead of destroyed) read their bytes
at admission, take freshly allocated device blocks, and ride
``req.fetch_pending`` to the engine, which lands them H2D before the
request's first prefill work: a host hit is a cache hit whose tail needs
only H2D, not recompute. Promoted blocks register under their chain keys
only once the copy lands, so a preemption between admission and fetch
loses nothing (the host entries survive).

Preemption is recompute-style (vLLM's default): when a running request
needs one more KV block and the pool (free + reclaimable cold blocks) is
dry, the policy-selected victim — LATEST-admitted under the default FIFO
policy; ``inference/policy.py`` plugs in priority-class and SLA-aware
(most-TTFT-slack) victim choice, plus which waiting request admits next —
is evicted: its blocks are dereferenced and it re-queues at the FRONT
with its prompt extended by the tokens it already generated. With prefix caching on, its own still-cold
blocks usually satisfy the re-admission probe, so "recompute" preemption
costs a cache hit instead of a full re-prefill. Victim choice, the FIFO
free list, the LRU cold list, and the prefill/decode interleave toggle are
all deterministic — identical request streams schedule identically.

Bookkeeping invariant: ``req.pos`` is the number of tokens whose k/v sit in
the pools; the newest generated token (``req.last_token``) is NOT yet
cached — it is the next decode step's input, written at slot ``pos`` by
that step. Hence cached = prompt + generated[:-1], pos = len(prompt) +
len(generated) - 1 whenever the request is running (and past prefill).
While prefilling, ``pos < prefill_target == len(prefix())`` counts the
chunked/cache-hit progress.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.block_allocator import ROOT_KEY, BlockAllocator
from deepspeed_tpu.utils.logging import logger

QUEUED, RUNNING, FINISHED = "queued", "running", "finished"


class PoolExhausted(RuntimeError):
    """The KV pool cannot supply one more block for ``req`` and there is
    nothing to evict. The closed loop propagates this (a misconfigured
    pool should fail the call loudly); the always-on loop catches it and
    retires ``req`` with an error instead — one oversized request must
    not take the server down for everyone else."""

    def __init__(self, msg: str, req: "Request"):
        super().__init__(msg)
        self.req = req


class ServingTelemetry:
    """Registry adapter for the Orca/vLLM-style iteration-level serving
    stats: the scheduler calls these hooks as its state machine moves and
    the series land in the process-global metrics registry
    (``deepspeed_tpu.monitor.metrics``).

    Invariants the tests pin: TTFT is observed exactly ONCE per request —
    the first token after the ORIGINAL arrival, even when a preemption
    forces a re-prefill later — and ``serving/preemptions`` equals the
    number of eviction events (``serving/recompute_tokens`` the prefix
    tokens those evictions will prefill again). With prefix caching,
    ``serving/prefix_cache_hit_tokens`` counts prompt tokens whose prefill
    was SKIPPED via cache hits (hits / lookups is the admission hit rate),
    and ``serving/cold_blocks`` gauges the freed-but-cached pool blocks."""

    _SERIES = ("ttft", "tpot", "queue_wait", "queue_depth", "running",
               "kv_blocks_used",
               "kv_blocks_free", "kv_block_utilization", "kv_fragmentation",
               "cold_blocks", "prefill_steps", "prefill_chunks",
               "decode_steps", "prefix_cache_lookups", "prefix_cache_hits",
               "prefix_cache_hit_tokens",
               "kv_host_blocks", "kv_host_bytes", "kv_spills",
               "kv_fetch_hits", "kv_fetch_tokens", "kv_host_errors",
               "preemptions", "recompute_tokens", "requests", "finished",
               "rejected_requests",
               "generated_tokens", "spec_verify_steps",
               "spec_proposed_tokens", "spec_accepted_tokens",
               "spec_rollbacks", "spec_acceptance_rate", "tp",
               "step_faults", "engine_restarts", "request_retries",
               "timeouts", "shed_requests", "phase_ms", "wasted_tokens")

    def __init__(self, registry=None, replica: str = "r0"):
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self.registry = registry
        #: replica label stamped on phase/waste observations — mutable,
        #: the router renames engines after construction (set_replica)
        self.replica = replica
        self.ensure()

    def ensure(self) -> None:
        """Pre-create every serving family so zero-valued series (e.g. a
        run with no preemptions) still appear in snapshots. Re-run by the
        scheduler per serve call — re-creates after a registry reset."""
        for name in self._SERIES:
            getattr(self, name)

    # families resolved per access (get-or-create under the registry
    # lock; serving events are host-side per engine step, not a jit hot
    # loop) so a registry reset between bench metrics can't orphan them

    @property
    def ttft(self):
        return self.registry.histogram(
            "serving/ttft_ms", "request arrival -> first generated token")

    @property
    def tpot(self):
        return self.registry.histogram(
            "serving/tpot_ms", "per-output-token latency after the first")

    @property
    def queue_wait(self):
        return self.registry.histogram(
            "serving/queue_wait_ms",
            "request submission -> first admission wait (one observation "
            "per request; preemption re-admissions do not re-observe)")

    @property
    def rejected_requests(self):
        return self.registry.counter(
            "serving/rejected_requests",
            "submissions refused by admission control (queue bound / pool "
            "pressure) before enqueueing")

    @property
    def queue_depth(self):
        return self.registry.gauge(
            "serving/queue_depth", "requests waiting for admission")

    @property
    def running(self):
        return self.registry.gauge(
            "serving/running", "running-batch occupancy (fused decode rows)")

    @property
    def kv_blocks_used(self):
        return self.registry.gauge(
            "serving/kv_blocks_used",
            "pool blocks referenced by live requests (excl. dummy)")

    @property
    def kv_blocks_free(self):
        return self.registry.gauge(
            "serving/kv_blocks_free",
            "allocatable pool blocks: free list + reclaimable cold "
            "(GLOBAL per slice under tensor parallelism — block ids are "
            "shard-invariant, see serving/tp)")

    @property
    def tp(self):
        return self.registry.gauge(
            "serving/tp",
            "tensor-parallel degree of the serving mesh: KV pools are "
            "head-sharded over tp, so block-count gauges are global per "
            "slice while per-shard pool BYTES are 1/tp")

    @property
    def kv_block_utilization(self):
        return self.registry.gauge(
            "serving/kv_block_utilization", "used / allocatable pool blocks")

    @property
    def kv_fragmentation(self):
        return self.registry.gauge(
            "serving/kv_fragmentation",
            "internal fragmentation: unfilled slot fraction of referenced "
            "blocks (capacity minus cached tokens; shared blocks counted "
            "once)")

    @property
    def cold_blocks(self):
        return self.registry.gauge(
            "serving/cold_blocks",
            "freed-but-cached blocks held for prefix reuse (LRU-reclaimed "
            "under allocation pressure)")

    @property
    def prefill_steps(self):
        return self.registry.counter(
            "serving/prefill_steps", "request admissions that scheduled "
            "prefill work (one per admission, however many chunks)")

    @property
    def prefill_chunks(self):
        return self.registry.counter(
            "serving/prefill_chunks",
            "chunked-prefill compute steps (incl. cache-hit tail chunks)")

    @property
    def decode_steps(self):
        return self.registry.counter(
            "serving/decode_steps", "fused decode steps (all rows at once)")

    @property
    def prefix_cache_lookups(self):
        return self.registry.counter(
            "serving/prefix_cache_lookups", "admission-time cache probes")

    @property
    def prefix_cache_hits(self):
        return self.registry.counter(
            "serving/prefix_cache_hits",
            "admission probes that matched at least one full block")

    @property
    def prefix_cache_hit_tokens(self):
        return self.registry.counter(
            "serving/prefix_cache_hit_tokens",
            "prompt tokens whose prefill was skipped via cache hits")

    @property
    def kv_host_blocks(self):
        return self.registry.gauge(
            "serving/kv_host_blocks",
            "demoted KV blocks resident in the host-RAM tier (tiered KV "
            "cache; LRU-bounded by serving.kv_host.max_host_blocks)")

    @property
    def kv_host_bytes(self):
        return self.registry.gauge(
            "serving/kv_host_bytes",
            "host RAM held by demoted KV blocks (k+v slices)")

    @property
    def kv_spills(self):
        return self.registry.counter(
            "serving/kv_spills",
            "cold blocks demoted D2H to the host pool instead of being "
            "destroyed under allocation pressure")

    @property
    def kv_fetch_hits(self):
        return self.registry.counter(
            "serving/kv_fetch_hits",
            "admission prefix probes served from the host tier: demoted "
            "blocks re-materialized H2D instead of recomputed (counted "
            "per block)")

    @property
    def kv_fetch_tokens(self):
        return self.registry.counter(
            "serving/kv_fetch_tokens",
            "prompt tokens whose prefill was skipped via host-tier "
            "fetches (subset of prefix_cache_hit_tokens)")

    @property
    def kv_host_errors(self):
        return self.registry.counter(
            "serving/kv_host_errors",
            "D2H/H2D failures degraded to destroy-on-reclaim / recompute "
            "(allocation errors, injected I/O faults)")

    @property
    def preemptions(self):
        return self.registry.counter(
            "serving/preemptions", "recompute-preempt eviction events")

    @property
    def recompute_tokens(self):
        return self.registry.counter(
            "serving/recompute_tokens",
            "prefix tokens re-prefilled by evictions")

    @property
    def requests(self):
        return self.registry.counter("serving/requests")

    @property
    def finished(self):
        return self.registry.counter("serving/finished_requests")

    @property
    def generated_tokens(self):
        return self.registry.counter("serving/generated_tokens")

    @property
    def spec_verify_steps(self):
        return self.registry.counter(
            "serving/spec_verify_steps",
            "fused speculative verify steps (all rows at once)")

    @property
    def spec_proposed_tokens(self):
        return self.registry.counter(
            "serving/spec_proposed_tokens",
            "candidate tokens proposed by the n-gram speculator")

    @property
    def spec_accepted_tokens(self):
        return self.registry.counter(
            "serving/spec_accepted_tokens",
            "proposed candidates greedy verification accepted")

    @property
    def spec_rollbacks(self):
        return self.registry.counter(
            "serving/spec_rollbacks",
            "verify steps that rejected candidates (pos rewound, "
            "uncommitted prefix-cache registrations withdrawn)")

    @property
    def spec_acceptance_rate(self):
        return self.registry.gauge(
            "serving/spec_acceptance_rate",
            "accepted / proposed candidate tokens (cumulative)")

    # ---- serving-plane fault tolerance (inference/serve.py) ---- #

    @property
    def step_faults(self):
        return self.registry.counter(
            "serving/step_faults",
            "engine-step exceptions contained by the serving loop, by "
            "dispatch site (per-request retry/quarantine or engine "
            "restart — the loop survived either way)", labelnames=("kind",))

    @property
    def engine_restarts(self):
        return self.registry.counter(
            "serving/engine_restarts",
            "crash-safe engine recoveries: pool workspace + fused jits "
            "rebuilt, in-flight requests re-admitted from prompt+generated")

    @property
    def request_retries(self):
        return self.registry.counter(
            "serving/request_retries",
            "per-request fault retries: the faulting action's requests "
            "re-queued through recompute-preemption with logical-step "
            "backoff")

    @property
    def timeouts(self):
        return self.registry.counter(
            "serving/timeouts",
            "requests retired for exceeding their deadline (deadline_ms "
            "wall clock / deadline_steps scheduler clock)")

    @property
    def shed_requests(self):
        return self.registry.counter(
            "serving/shed_requests",
            "queued requests dropped by load shedding under queue "
            "pressure (policy select_shed_victim, lowest priority first)")

    # ---- request latency anatomy (phase ledger) ---- #

    @property
    def phase_ms(self):
        return self.registry.histogram(
            "serving/phase_ms",
            "per-request latency anatomy, one histogram per phase and "
            "replica: TTFT = intake + queue + prefill (+ fetch) + "
            "first decode; TPOT = scheduler wait + decode step. Phases "
            "with device work observe at the recorder's sync points, so "
            "they populate when telemetry.events is on",
            labelnames=("phase", "replica"))

    @property
    def wasted_tokens(self):
        return self.registry.counter(
            "serving/wasted_tokens",
            "tokens whose compute produced no delivered output, by cause: "
            "recompute (preemption re-prefill), spec_reject (verify "
            "rollback), timeout / shed (retired unfinished), failover "
            "(sibling re-derived a failed replica's progress) — the "
            "goodput-vs-throughput gap", labelnames=("cause", "replica"))

    def phase(self, phase: str, ms: float, rid=None) -> None:
        """Observe one phase-ledger sample (exemplar = the request id, so
        a p99 bucket links back to the merged trace's request track)."""
        self.phase_ms.labels(phase=phase, replica=self.replica).observe(
            ms, exemplar={"rid": str(rid)} if rid is not None else None)

    def waste(self, cause: str, n) -> None:
        """Count wasted tokens (``n == 0`` still materializes the series,
        so a fleet scrape shows every cause it is tracking)."""
        self.wasted_tokens.labels(cause=cause,
                                  replica=self.replica).inc(int(n))


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray              # [P] int32, immutable
    max_new: int
    eos: Optional[int] = None
    state: str = QUEUED
    blocks: List[int] = dataclasses.field(default_factory=list)
    pos: int = 0                    # tokens currently cached in the pools
    generated: List[int] = dataclasses.field(default_factory=list)
    admit_seq: int = -1             # admission stamp (eviction order)
    preemptions: int = 0
    t_arrival: float = 0.0          # perf_counter at add_request
    t_submit: float = 0.0           # perf_counter at SUBMISSION (async
    # front-end hand-off; == t_arrival for closed-loop generate_batch) —
    # the serving/queue_wait_ms base
    t_first_token: Optional[float] = None   # TTFT stamp (set once, ever)
    t_last_token: float = 0.0       # previous token's stamp (TPOT base)
    # ---- causal trace context (fleet tracing) ----
    trace: Optional[str] = None     # trace id minted at router intake and
    # carried across the prefill->decode handoff (requests sharing it are
    # one causal chain; the fleet renderer stitches them with flow arrows)
    parent: Optional[int] = None    # parent span = the rid of the
    # upstream hop (the prefill-side warm rid on the decode replica)
    # ---- scheduling-policy inputs (inference/policy.py) ----
    priority: int = 0               # PriorityPolicy class (higher = sooner)
    ttft_budget: Optional[int] = None  # SlaPolicy: scheduler steps past
    # arrival_step before the first token is late (logical clock, not ms)
    arrival_step: int = 0           # sched.step_seq at enqueue
    cancelled: bool = False         # retired by cancellation, not eos/max
    # ---- deadlines / fault containment (serving.fault) ----
    deadline_ms: Optional[float] = None   # wall-clock budget from t_submit;
    # expiry retires the request as timeout (checked at scheduler action
    # boundaries + the async front-end's intake)
    deadline_steps: Optional[int] = None  # logical-step budget on the
    # scheduler clock (like ttft_budget: replay-deterministic)
    timed_out: bool = False         # retired by deadline expiry
    shed: bool = False              # dropped by load shedding while queued
    retry_count: int = 0            # per-request step-fault retries so far
    retry_at_step: int = 0          # backoff hold-down: not admittable
    # before sched.step_seq reaches this (exponential in logical steps)
    # ---- prefix caching / chunked prefill state ----
    prefilling: bool = False        # admitted but pos < prefill_target
    prefill_target: int = 0         # len(prefix()) captured at admission
    keys: List[bytes] = dataclasses.field(default_factory=list)
    # chain keys of this request's REGISTERED-or-matched full blocks
    cow_pending: Optional[Tuple[int, int]] = None  # (src, dst) device copy
    # host-tier fetches the engine must land H2D before this request's
    # next prefill work: (dst_block, chain_key_or_None, k_np, v_np,
    # tokens) per demoted block — key None for the COW split's private
    # (unregistered) copy, tokens the prompt tokens the fetch saves from
    # recompute (the engine's kv_fetch counter base). Bytes in hand, so a
    # host-LRU eviction after admission is safe.
    fetch_pending: List[Tuple] = dataclasses.field(default_factory=list)
    error: Optional[str] = None     # set when retired without completing
    # ---- speculative decoding state ----
    spec_tokens: Tuple[int, ...] = ()  # candidates for the pending verify

    def prefix(self) -> np.ndarray:
        """The token prefix a (re)admission must have cached before decode
        resumes: the prompt plus every already-generated token. Prefill
        caches k/v for ALL of them (minus any prefix-cache hit) and samples
        the next (new) token from the last position — so a recomputed
        request continues exactly where it left off (greedy decoding
        reproduces the unpreempted continuation)."""
        if not self.generated:
            return self.prompt
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])

    @property
    def last_token(self) -> Optional[int]:
        return self.generated[-1] if self.generated else None

    @property
    def output(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


class ContinuousBatchingScheduler:
    """FIFO admission (with optional prefix-cache probe), chunked prefill
    interleaved with fused decode over all running requests, retire on
    eos/max_new, recompute-preempt the latest-admitted request on OOM."""

    def __init__(self, allocator: BlockAllocator, max_running: int,
                 max_blocks_per_seq: int,
                 telemetry: Optional[ServingTelemetry] = None,
                 prefix_caching: bool = False, chunk_tokens: int = 0,
                 events=None, rid_base: int = 0,
                 spec_k: int = 0, spec_proposer=None, policy=None):
        if max_running < 1:
            raise ValueError("max_running must be >= 1")
        if chunk_tokens < 0:
            raise ValueError("chunk_tokens must be >= 0 (0 = whole-prompt)")
        if spec_k < 0:
            raise ValueError("spec_k must be >= 0 (0 = speculation off)")
        self.allocator = allocator
        self.max_running = max_running
        self.max_blocks_per_seq = max_blocks_per_seq
        self.prefix_caching = prefix_caching and allocator.prefix_cache
        # chunk_tokens and spec_k are runtime-mutable by contract: the
        # adaptive controller (monitor/controller.py) lowers them under
        # SLO burn and restores them under headroom, always between steps
        # on the serving thread, and only to values inside the compile
        # buckets the engine already owns (128-multiple chunks; spec k
        # within its fixed pow2 verify window)
        self.chunk_tokens = chunk_tokens
        # speculative decoding: propose up to spec_k candidates per decode-
        # ready request and verify them in one fused step (0/None = off)
        self.spec_k = spec_k if spec_proposer is not None else 0
        self.spec_proposer = spec_proposer
        # plain host counters, always on (the engine/tests read step
        # accounting from here even with the metrics registry disabled):
        # accepted_tokens_per_step = emitted_tokens / (decode + verify)
        self.stats = {"decode_steps": 0, "verify_steps": 0,
                      "emitted_tokens": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_rollbacks": 0,
                      "preemptions": 0}
        self.telemetry = telemetry
        # flight recorder (monitor/events.py): None when disabled, so
        # every emit site below gates at one None check
        self.events = events
        if telemetry is not None:
            telemetry.ensure()
        # scheduling policy (inference/policy.py): admission pick, victim
        # pick, submission-time admission control. None = the FIFO rules
        # every decision here used before policies existed.
        if policy is None:
            from deepspeed_tpu.inference.policy import FifoPolicy
            policy = FifoPolicy()
        self.policy = policy
        # logical clock: one tick per compute action handed to the engine.
        # SlaPolicy measures TTFT slack against THIS (replay-deterministic),
        # never against wall time.
        self.step_seq = 0
        self.waiting: deque = deque()
        self.running: List[Request] = []   # admission-ordered
        self.finished: List[Request] = []
        self._admit_counter = 0
        # rid_base: the engine threads a per-engine offset through so rids
        # stay unique ACROSS generate_batch calls — the flight recorder's
        # request identity must not collide between serve calls
        self._next_rid = int(rid_base)
        # prefill/decode interleave: after a chunk, give decode a turn (when
        # decodable rows exist) so one long prompt never monopolizes steps
        self._decode_turn = False
        # deadline-free workloads (every closed-loop generate_batch, any
        # serve that never sets a deadline) must not pay the per-action
        # expiry sweep: one integer check, counting LIVE deadline-carrying
        # requests — the sweep cost ends when the last of them retires
        self._deadline_live = 0

    def _tel_gauges(self) -> None:
        """Refresh the occupancy gauges (queue depth, running rows, KV
        pool utilization) from current scheduler/allocator state, and
        emit the flight-recorder occupancy sample (the serving trace's
        counter-track source) at the same transitions."""
        ev = self.events
        if ev is not None:
            a = self.allocator
            ev.emit("sched.gauge", queued=len(self.waiting),
                    running=len(self.running), kv_used=a.num_used,
                    kv_free=a.num_free)
        t = self.telemetry
        if t is None:
            return
        a = self.allocator
        t.queue_depth.set(len(self.waiting))
        t.running.set(len(self.running))
        used = a.num_used
        t.kv_blocks_used.set(used)
        t.kv_blocks_free.set(a.num_free)
        t.cold_blocks.set(a.num_cold)
        hp = a.host_pool
        if hp is not None:
            t.kv_host_blocks.set(hp.num_blocks)
            t.kv_host_bytes.set(hp.nbytes)
        t.kv_block_utilization.set(used / max(1, a.capacity))
        # internal fragmentation: slots allocated to requests but not yet
        # holding cached k/v (last-block waste + blocks grown ahead of
        # pos). Shared blocks count ONCE (dedup by block id); a mid-prefill
        # request counts its whole target as cached — its blocks are spoken
        # for, not wasted, and the gauge would otherwise spike at admission
        fills = {}
        bs = a.block_size
        for r in self.running:
            c = r.prefill_target if r.prefilling else r.pos
            for j, b in enumerate(r.blocks):
                f = min(bs, max(0, c - j * bs))
                if f > fills.get(b, 0):
                    fills[b] = f
        cap = used * bs
        cached = sum(fills.values())
        t.kv_fragmentation.set(1.0 - cached / cap if cap > 0 else 0.0)

    # ------------------------------------------------------------------ #

    def add_request(self, prompt, max_new: int,
                    eos: Optional[int] = None, priority: int = 0,
                    ttft_budget: Optional[int] = None,
                    t_submit: Optional[float] = None,
                    deadline_ms: Optional[float] = None,
                    deadline_steps: Optional[int] = None,
                    trace: Optional[str] = None,
                    parent: Optional[int] = None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        total = prompt.size + max_new
        cap = self.max_blocks_per_seq * self.allocator.block_size
        if total > cap:
            raise ValueError(
                f"request needs {total} KV slots but the block table holds "
                f"{cap} ({self.max_blocks_per_seq} blocks of "
                f"{self.allocator.block_size})")
        # admission livelock guard: a prompt that needs more blocks than the
        # pool can EVER supply would sit at the FIFO head forever, starving
        # everything queued behind it — reject it up front instead
        need = self.allocator.blocks_for_tokens(prompt.size)
        if need > self.allocator.capacity:
            raise ValueError(
                f"prompt of {prompt.size} tokens needs {need} KV blocks but "
                f"the pool only has {self.allocator.capacity} allocatable "
                f"blocks in total — it can never be admitted; raise "
                "serving.max_num_blocks or shorten the prompt")
        now = time.perf_counter()
        req = Request(rid=self._next_rid, prompt=prompt, max_new=max_new,
                      eos=eos, t_arrival=now,
                      t_submit=t_submit if t_submit is not None else now,
                      # coerce HERE so a garbage budget fails the one
                      # submission (ValueError/TypeError at add time), not
                      # SlaPolicy's slack math mid-loop for everyone
                      priority=int(priority),
                      ttft_budget=(None if ttft_budget is None
                                   else int(ttft_budget)),
                      deadline_ms=(None if deadline_ms is None
                                   else float(deadline_ms)),
                      deadline_steps=(None if deadline_steps is None
                                      else int(deadline_steps)),
                      arrival_step=self.step_seq,
                      trace=(None if trace is None else str(trace)),
                      parent=(None if parent is None else int(parent)))
        self._next_rid += 1
        if req.deadline_ms is not None or req.deadline_steps is not None:
            self._deadline_live += 1
        self.waiting.append(req)
        if self.events is not None:
            # the trace context rides the enqueue: rids sharing a trace id
            # are one causal chain (the fleet renderer's flow-arrow source)
            ctx = {}
            if req.trace is not None:
                ctx["trace"] = req.trace
            if req.parent is not None:
                ctx["parent"] = req.parent
            self.events.emit("req.enqueue", rid=req.rid,
                             prompt_tokens=int(prompt.size),
                             max_new=max_new, **ctx)
        if self.telemetry is not None:
            self.telemetry.requests.inc()
        self._tel_gauges()
        return req

    def all_done(self) -> bool:
        return not self.waiting and not self.running

    def _deadline_retired(self, req: Request) -> None:
        """Called at every permanent retirement: the deadline sweep's
        cost ends when the last live deadline-carrying request leaves."""
        if req.deadline_ms is not None or req.deadline_steps is not None:
            self._deadline_live -= 1

    def cancel_request(self, req: Request) -> bool:
        """Retire ``req`` by cancellation at any lifecycle point: a QUEUED
        request leaves the waiting queue, a RUNNING one leaves the batch
        with ALL its blocks dereferenced (prefix-cache registrations stay
        — committed content another request may hit). The request lands in
        ``finished`` with ``cancelled=True`` and whatever it generated so
        far. Returns False when it had already finished (nothing to do).
        The caller owns the engine-step boundary: cancellations must land
        BETWEEN scheduler actions, never between a returned action and its
        ``record_*`` callback."""
        return self._force_retire(req, error=None)

    def fail_request(self, req: Request, error: str) -> bool:
        """Retire ``req`` with ``error`` at any lifecycle point — the
        always-on loop's answer to :class:`PoolExhausted` (and to a
        quarantined poison request): same cleanup as
        :meth:`cancel_request`, but the request's handle terminates with
        status "error" while the loop keeps serving everyone else."""
        return self._force_retire(req, error=str(error))

    def timeout_request(self, req: Request, error: str) -> bool:
        """Retire ``req`` as a deadline expiry (``req.timed_out``): same
        cleanup as :meth:`cancel_request`, emitting ``req.timeout`` and
        counting ``serving/timeouts`` — the handle terminates with status
        "timeout" (HTTP 504 / SSE ``finish_reason: "timeout"``)."""
        return self._force_retire(req, error=str(error), flavor="timeout")

    def shed_request(self, req: Request) -> bool:
        """Drop a QUEUED ``req`` under load-shedding pressure: emits
        ``req.shed`` and counts ``serving/shed_requests``; the handle
        terminates with status "rejected" (HTTP 429 + Retry-After). Only
        waiting requests shed — running work is never abandoned for
        backpressure (preemption owns pool pressure)."""
        if req.state != QUEUED:
            raise ValueError(
                f"request {req.rid} is {req.state}; only QUEUED requests "
                "can be shed")
        return self._force_retire(
            req, error="shed under queue pressure", flavor="shed")

    def _force_retire(self, req: Request, error: Optional[str],
                      flavor: str = "error") -> bool:
        if req.state == FINISHED:
            return False
        if req.state == QUEUED:
            for i, r in enumerate(self.waiting):   # identity, not __eq__
                if r is req:
                    del self.waiting[i]
                    break
            else:
                raise ValueError(f"request {req.rid} is QUEUED but not in "
                                 "this scheduler's waiting queue")
        else:
            self.running.remove(req)
            self._free_blocks(req)
        req.spec_tokens = ()
        req.state = FINISHED
        self._deadline_retired(req)
        self.finished.append(req)
        if error is None:
            req.cancelled = True
            if self.events is not None:
                self.events.emit("req.cancel", rid=req.rid,
                                 generated=len(req.generated))
        else:
            req.error = error
            logger.warning(f"request {req.rid} retired: {error}")
            if flavor == "timeout":
                req.timed_out = True
                if self.telemetry is not None:
                    self.telemetry.timeouts.inc()
                    # everything generated dies with the deadline: the
                    # client gets a 504, not the tokens
                    self.telemetry.waste("timeout", len(req.generated))
                if self.events is not None:
                    self.events.emit("req.timeout", rid=req.rid,
                                     generated=len(req.generated),
                                     error=error)
            elif flavor == "shed":
                req.shed = True
                if self.telemetry is not None:
                    self.telemetry.shed_requests.inc()
                    # shed requests are QUEUED (generated == 0): the zero
                    # inc still materializes the cause series
                    self.telemetry.waste("shed", len(req.generated))
                if self.events is not None:
                    self.events.emit("req.shed", rid=req.rid,
                                     priority=req.priority)
            elif self.events is not None:
                self.events.emit("req.retire", rid=req.rid,
                                 generated=len(req.generated), error=error)
        if self.telemetry is not None:
            self.telemetry.finished.inc()
        self._tel_gauges()
        return True

    def requeue_for_retry(self, req: Request, backoff_steps: int,
                          error: str = "") -> None:
        """Per-request step-fault containment: re-queue a RUNNING request
        through the recompute-preemption machinery (all blocks
        dereferenced, prompt + generated becomes the new prefix — with
        prefix caching its own still-cold blocks usually satisfy the
        re-admission) with an admission hold-down of ``backoff_steps``
        LOGICAL steps (the ``step_seq`` clock, replay-deterministic).
        Greedy decoding reproduces the un-faulted continuation exactly,
        the same guarantee preemption has always carried."""
        if req.state != RUNNING:
            raise ValueError(
                f"request {req.rid} is {req.state}; only RUNNING requests "
                "retry through re-queue")
        if self.events is not None:
            self.events.emit("req.requeue", rid=req.rid,
                             retry=req.retry_count,
                             backoff_steps=int(backoff_steps), error=error)
        if self.telemetry is not None:
            self.telemetry.request_retries.inc()
        # FRONT of the queue like preemption: the backoff hold-down, not
        # queue position, is what delays the retry
        self._demote_to_queue(req)
        req.retry_at_step = self.step_seq + max(int(backoff_steps), 0)
        self._tel_gauges()

    def reset_pool(self, allocator: BlockAllocator) -> None:
        """Crash-safe engine recovery: the device pools died mid-step, so
        every block placement is invalid. Swap in the freshly built
        ``allocator`` and re-queue ALL running requests from prompt +
        generated tokens — exactly the state recompute-preemption already
        proves sufficient to continue greedy-identically. Admission order
        is preserved (earlier-admitted requests re-admit first, ahead of
        anything that was still waiting). The old allocator's refs are
        dereferenced first — pure host bookkeeping (the spill hook was
        already cleared; the buffers its cold cache would describe are
        gone either way) — so an abandoned allocator ends consistent,
        which is what the leak-audit fixtures assert."""
        for req in list(self.running)[::-1]:  # earliest ends at the front
            self._demote_to_queue(req)
        self.allocator = allocator
        self._decode_turn = False
        self._tel_gauges()

    def _demote_to_queue(self, req: Request) -> None:
        """The ONE RUNNING -> QUEUED demotion (preemption, step-fault
        retry, engine restart): every block dereferenced, prefill state
        reset so prompt + generated becomes the re-admission prefix, and
        the request re-queued at the FRONT. A Request field that must
        clear on demotion belongs here (or in ``_free_blocks``), never in
        one caller."""
        self.running.remove(req)
        self._free_blocks(req)
        req.pos = 0
        req.prefilling = False
        req.prefill_target = 0
        req.spec_tokens = ()
        req.state = QUEUED
        self.waiting.appendleft(req)

    # ------------------------------------------------------------------ #
    # admission

    def _try_admit(self) -> Optional[Tuple[str, Request]]:
        """Admit the FIFO queue head when a slot and its (tail) blocks are
        available: probe the prefix cache, acquire the hit, allocate only
        the rest, and start the request's ``pos`` past the cached tokens.
        Returns the prefill action, or None when nothing was admitted."""
        if not self.waiting or len(self.running) >= self.max_running:
            return None
        # the policy picks WHICH waiting request this attempt tries (FIFO:
        # the head); one candidate per attempt keeps admission all-or-
        # nothing and deterministic
        idx = int(self.policy.select_admission(self))
        if not 0 <= idx < len(self.waiting):
            raise ValueError(
                f"policy {self.policy.name!r} selected waiting index {idx} "
                f"out of range (queue depth {len(self.waiting)})")
        req = self.waiting[idx]
        if req.retry_at_step > self.step_seq:
            # the policy's pick is holding down after a step-fault retry
            # (exponential backoff on the logical clock): take the first
            # ELIGIBLE waiting request in FIFO order instead, or admit
            # nothing this step — the backoff must never starve the rest
            # of the queue, and FIFO-among-eligible keeps it deterministic
            for j, r in enumerate(self.waiting):
                if r.retry_at_step <= self.step_seq:
                    idx, req = j, r
                    break
            else:
                return None
        prefix = req.prefix()
        target = int(prefix.size)
        bs = self.allocator.block_size
        need_total = self.allocator.blocks_for_tokens(target)
        if need_total > self.allocator.capacity:
            # prompt fit at add_request but preemption-appended generated
            # tokens grew the prefix past the whole pool: retire with an
            # error instead of wedging the FIFO head forever
            del self.waiting[idx]
            req.state = FINISHED
            self._deadline_retired(req)
            req.error = (
                f"prefix of {target} tokens (prompt + {len(req.generated)} "
                f"generated) needs {need_total} KV blocks but the pool has "
                f"{self.allocator.capacity}; raise serving.max_num_blocks")
            logger.warning(f"request {req.rid} retired: {req.error}")
            self.finished.append(req)
            if self.events is not None:
                self.events.emit("req.retire", rid=req.rid,
                                 generated=len(req.generated),
                                 error=req.error)
            if self.telemetry is not None:
                self.telemetry.finished.inc()
            self._tel_gauges()
            return self._try_admit()

        entries: List[Tuple] = []       # chain order: ("dev", b) | ("host",
        #                                 key, k_np, v_np) — host bytes in hand
        keys: List[bytes] = []
        cow_src: Optional[int] = None
        cow_fetch = None                # (k_np, v_np): host-resident COW src
        cached = 0
        had_hit = False
        if self.prefix_caching:
            hits, hit_keys = self.allocator.match_prefix_tiered(prefix)
            # resolve host entries NOW — bytes in hand before any
            # allocation below can demote-evict them from the host LRU. A
            # vanished/faulted entry truncates the usable chain at its
            # position (the hit must stay a contiguous prefix).
            resolved: List[Tuple] = []
            for ent, key in zip(hits, hit_keys):
                if ent[0] == "dev":
                    resolved.append((ent, key))
                    continue
                data = self.allocator.host_pool.get(ent[1])
                if data is None:
                    break
                resolved.append((("host", ent[1], data[0], data[1]), key))
            had_hit = bool(resolved)
            if self.telemetry is not None:
                self.telemetry.prefix_cache_lookups.inc()
                if resolved:
                    self.telemetry.prefix_cache_hits.inc()
            cached = len(resolved) * bs
            if cached >= target:
                # full prefix cached: cap the hit at target-1 (the last
                # token's logits must still be computed to sample the
                # continuation), which restarts mid-block inside the last
                # shared block — copy-on-write it (partial blocks are
                # never shared). A host-resident COW source fetches into
                # the private block directly (no device registration to
                # split; the host entry stays cached for future hits).
                cached = target - 1
                last, _ = resolved[-1]
                resolved = resolved[:-1]
                if last[0] == "dev":
                    cow_src = last[1]
                else:
                    cow_fetch = (last[2], last[3])
            entries = [e for e, _ in resolved]
            keys = [k for _, k in resolved]

        shared = [e[1] for e in entries if e[0] == "dev"]
        # host-hit blocks need fresh device placements, so they come out
        # of the same allocation as the uncached tail
        alloc_needed = need_total - len(shared)
        # acquire the hit FIRST so the tail allocation's cold-list reclaim
        # can't cannibalize the very blocks we are about to share. The COW
        # source is NOT acquired: the only allocation between here and the
        # engine's copy is the COW destination itself, and if LRU reclaim
        # hands back the source as that destination the copy degenerates to
        # the identity (content still intact — nothing writes between
        # admission and the engine processing the returned action).
        self.allocator.acquire(shared)
        # with host hits in the chain, the single-allocation guarantee
        # behind the un-acquired COW source no longer holds: the
        # allocation below also covers fetch destinations, and LRU
        # reclaim could hand the (cold) source out as one of them — the
        # H2D scatter would then overwrite it BEFORE the COW copy reads
        # it. Pin the source with a temporary reference for the
        # allocation (released right after placement); without host hits
        # the degenerate src==dst identity-copy case stays exactly as
        # before.
        protect_cow = cow_src is not None \
            and any(e[0] == "host" for e in entries)
        if protect_cow:
            self.allocator.acquire([cow_src])
        got = self.allocator.allocate(alloc_needed)
        if got is None and protect_cow:
            # the pool can't place the fetches AND preserve the pinned COW
            # source: degrade the full-prefix hit — drop the COW (the last
            # block's tokens recompute in the tail chunk; alloc_needed
            # already covers that block as plain tail) and retry unpinned
            self.allocator.free([cow_src])
            cow_src = None
            protect_cow = False
            cached = bs * len(entries)
            got = self.allocator.allocate(alloc_needed)
        if got is None:
            # roll the probe back — in REVERSE like _free_blocks, so LRU
            # reclaim takes chain tails before parents (a reclaimed parent
            # orphans its still-cached children for every future probe).
            # Host entries were only read (get), never removed: nothing to
            # restore there.
            self.allocator.free(list(reversed(shared)))
            if not self.running:
                raise PoolExhausted(
                    f"prefix of request {req.rid} needs {alloc_needed} more "
                    f"KV blocks but the pool only has "
                    f"{self.allocator.num_free} available and nothing is "
                    "running to evict; raise serving.max_num_blocks or "
                    "shrink the prompt", req)
            return None

        # interleave: chain positions keep their tier order — device hits
        # keep their blocks, host hits take fresh placements that the
        # engine fills H2D (fetch_pending) before this request's first
        # prefill work; the remainder is the uncached tail
        it = iter(got)
        blocks: List[int] = []
        fetches: List[Tuple] = []
        for e in entries:
            if e[0] == "dev":
                blocks.append(e[1])
            else:
                dst = next(it)
                blocks.append(dst)
                # key and token count ride along: the engine registers dst
                # under the key — and observes the fetch counters — only
                # once the copy actually lands (a preemption between
                # admission and fetch must not advertise unwritten content
                # nor count an H2D that never happened)
                fetches.append((dst, e[1], e[2], e[3], bs))
        tail = list(it)
        blocks += tail
        if protect_cow:
            self.allocator.free([cow_src])   # placement done: back cold
        if cow_fetch is not None:
            # the COW split's private copy: fetched, never registered
            fetches.append((tail[0], None, cow_fetch[0], cow_fetch[1],
                            cached - bs * len(entries)))

        del self.waiting[idx]
        first_admit = req.admit_seq == -1
        if self.telemetry is not None and first_admit:
            # first admission only: the submit->admit wait (a preemption
            # re-admission is recompute latency, not queueing delay)
            now = time.perf_counter()
            self.telemetry.queue_wait.observe(
                (now - req.t_submit) * 1e3,
                exemplar={"rid": str(req.rid)})
            # phase ledger: intake = submit->enqueue (front-end hand-off),
            # queue = enqueue->admit (admission wait proper)
            self.telemetry.phase(
                "intake", max(req.t_arrival - req.t_submit, 0.0) * 1e3,
                rid=req.rid)
            self.telemetry.phase(
                "queue", max(now - req.t_arrival, 0.0) * 1e3, rid=req.rid)
        req.blocks = blocks
        req.keys = list(keys)
        req.pos = cached
        req.prefill_target = target
        req.prefilling = True
        req.cow_pending = None if cow_src is None \
            else (cow_src, tail[0])
        req.fetch_pending = fetches
        req.state = RUNNING
        req.admit_seq = self._admit_counter
        self._admit_counter += 1
        self.running.append(req)
        if self.events is not None:
            # probe outcome emitted only on the admission that sticks: a
            # block-short pool retries admission every engine step, and
            # per-attempt instants would flood the bounded ring
            if self.prefix_caching:
                if had_hit:
                    self.events.emit("req.cache_hit", rid=req.rid,
                                     tokens=cached,
                                     host_blocks=len(fetches))
                else:
                    self.events.emit("req.cache_miss", rid=req.rid)
            self.events.emit("req.admit", rid=req.rid,
                             cached_tokens=cached, blocks=len(req.blocks),
                             prefill_target=target)
            if first_admit:
                # phase-ledger spans for the pre-admission phases (the
                # compute phases carry their own timed events); durations
                # are already-elapsed intervals ending here
                now_ns = time.monotonic_ns()
                self.events.emit(
                    "req.phase", rid=req.rid, t_ns=now_ns, phase="intake",
                    dur_ns=int(max(req.t_arrival - req.t_submit, 0.0) * 1e9))
                self.events.emit(
                    "req.phase", rid=req.rid, t_ns=now_ns, phase="queue",
                    dur_ns=int(max(time.perf_counter() - req.t_arrival, 0.0)
                               * 1e9))
        if self.telemetry is not None:
            self.telemetry.prefill_steps.inc()
            if cached:
                self.telemetry.prefix_cache_hit_tokens.inc(cached)
        self._tel_gauges()
        if req.pos > 0 or self.chunk_tokens > 0:
            if self.telemetry is not None:
                self.telemetry.prefill_chunks.inc()
            self._decode_turn = True
            return ("prefill_chunk", req)
        return ("prefill", req)

    # ------------------------------------------------------------------ #

    def next_action(self) -> Optional[Tuple[str, object]]:
        """Pick the next engine step: admit+start the policy-selected
        waiting request when a slot and its tail blocks are available
        (admission has priority — back-fill freed slots immediately), else
        alternate one prefill chunk of the oldest mid-prefill request with
        one fused decode step over the prefill-complete running set. None
        when everything is finished. Every returned action advances the
        logical ``step_seq`` clock (the SLA policies' time base).

        Deadline-carrying requests are swept first: an expired request —
        ``deadline_steps`` on the logical clock, ``deadline_ms`` on wall
        time — retires as ``timeout`` before the next step is chosen.
        ``("wait", None)`` is returned (and the clock ticked) when the
        only waiting requests are holding down in step-fault retry
        backoff — the tick is what moves them toward eligibility."""
        if self._deadline_live:
            self._sweep_deadlines()
        action = self._next_action()
        if action is not None:
            self.step_seq += 1
        return action

    def _sweep_deadlines(self) -> None:
        now = None
        for req in list(self.waiting) + list(self.running):
            expired = None
            if req.deadline_steps is not None and \
                    self.step_seq - req.arrival_step >= req.deadline_steps:
                expired = (f"deadline of {req.deadline_steps} scheduler "
                           f"steps exceeded")
            elif req.deadline_ms is not None:
                if now is None:
                    now = time.perf_counter()
                waited_ms = (now - req.t_submit) * 1e3
                if waited_ms > req.deadline_ms:
                    expired = (f"deadline of {req.deadline_ms:.0f} ms "
                               f"exceeded ({waited_ms:.0f} ms since "
                               "submission)")
            if expired is not None:
                self.timeout_request(req, expired)

    def _next_action(self) -> Optional[Tuple[str, object]]:
        action = self._try_admit()
        if action is not None:
            return action
        prefilling = [r for r in self.running if r.prefilling]
        decodable = [r for r in self.running if not r.prefilling]
        if prefilling and (not decodable or not self._decode_turn):
            if self.telemetry is not None:
                self.telemetry.prefill_chunks.inc()
            self._decode_turn = True
            return ("prefill_chunk", prefilling[0])
        if decodable:
            self._decode_turn = False
            self._ensure_decode_capacity()
            decodable = [r for r in self.running if not r.prefilling]
            if not decodable:
                # capacity growth evicted every decodable row (they went
                # back to the queue); pick again from the new state (the
                # outer next_action ticks step_seq once for whatever comes
                # out)
                return self._next_action()
            if self.spec_k > 0:
                action = self._prepare_verify(decodable)
                if action is not None:
                    self._tel_gauges()   # window growth moved blocks
                    return action
            self.stats["decode_steps"] += 1
            if self.telemetry is not None:
                self.telemetry.decode_steps.inc()
            self._tel_gauges()       # capacity growth/evictions moved blocks
            return ("decode", decodable)
        if self.waiting:
            if all(r.retry_at_step > self.step_seq for r in self.waiting):
                # everything queued is holding down in retry backoff: a
                # no-op action whose clock tick moves them toward
                # eligibility (bounded — backoff is finite logical steps)
                return ("wait", None)
            # slots full but pool dry would have been handled above; here
            # the running set is empty yet requests wait — impossible unless
            # max_running slots are all mid-preemption; defensive guard
            raise RuntimeError("scheduler stuck: waiting requests but "
                               "nothing runnable")
        return None

    def _ensure_decode_capacity(self) -> None:
        """Every decode-ready request writes its next token at slot
        ``pos``; grow its block list when that slot crosses a block
        boundary, evicting the policy's victim (FIFO: latest admitted,
        SLA: most TTFT slack) when the pool — free list AND reclaimable
        cold blocks — is dry."""
        for req in list(self.running):
            if req.state != RUNNING or req.prefilling:
                continue  # evicted by an earlier iteration, or mid-prefill
            while req.pos >= len(req.blocks) * self.allocator.block_size:
                got = self.allocator.allocate(1)
                if got is not None:
                    req.blocks.extend(got)
                    break
                victim = self.policy.select_victim(self, req)
                # identity scan: Request's dataclass __eq__ compares numpy
                # fields (ambiguous truth value) — never use `in` here
                if not any(victim is r for r in self.running):
                    raise ValueError(
                        f"policy {self.policy.name!r} selected a victim "
                        "that is not running")
                if victim is req and len(self.running) == 1:
                    raise PoolExhausted(
                        f"request {req.rid} needs one more KV block but the "
                        "pool is exhausted and it is the only running "
                        "request; raise serving.max_num_blocks", req)
                self._preempt(victim)
                if victim is req:
                    break  # the requester evicted itself; it re-queued

    def _prepare_verify(self, decodable: List[Request]) \
            -> Optional[Tuple[str, object]]:
        """Propose n-gram candidates for every decode-ready request and
        secure the KV slots their verify windows write (slots ``pos`` ..
        ``pos + len(candidates)``; slot ``pos`` itself is already assured
        by ``_ensure_decode_capacity``). Window growth draws ONLY on the
        free pool and truncates the candidate list when it runs dry —
        speculation never preempts, so eviction behavior is identical to
        plain decode (growth therefore cannot drop rows from
        ``decodable``). Returns ``("verify", decodable)``, or None when no
        request found a match (the caller emits a plain decode step — the
        1-wide program is cheaper than an empty verify window)."""
        ev = self.events
        bs = self.allocator.block_size
        any_cands = False
        for r in decodable:
            # candidates may never push the request past max_new: a verify
            # step emits up to len(candidates)+1 tokens
            headroom = r.max_new - len(r.generated) - 1
            if headroom <= 0:
                r.spec_tokens = ()
                continue
            t0 = time.monotonic_ns() if ev is not None else 0
            cands = self.spec_proposer.propose(
                r.output, min(self.spec_k, headroom))
            found = len(cands)
            if len(cands):
                # clamp to the slots the request owns plus what the PLAIN
                # free list supplies — never evicting AND never reclaiming
                # a cold cached block: speculation is best-effort, so it
                # must not destroy a prefix-cache registration (and the
                # later cache miss + recompute) that spec-off serving
                # would have kept. Highest written slot is pos + len(cands)
                need = self.allocator.blocks_for_tokens(
                    r.pos + 1 + len(cands)) - len(r.blocks)
                if need > 0:
                    got = self.allocator.allocate(
                        min(need, self.allocator.num_free_list))
                    if got:
                        r.blocks.extend(got)
                    cands = cands[:len(r.blocks) * bs - 1 - r.pos]
            r.spec_tokens = tuple(int(c) for c in cands)
            # emitted only when the proposer actually matched: a zero-found
            # probe per request per decode turn would flood the bounded
            # ring and evict the lifecycle tail a post-mortem needs (the
            # same failure mode the per-attempt cache_hit instants had)
            if ev is not None and found:
                ev.emit("req.spec_propose", rid=r.rid, t_ns=t0,
                        dur_ns=time.monotonic_ns() - t0,
                        tokens=len(r.spec_tokens), found=found)
            if r.spec_tokens:
                any_cands = True
                self.stats["spec_proposed"] += len(r.spec_tokens)
                if self.telemetry is not None:
                    self.telemetry.spec_proposed_tokens.inc(
                        len(r.spec_tokens))
        if not any_cands:
            return None
        self.stats["verify_steps"] += 1
        if self.telemetry is not None:
            self.telemetry.spec_verify_steps.inc()
        return ("verify", decodable)

    def _preempt(self, victim: Request) -> None:
        logger.warning(
            f"KV pool exhausted: preempting request {victim.rid} "
            f"({len(victim.blocks)} blocks dereferenced; will recompute "
            f"{len(victim.prefix())} tokens on re-admission"
            + (" minus any prefix-cache hit" if self.prefix_caching else "")
            + ")")
        if self.events is not None:
            self.events.emit("req.preempt", rid=victim.rid,
                             blocks=len(victim.blocks),
                             recompute_tokens=len(victim.prefix()))
        self.stats["preemptions"] += 1
        if self.telemetry is not None:
            self.telemetry.preemptions.inc()
            self.telemetry.recompute_tokens.inc(len(victim.prefix()))
            # wasted-work ledger: the evicted prefix is compute the pool
            # pressure threw away (re-prefilled on re-admission)
            self.telemetry.waste("recompute", len(victim.prefix()))
        # FRONT of the queue: the victim was admitted before anything still
        # waiting, so FIFO fairness re-admits it first
        self._demote_to_queue(victim)
        victim.preemptions += 1

    def _free_blocks(self, req: Request) -> None:
        """Dereference a retiring/preempted request's blocks. Freed in
        REVERSE order when caching so the LRU cold list reclaims chain
        TAILS before their parents — a reclaimed parent orphans its still-
        cached children (match walks front-to-back)."""
        blocks = req.blocks
        if self.prefix_caching:
            blocks = list(reversed(blocks))
        self.allocator.free(blocks)
        req.blocks = []
        req.keys = []
        req.cow_pending = None
        # un-landed host fetches die with the placement: the host pool
        # still holds the entries (removed only when a fetch lands), so a
        # re-admission re-hits them
        req.fetch_pending = []

    def _register_full_blocks(self, req: Request) -> None:
        """Publish every newly-FILLED block (all ``pos`` tokens' k/v are in
        the pools) into the content-addressed cache, extending the
        request's hash chain. First-writer-wins on conflicts (a concurrent
        identical prompt): the chain keys still advance so later blocks
        stay addressable."""
        if not self.prefix_caching:
            return
        bs = self.allocator.block_size
        full = req.pos // bs
        if full <= len(req.keys):
            return
        seq = req.prefix()
        parent = req.keys[-1] if req.keys else ROOT_KEY
        for j in range(len(req.keys), full):
            key = self.allocator.chain_key(parent, seq[j * bs:(j + 1) * bs])
            self.allocator.register(req.blocks[j], key)
            req.keys.append(key)
            parent = key

    # ------------------------------------------------------------------ #
    # engine callbacks after each compute step

    def record_prefill(self, req: Request, token: int) -> None:
        """The engine prefilled ``req.prefix()`` whole and sampled
        ``token`` from the last position."""
        req.pos = len(req.prefix())
        req.prefilling = False
        self._register_full_blocks(req)
        req.generated.append(int(token))
        self._record_token_time(req)
        self._maybe_finish(req)

    def record_prefill_chunk(self, req: Request, n_tokens: int,
                             token: Optional[int] = None) -> None:
        """One prefill chunk of ``n_tokens`` is cached. On the FINAL chunk
        the engine passes the ``token`` it sampled from the prefix's last
        position, completing the prefill exactly like
        :meth:`record_prefill`."""
        req.pos += int(n_tokens)
        if req.pos > req.prefill_target:
            raise ValueError(
                f"prefill chunk overran request {req.rid}: pos {req.pos} > "
                f"target {req.prefill_target}")
        self._register_full_blocks(req)
        if token is None:
            return
        if req.pos != req.prefill_target:
            raise ValueError(
                f"request {req.rid} sampled a token at pos {req.pos} before "
                f"reaching its prefill target {req.prefill_target}")
        req.prefilling = False
        req.generated.append(int(token))
        self._record_token_time(req)
        self._maybe_finish(req)

    def record_decode(self, req: Request, token: int) -> None:
        """One decode step: the previous ``last_token``'s k/v was written at
        slot ``pos`` and ``token`` sampled from the resulting logits."""
        req.pos += 1
        self._register_full_blocks(req)
        req.generated.append(int(token))
        self.stats["emitted_tokens"] += 1
        self._record_token_time(req)
        self._maybe_finish(req)

    def record_verify(self, req: Request, tokens: List[int]) -> None:
        """One fused verify step for ``req``: the engine scattered k/v for
        the whole window — the pending ``last_token`` plus every candidate
        in ``req.spec_tokens`` at slots ``pos .. pos + m`` — and greedy
        acceptance emitted ``tokens``: the accepted candidate prefix plus
        the first-mismatch (or, on full acceptance, bonus) token.

        Bookkeeping is optimistic-then-rollback, mirroring what the device
        actually did: ``pos`` first advances over every scattered input and
        blocks register into the prefix cache as they fill (their hash
        chains include the candidate tokens — that IS their content right
        now). A rejection then rewinds: the uncommitted candidates leave
        ``generated``, ``pos`` rewinds past them (their k/v stays beyond
        ``pos`` — never read, overwritten as decode advances), and every
        block whose fill boundary sits inside the rejected span is
        unregistered via ``unregister_if_owner`` — its slots WILL be
        overwritten by the real continuation, so a surviving registration
        would advertise content about to be destroyed. When a first writer
        (another request whose identical tokens DID commit) already owned
        the hash, its mapping is preserved untouched."""
        cands = req.spec_tokens
        m = len(cands)
        req.spec_tokens = ()
        tokens = [int(t) for t in tokens]
        if not 1 <= len(tokens) <= m + 1:
            raise ValueError(
                f"verify of request {req.rid} emitted {len(tokens)} tokens "
                f"from a window of {m} candidates")
        # eos can land anywhere in the multi-token window: cut exactly
        # where token-by-token greedy decode would have stopped
        if req.eos is not None and req.eos in tokens:
            tokens = tokens[:tokens.index(req.eos) + 1]
        a = len(tokens) - 1            # candidates that commit

        # ---- optimistic advance over the whole scattered window ----
        req.generated.extend(int(c) for c in cands)
        req.pos += m + 1
        self._register_full_blocks(req)

        # ---- rollback of the rejected tail ----
        drop = m - a
        if drop:
            req.pos -= drop
            del req.generated[-drop:]
            bs = self.allocator.block_size
            unregistered = 0
            while len(req.keys) > req.pos // bs:
                key = req.keys.pop()
                if self.allocator.unregister_if_owner(
                        req.blocks[len(req.keys)], key):
                    unregistered += 1
            # return the window's surplus whole blocks: a rejected
            # speculation holding pool capacity would preempt requests
            # plain decode would have kept (only blocks past the rewound
            # pos's own slot can be surplus — all unregistered, the pop
            # loop above already withdrew any boundary-crossing keys)
            keep = max(self.allocator.blocks_for_tokens(req.pos + 1),
                       len(req.keys))
            if len(req.blocks) > keep:
                tail = req.blocks[keep:]
                del req.blocks[keep:]
                self.allocator.free(list(reversed(tail)))
            self.stats["spec_rollbacks"] += 1
            if self.telemetry is not None:
                self.telemetry.spec_rollbacks.inc()
                # rejected candidates were scattered and verified on the
                # device, then thrown away: speculative wasted work
                self.telemetry.waste("spec_reject", drop)
            if self.events is not None:
                self.events.emit("req.spec_rollback", rid=req.rid,
                                 rejected=drop, unregistered=unregistered)

        # ---- commit: accepted candidates are already in ``generated``;
        # the mismatch/bonus token is the next step's pending input ----
        req.generated.append(tokens[-1])
        self.stats["spec_accepted"] += a
        self.stats["emitted_tokens"] += len(tokens)
        if self.telemetry is not None:
            t = self.telemetry
            t.spec_accepted_tokens.inc(a)
            # the rate gauge derives from the CUMULATIVE registry counters
            # (they outlive this scheduler — one per serve call), so it
            # always equals accepted/proposed as the snapshot reports them
            proposed = t.spec_proposed_tokens.value
            if proposed:
                t.spec_acceptance_rate.set(
                    t.spec_accepted_tokens.value / proposed)
        for _ in tokens:
            self._record_token_time(req)
        self._maybe_finish(req)

    def _record_token_time(self, req: Request) -> None:
        """TTFT once per request (first token after the ORIGINAL arrival —
        a post-preemption re-prefill token counts as a per-output-token
        latency, not a second TTFT), TPOT for every token after it."""
        # an emitted token is real progress: step-fault retries reset, so
        # an innocent request co-batched with a poison one (whose fused
        # steps keep faulting) never accrues its way into quarantine —
        # only a request that cannot progress past its faulting action
        # exhausts serving.fault.max_request_retries
        req.retry_count = 0
        now = time.perf_counter()
        t = self.telemetry
        if t is not None:
            # the exemplar links the histogram's newest observation back
            # to its flight-recorder request track: a scraped p99 spike
            # carries the rid whose trace explains it
            if req.t_first_token is None:
                t.ttft.observe((now - req.t_arrival) * 1e3,
                               exemplar={"rid": str(req.rid)})
            else:
                t.tpot.observe((now - req.t_last_token) * 1e3,
                               exemplar={"rid": str(req.rid)})
            t.generated_tokens.inc()
        if req.t_first_token is None:
            req.t_first_token = now
        req.t_last_token = now

    def _maybe_finish(self, req: Request) -> None:
        done = len(req.generated) >= req.max_new
        if req.eos is not None and req.generated[-1] == req.eos:
            done = True
        if done:
            req.state = FINISHED
            self._deadline_retired(req)
            self.running.remove(req)
            self._free_blocks(req)
            self.finished.append(req)
            if self.events is not None:
                self.events.emit("req.retire", rid=req.rid,
                                 generated=len(req.generated),
                                 preemptions=req.preemptions)
            if self.telemetry is not None:
                self.telemetry.finished.inc()
            self._tel_gauges()
