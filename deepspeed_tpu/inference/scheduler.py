"""Continuous-batching scheduler (Orca iteration-level scheduling + vLLM
eviction, host side).

The engine drives one *step* at a time: :meth:`next_action` returns either
``("prefill", request)`` — admit the FIFO queue head into freshly allocated
blocks and run its prompt — or ``("decode", running)`` — one fused decode
step over every running request. Finished requests retire between steps
(their blocks return to the pool) and queued requests take their slots, so
a convoying long request never stalls the batch the way the static
``generate`` loop does.

Request lifecycle::

    QUEUED --admit(alloc prompt blocks)--> RUNNING --eos/max_new--> FINISHED
       ^                                      |
       +------- preempt (free ALL blocks) ----+

Preemption is recompute-style (vLLM's default): when a running request
needs one more KV block and the pool is dry, the LATEST-admitted running
request is evicted — its blocks are freed and it re-queues at the FRONT
with its prompt extended by the tokens it already generated, so its next
admission prefills the whole prefix again (compute traded for memory;
generated tokens are never lost, and greedy decoding reproduces the exact
same continuation). Both the victim choice and the FIFO free list are
deterministic — identical request streams schedule identically.

Bookkeeping invariant: ``req.pos`` is the number of tokens whose k/v sit in
the pools; the newest generated token (``req.last_token``) is NOT yet
cached — it is the next decode step's input, written at slot ``pos`` by
that step. Hence cached = prompt + generated[:-1], pos = len(prompt) +
len(generated) - 1 whenever the request is running.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.block_allocator import BlockAllocator
from deepspeed_tpu.utils.logging import logger

QUEUED, RUNNING, FINISHED = "queued", "running", "finished"


class ServingTelemetry:
    """Registry adapter for the Orca/vLLM-style iteration-level serving
    stats: the scheduler calls these hooks as its state machine moves and
    the series land in the process-global metrics registry
    (``deepspeed_tpu.monitor.metrics``).

    Invariants the tests pin: TTFT is observed exactly ONCE per request —
    the first token after the ORIGINAL arrival, even when a preemption
    forces a re-prefill later — and ``serving/preemptions`` equals the
    number of eviction events (``serving/recompute_tokens`` the prefix
    tokens those evictions will prefill again)."""

    _SERIES = ("ttft", "tpot", "queue_depth", "running", "kv_blocks_used",
               "kv_blocks_free", "kv_block_utilization", "kv_fragmentation",
               "prefill_steps", "decode_steps",
               "preemptions", "recompute_tokens", "requests", "finished",
               "generated_tokens")

    def __init__(self, registry=None):
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self.registry = registry
        self.ensure()

    def ensure(self) -> None:
        """Pre-create every serving family so zero-valued series (e.g. a
        run with no preemptions) still appear in snapshots. Re-run by the
        scheduler per serve call — re-creates after a registry reset."""
        for name in self._SERIES:
            getattr(self, name)

    # families resolved per access (get-or-create under the registry
    # lock; serving events are host-side per engine step, not a jit hot
    # loop) so a registry reset between bench metrics can't orphan them

    @property
    def ttft(self):
        return self.registry.histogram(
            "serving/ttft_ms", "request arrival -> first generated token")

    @property
    def tpot(self):
        return self.registry.histogram(
            "serving/tpot_ms", "per-output-token latency after the first")

    @property
    def queue_depth(self):
        return self.registry.gauge(
            "serving/queue_depth", "requests waiting for admission")

    @property
    def running(self):
        return self.registry.gauge(
            "serving/running", "running-batch occupancy (fused decode rows)")

    @property
    def kv_blocks_used(self):
        return self.registry.gauge(
            "serving/kv_blocks_used", "allocated pool blocks (excl. dummy)")

    @property
    def kv_blocks_free(self):
        return self.registry.gauge(
            "serving/kv_blocks_free", "free-list pool blocks (excl. dummy)")

    @property
    def kv_block_utilization(self):
        return self.registry.gauge(
            "serving/kv_block_utilization", "used / allocatable pool blocks")

    @property
    def kv_fragmentation(self):
        return self.registry.gauge(
            "serving/kv_fragmentation",
            "internal fragmentation: unfilled slot fraction of allocated "
            "blocks (allocated capacity minus cached tokens)")

    @property
    def prefill_steps(self):
        return self.registry.counter("serving/prefill_steps")

    @property
    def decode_steps(self):
        return self.registry.counter(
            "serving/decode_steps", "fused decode steps (all rows at once)")

    @property
    def preemptions(self):
        return self.registry.counter(
            "serving/preemptions", "recompute-preempt eviction events")

    @property
    def recompute_tokens(self):
        return self.registry.counter(
            "serving/recompute_tokens",
            "prefix tokens re-prefilled by evictions")

    @property
    def requests(self):
        return self.registry.counter("serving/requests")

    @property
    def finished(self):
        return self.registry.counter("serving/finished_requests")

    @property
    def generated_tokens(self):
        return self.registry.counter("serving/generated_tokens")


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray              # [P] int32, immutable
    max_new: int
    eos: Optional[int] = None
    state: str = QUEUED
    blocks: List[int] = dataclasses.field(default_factory=list)
    pos: int = 0                    # tokens currently cached in the pools
    generated: List[int] = dataclasses.field(default_factory=list)
    admit_seq: int = -1             # admission stamp (eviction order)
    preemptions: int = 0
    t_arrival: float = 0.0          # perf_counter at add_request
    t_first_token: Optional[float] = None   # TTFT stamp (set once, ever)
    t_last_token: float = 0.0       # previous token's stamp (TPOT base)

    def prefix(self) -> np.ndarray:
        """The token prefix a (re)admission must prefill: the prompt plus
        every already-generated token. Prefill caches k/v for ALL of them
        and samples the next (new) token from the last position — so a
        recomputed request continues exactly where it left off (greedy
        decoding reproduces the unpreempted continuation)."""
        if not self.generated:
            return self.prompt
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])

    @property
    def last_token(self) -> Optional[int]:
        return self.generated[-1] if self.generated else None

    @property
    def output(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


class ContinuousBatchingScheduler:
    """FIFO admission, fused decode over all running requests, retire on
    eos/max_new, recompute-preempt the latest-admitted request on OOM."""

    def __init__(self, allocator: BlockAllocator, max_running: int,
                 max_blocks_per_seq: int,
                 telemetry: Optional[ServingTelemetry] = None):
        if max_running < 1:
            raise ValueError("max_running must be >= 1")
        self.allocator = allocator
        self.max_running = max_running
        self.max_blocks_per_seq = max_blocks_per_seq
        self.telemetry = telemetry
        if telemetry is not None:
            telemetry.ensure()
        self.waiting: deque = deque()
        self.running: List[Request] = []   # admission-ordered
        self.finished: List[Request] = []
        self._admit_counter = 0
        self._next_rid = 0

    def _tel_gauges(self) -> None:
        """Refresh the occupancy gauges (queue depth, running rows, KV
        pool utilization) from current scheduler/allocator state."""
        t = self.telemetry
        if t is None:
            return
        t.queue_depth.set(len(self.waiting))
        t.running.set(len(self.running))
        used = self.allocator.num_blocks - 1 - self.allocator.num_free
        t.kv_blocks_used.set(used)
        t.kv_blocks_free.set(self.allocator.num_free)
        t.kv_block_utilization.set(used / max(1, self.allocator.num_blocks - 1))
        # internal fragmentation: slots allocated to requests but not yet
        # holding cached k/v (last-block waste + blocks grown ahead of
        # pos). A just-admitted request (pos still 0, prefill scheduled)
        # counts its prefix as cached — its blocks are spoken for, not
        # wasted, and the gauge would otherwise spike to 1.0 at admission
        cached = sum(r.pos or len(r.prefix()) for r in self.running)
        cap = used * self.allocator.block_size
        t.kv_fragmentation.set(1.0 - cached / cap if cap > 0 else 0.0)

    # ------------------------------------------------------------------ #

    def add_request(self, prompt, max_new: int,
                    eos: Optional[int] = None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        total = prompt.size + max_new
        cap = self.max_blocks_per_seq * self.allocator.block_size
        if total > cap:
            raise ValueError(
                f"request needs {total} KV slots but the block table holds "
                f"{cap} ({self.max_blocks_per_seq} blocks of "
                f"{self.allocator.block_size})")
        req = Request(rid=self._next_rid, prompt=prompt, max_new=max_new,
                      eos=eos, t_arrival=time.perf_counter())
        self._next_rid += 1
        self.waiting.append(req)
        if self.telemetry is not None:
            self.telemetry.requests.inc()
            self._tel_gauges()
        return req

    def all_done(self) -> bool:
        return not self.waiting and not self.running

    # ------------------------------------------------------------------ #

    def next_action(self) -> Optional[Tuple[str, object]]:
        """Pick the next engine step: admit+prefill the queue head when a
        slot and its prompt blocks are available (admission has priority —
        back-fill freed slots immediately), else one fused decode step over
        the running set. None when everything is finished."""
        if self.waiting and len(self.running) < self.max_running:
            req = self.waiting[0]
            need = self.allocator.blocks_for_tokens(len(req.prefix()))
            blocks = self.allocator.allocate(need)
            if blocks is not None:
                self.waiting.popleft()
                req.blocks = blocks
                req.state = RUNNING
                req.admit_seq = self._admit_counter
                self._admit_counter += 1
                self.running.append(req)
                if self.telemetry is not None:
                    self.telemetry.prefill_steps.inc()
                    self._tel_gauges()
                return ("prefill", req)
            if not self.running:
                raise RuntimeError(
                    f"prompt of request {req.rid} needs {need} KV blocks but "
                    f"the pool only has {self.allocator.num_free} free and "
                    "nothing is running to evict; raise "
                    "serving.max_num_blocks or shrink the prompt")
        if self.running:
            self._ensure_decode_capacity()
            if self.telemetry is not None:
                self.telemetry.decode_steps.inc()
                self._tel_gauges()   # capacity growth/evictions moved blocks
            return ("decode", list(self.running))
        if self.waiting:
            # slots full but pool dry would have been handled above; here
            # the running set is empty yet requests wait — impossible unless
            # max_running slots are all mid-preemption; defensive guard
            raise RuntimeError("scheduler stuck: waiting requests but "
                               "nothing runnable")
        return None

    def _ensure_decode_capacity(self) -> None:
        """Every running request writes its next token at slot ``pos``;
        grow its block list when that slot crosses a block boundary,
        evicting from the back (latest admitted) when the pool is dry."""
        for req in list(self.running):
            if req.state != RUNNING:
                continue  # evicted by an earlier iteration of this loop
            while req.pos >= len(req.blocks) * self.allocator.block_size:
                got = self.allocator.allocate(1)
                if got is not None:
                    req.blocks.extend(got)
                    break
                victim = self.running[-1]
                if victim is req and len(self.running) == 1:
                    raise RuntimeError(
                        f"request {req.rid} needs one more KV block but the "
                        "pool is exhausted and it is the only running "
                        "request; raise serving.max_num_blocks")
                self._preempt(victim)
                if victim is req:
                    break  # the requester evicted itself; it re-queued

    def _preempt(self, victim: Request) -> None:
        logger.warning(
            f"KV pool exhausted: preempting request {victim.rid} "
            f"({len(victim.blocks)} blocks freed; will recompute "
            f"{len(victim.prefix())} tokens on re-admission)")
        if self.telemetry is not None:
            self.telemetry.preemptions.inc()
            self.telemetry.recompute_tokens.inc(len(victim.prefix()))
        self.running.remove(victim)
        self.allocator.free(victim.blocks)
        victim.blocks = []
        victim.pos = 0
        victim.state = QUEUED
        victim.preemptions += 1
        # FRONT of the queue: the victim was admitted before anything still
        # waiting, so FIFO fairness re-admits it first
        self.waiting.appendleft(victim)

    # ------------------------------------------------------------------ #
    # engine callbacks after each compute step

    def record_prefill(self, req: Request, token: int) -> None:
        """The engine prefilled ``req.prefix()`` and sampled ``token`` from
        the last position."""
        req.pos = len(req.prefix())
        req.generated.append(int(token))
        self._record_token_time(req)
        self._maybe_finish(req)

    def record_decode(self, req: Request, token: int) -> None:
        """One decode step: the previous ``last_token``'s k/v was written at
        slot ``pos`` and ``token`` sampled from the resulting logits."""
        req.pos += 1
        req.generated.append(int(token))
        self._record_token_time(req)
        self._maybe_finish(req)

    def _record_token_time(self, req: Request) -> None:
        """TTFT once per request (first token after the ORIGINAL arrival —
        a post-preemption re-prefill token counts as a per-output-token
        latency, not a second TTFT), TPOT for every token after it."""
        now = time.perf_counter()
        t = self.telemetry
        if t is not None:
            if req.t_first_token is None:
                t.ttft.observe((now - req.t_arrival) * 1e3)
            else:
                t.tpot.observe((now - req.t_last_token) * 1e3)
            t.generated_tokens.inc()
        if req.t_first_token is None:
            req.t_first_token = now
        req.t_last_token = now

    def _maybe_finish(self, req: Request) -> None:
        done = len(req.generated) >= req.max_new
        if req.eos is not None and req.generated[-1] == req.eos:
            done = True
        if done:
            req.state = FINISHED
            self.running.remove(req)
            self.allocator.free(req.blocks)
            req.blocks = []
            self.finished.append(req)
            if self.telemetry is not None:
                self.telemetry.finished.inc()
                self._tel_gauges()
