"""Inference engine.

Reference parity: ``deepspeed/inference/engine.py:35`` — ``InferenceEngine``
wraps a model for serving: dtype conversion, tensor-parallel sharding of the
weights, checkpoint loading, and a ``generate`` loop. The reference's three
injection modes (user policy / kernel injection / AutoTP,
``inference/engine.py:120-144``) map here to:

- models from ``deepspeed_tpu.models``: TP sharding comes from the model's
  own ``tp_specs()`` (policy equivalent);
- arbitrary param pytrees: ``AutoShard`` heuristics
  (``deepspeed_tpu.inference.auto_tp``) pick specs by name/shape, the AutoTP
  analogue;
- kernel injection = swapping the attention op for the Pallas decode kernel
  with KV cache (``deepspeed_tpu.ops``), enabled when available.

CUDA-graph capture/replay (reference ``:435-463``) is subsumed by ``jit``:
the decode step is one compiled program with a donated KV cache.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu.comm as dist
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.utils.fault_injection import step_fault as _step_fault
from deepspeed_tpu.utils.logging import log_dist, logger, warn_once


class InferenceEngine:

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None, params=None):
        self.module = model
        self._config = config or DeepSpeedInferenceConfig()
        # int8 = weight-only quantisation (reference GroupQuantizer,
        # module_inject/replace_module.py:135): activations run bf16, weight
        # matrices are stored int8 + per-group scales (see ops/quant.py)
        dt = str(getattr(self._config.dtype, "value", self._config.dtype))
        self._weight_quant = dt == "int8"
        # use_enum_values stores the plain string — map it explicitly (a
        # hasattr(.jnp) probe silently turned every requested dtype into bf16)
        self.dtype = {"fp32": jnp.float32, "fp16": jnp.float16,
                      "bf16": jnp.bfloat16, "int8": jnp.bfloat16}[dt]

        tp_size = self._config.tensor_parallel.tp_size
        # serving.tp: the paged serving engine's tensor-parallel degree —
        # one knob that implies the whole sharded-serving layout (params
        # via tp_specs/auto_tp, KV pools head-sharded, shard_map'd paged
        # kernel). 0 follows tensor_parallel.tp_size; both set and
        # disagreeing is a config contradiction, not a tie to break
        srv_tp = int(getattr(self._config.serving, "tp", 0) or 0)
        if srv_tp > 0:
            if tp_size > 1 and srv_tp != tp_size:
                raise ValueError(
                    f"serving.tp={srv_tp} conflicts with "
                    f"tensor_parallel.tp_size={tp_size}; set one (serving.tp"
                    " alone is enough for the serving engine)")
            tp_size = srv_tp
        # MoE serving (reference inference/engine.py:209-216 _create_ep_parallel_group):
        # the ep axis shards the expert dimension at serve time; gating and
        # attention replicate over it
        moe_cfg = self._config.moe
        if isinstance(moe_cfg, bool):
            moe_enabled, ep_size = moe_cfg, max(1, int(self._config.ep_size))
            moe_type = str(getattr(self._config.moe_type, "value", self._config.moe_type))
        else:
            moe_enabled = moe_cfg.enabled
            ep_size = max(int(moe_cfg.ep_size), int(self._config.ep_size), 1)
            moe_type = str(getattr(moe_cfg.type, "value", moe_cfg.type))
        self._ep_size = ep_size if moe_enabled else 1
        if moe_type not in ("standard", "residual"):
            raise NotImplementedError(
                f"MoE inference type {moe_type!r} is not implemented; "
                "'standard' and 'residual' (PR-MoE) are supported")
        self._moe_type = moe_type
        axes = {}
        if self._ep_size > 1:
            axes["ep"] = self._ep_size
        if tp_size > 1:
            axes["tp"] = tp_size
        axes["dp"] = -1
        if not dist.has_mesh():
            dist.init_mesh(axes)
            self.mesh = dist.get_mesh()
        else:
            mesh = dist.get_mesh()
            need = {a: s for a, s in axes.items() if a != "dp"}
            if all(mesh.shape.get(a, 1) == s for a, s in need.items()):
                self.mesh = mesh
            else:
                # the live mesh (a training run's, or another engine's)
                # does not carry this engine's tp/ep axes: silently
                # adopting it would serve UNSHARDED despite the explicit
                # config (every spec would sanitize to replicated). Build
                # a private mesh instead — the global one is left alone
                # (a training engine may own it) and ``_mesh_scope`` pins
                # ours around every forward/serve trace.
                from deepspeed_tpu.comm.mesh import build_mesh
                self.mesh = build_mesh(axes)
                log_dist(
                    f"InferenceEngine: existing mesh "
                    f"{dict(mesh.shape)} lacks the configured axes "
                    f"{need}; serving on a private mesh "
                    f"{dict(self.mesh.shape)}", ranks=[0])

        # checkpoint loading (reference inference/engine.py:354-419
        # _load_checkpoint): an HF checkpoint dir/file (or a model given as a
        # path string) loads through the per-architecture policies
        ckpt = self._config.checkpoint
        if isinstance(model, str) and ckpt is None:
            ckpt, model = model, None
        if params is None and isinstance(ckpt, str) and not ckpt.endswith(".json"):
            from deepspeed_tpu.module_inject import load_hf_checkpoint
            loaded_model, params = load_hf_checkpoint(ckpt)
            if model is None:
                model = loaded_model
            self.module = model = model if not isinstance(model, str) else loaded_model
            n_params = sum(int(np.prod(a.shape))
                           for a in jax.tree.leaves(params))
            log_dist(f"InferenceEngine: loaded HF checkpoint {ckpt} "
                     f"({n_params / 1e6:.1f}M params)", ranks=[0])
        elif params is None and isinstance(ckpt, (dict,)) or \
                (params is None and isinstance(ckpt, str) and ckpt.endswith(".json")):
            # ds_inference meta json (reference engine.py:354-419 sharded
            # "tp/pp" checkpoints): per-TP-rank Megatron files merged by the
            # SD loader, then mapped to the zoo layout for model.config
            from deepspeed_tpu.module_inject.megatron import load_megatron_checkpoint
            if model is None or not hasattr(model, "config"):
                raise ValueError("Megatron meta-json checkpoints need the model "
                                 "(with .config) passed to init_inference")
            params = load_megatron_checkpoint(ckpt, model.config)
            log_dist("InferenceEngine: loaded Megatron ds_inference checkpoint "
                     f"({len(jax.tree.leaves(params))} tensors)", ranks=[0])

        if params is None and hasattr(model, "init_params"):
            params = model.init_params(jax.random.key(0))
        if params is None:
            raise ValueError("InferenceEngine needs params (or a model with init_params, "
                             "or config.checkpoint pointing at an HF checkpoint)")

        # MoE models (zoo MoECausalLM shape: .moe config + aux-loss forward):
        # wire the serve mesh into the model so dispatch_combine constrains
        # the dispatched tensor to the ep axis (all-to-all over ICI), and
        # drop the aux loss from the served logits
        self._is_moe = hasattr(model, "moe") and hasattr(model, "_moe_mlp")
        if self._ep_size > 1 and not self._is_moe:
            raise ValueError(
                f"config.moe.ep_size={self._ep_size} but the model has no MoE "
                "layers; remove the moe section or serve an MoE model")
        if self._is_moe:
            n_experts = int(getattr(model.moe, "num_experts", 0))
            if self._ep_size > 1 and n_experts % self._ep_size:
                raise ValueError(
                    f"moe.ep_size={self._ep_size} must divide the model's "
                    f"num_experts={n_experts}")
            # the config's moe type and the model's architecture must agree:
            # serving a PR-MoE with standard routing (or vice versa) would be
            # silently wrong (reference moe_inference moe_type dispatch)
            model_residual = bool(getattr(model.moe, "use_residual", False))
            if model_residual != (self._moe_type == "residual"):
                raise ValueError(
                    f"config moe.type={self._moe_type!r} but the model "
                    f"{'IS' if model_residual else 'is NOT'} a residual "
                    "(PR-)MoE; set moe.type accordingly")
            # serve on a shallow copy bound to the serve mesh — mutating the
            # caller's model would clobber a training mesh (or an earlier
            # engine's) and put stale sharding constraints inside their jit
            import copy
            self.module = model = copy.copy(model)
            model.mesh = self.mesh

        tp_specs = None
        if hasattr(model, "tp_specs"):
            tp_specs = model.tp_specs() if callable(model.tp_specs) else model.tp_specs
        elif tp_size > 1:
            from deepspeed_tpu.inference.auto_tp import auto_tp_specs
            tp_specs = auto_tp_specs(params, tp=tp_size)
        if tp_specs is not None and tp_size > 1:
            # one divisibility gate for EVERY param layout (model-provided
            # and auto): a dim tp does not divide replicates with a warning
            # instead of relying on each placement path's silent drop
            from deepspeed_tpu.inference.auto_tp import validate_tp_specs
            tp_specs = validate_tp_specs(params, tp_specs, self.mesh)

        if self._weight_quant:
            from deepspeed_tpu.ops.quant import quantize_params, tree_nbytes
            groups = max(1, int(self._config.quant.weight.q_groups))
            dense_bytes = sum(a.size * 2 for a in jax.tree.leaves(params))
            params = quantize_params(params, groups=groups,
                                     include_embed=not getattr(getattr(model, "config", None),
                                                               "tie_embeddings", True))
            log_dist(f"int8 weight-only quantisation: q_groups={groups}, "
                     f"{dense_bytes / 2**20:.0f} MiB (bf16) -> "
                     f"{tree_nbytes(params) / 2**20:.0f} MiB at rest", ranks=[0])

        # ZeRO-Inference: layer weights stay in HOST memory and stream to the
        # device one layer at a time during forward/decode (reference
        # zero.stage3 + offload_param powering ZeRO-Inference; the BLOOM-176B
        # serving recipe). Device residency = one layer + activations + KV.
        off = dict(self._config.zero or {}).get("offload_param", {})
        off_dev = str(off.get("device", "none")).lower()
        # nvme: layer weights live on fast local storage and stream through
        # the native aio engine (reference partitioned_param_swapper.py:35
        # powering NVMe ZeRO-Inference); cpu: host RAM
        self._stream_weights = off_dev in ("cpu", "nvme")
        self._stream_nvme = off_dev == "nvme"
        if self._stream_nvme and not off.get("nvme_path"):
            raise ValueError("offload_param device='nvme' requires nvme_path")
        if self._stream_weights and not (hasattr(model, "config")
                                         and "layers" in params):
            raise ValueError("weight streaming needs a zoo-layout model "
                             "(.config + params['layers'] stacked per layer)")
        if self._stream_weights and getattr(model.config, "norm_position", "pre") == "post":
            # the streamed path is built from the pre-LN cached_* blocks
            raise ValueError("weight streaming supports pre-LN models only "
                             "(norm_position='post' has no cached path)")
        if self._stream_weights and (hasattr(model, "moe") or self._ep_size > 1):
            raise NotImplementedError(
                "ZeRO-Inference weight streaming of MoE models is not "
                "implemented (the streamed block is the dense cached path)")

        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.tree_util import GetAttrKey, tree_map_with_path

        def _is_qscale(path):
            # Quantized8.scale leaves (reached via a dataclass attr, unlike
            # dict-keyed layernorm "scale") stay f32
            return any(isinstance(k, GetAttrKey) and k.name == "scale" for k in path)

        if self._stream_weights:
            import numpy as _np
            import ml_dtypes
            np_dtype = {jnp.bfloat16: ml_dtypes.bfloat16,
                        jnp.float16: _np.float16,
                        jnp.float32: _np.float32}[self.dtype]

            def host_leaf(path, a):
                a = _np.asarray(a)
                if not _is_qscale(path) and _np.issubdtype(a.dtype, _np.floating):
                    a = a.astype(np_dtype)
                return a

            L = model.config.n_layer
            host_stack = tree_map_with_path(host_leaf, params["layers"])
            self._host_layers = [jax.tree.map(lambda a: a[i], host_stack)
                                 for i in range(L)]
            params = {k: v for k, v in params.items() if k != "layers"}
            host_bytes = sum(a.nbytes for lp in self._host_layers
                             for a in jax.tree.leaves(lp))
            self._n_stream_layers = L
            self._swapper = None
            # streaming x TP: the per-layer H2D copy lands SHARDED (each chip
            # receives its slice of the layer; XLA partitions the block step
            # and inserts the TP collectives). Non-layer params (embed/head)
            # stay replicated — they are small next to the layer stack.
            self._layer_put_shardings = None
            if tp_size > 1 and tp_specs is not None and "layers" in tp_specs:
                from deepspeed_tpu.ops.quant import (align_quant_groups,
                                                     quantized_shardings)
                drop_lead = lambda s: P(*list(s)[1:])  # unstack the layer dim
                per_layer = jax.tree.map(drop_lead, tp_specs["layers"],
                                         is_leaf=lambda x: isinstance(x, P))
                # regroup int8 scales (lossless subdivision) so the quant
                # axis stays sharded even when q_groups % tp != 0
                self._host_layers = [align_quant_groups(lp, per_layer, self.mesh)
                                     for lp in self._host_layers]
                self._layer_put_shardings = quantized_shardings(
                    self._host_layers[0], per_layer, self.mesh)
            elif tp_size > 1:
                logger.warning(
                    "weight streaming with tp_size>1 but no per-layer TP "
                    "specs: layers stream REPLICATED (no memory split or "
                    "speedup from the tp axis)")
            if self._stream_nvme:
                # leaves ride as raw bytes (dtype restored from in-memory
                # metadata — bf16 has no stable numpy dtype_str round-trip).
                # A unique per-engine subdir: engines sharing an nvme_path
                # must not overwrite each other's same-keyed swap files.
                import tempfile

                from deepspeed_tpu.runtime.swap_tensor.async_swapper import \
                    AsyncTensorSwapper
                os.makedirs(str(off.get("nvme_path")), exist_ok=True)
                self._sweep_stale_swap_dirs(str(off.get("nvme_path")))
                swap_dir = tempfile.mkdtemp(dir=str(off.get("nvme_path")),
                                            prefix="zero_inference_")
                # ownership marker: lets a future engine init reclaim this
                # model-sized footprint if we die without running finalizers
                with open(os.path.join(swap_dir, "owner.pid"), "w") as f:
                    f.write(self._owner_marker())
                self._swapper = AsyncTensorSwapper(swap_dir)
                # swap files are engine-lifetime caches of a model-sized
                # footprint: reclaim them on engine GC / interpreter exit
                import shutil
                import weakref
                self._swap_cleanup = weakref.finalize(
                    self, shutil.rmtree, swap_dir, True)
                self._layer_meta = []
                for i, lp in enumerate(self._host_layers):
                    leaves, treedef = jax.tree.flatten(lp)
                    metas = []
                    for j, a in enumerate(leaves):
                        a = _np.ascontiguousarray(a)
                        key = f"L{i}_{j}"
                        self._swapper.swap_out(key, a.view(_np.uint8).ravel(),
                                               async_op=True)
                        metas.append((key, a.shape, a.dtype))
                    # per-layer barrier: bounds staged aligned buffers to one
                    # layer (async across the whole model would transiently
                    # double the model's host footprint)
                    self._swapper.wait()
                    self._host_layers[i] = None  # free as we go
                    self._layer_meta.append((treedef, metas))
                self._host_layers = None  # host copy dropped; NVMe holds it
            where = (f"on NVMe at {off.get('nvme_path')}" if self._stream_nvme
                     else "resident on host")
            log_dist(f"ZeRO-Inference streaming: {L} layers "
                     f"({host_bytes / 2**20:.0f} MiB) {where}; device "
                     "holds two layers at a time (double-buffered)", ranks=[0])

        # quantized param trees (int8 config or quantize-on-load) carry
        # Quantized8 nodes: their payload+scale shardings are derived
        # together so group boundaries align with TP shard boundaries
        # (reference GroupQuantizer x TP slicing, replace_module.py:42-135)
        from deepspeed_tpu.ops.quant import (Quantized8, align_quant_groups,
                                             quantized_shardings)
        has_quant_nodes = any(isinstance(l, Quantized8) for l in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, Quantized8)))
        if tp_specs is not None and not self._stream_weights \
                and (self._weight_quant or has_quant_nodes):
            params = align_quant_groups(params, tp_specs, self.mesh)
            shardings = quantized_shardings(params, tp_specs, self.mesh)
        elif tp_specs is not None and not self._stream_weights:
            from deepspeed_tpu.runtime.zero.partition import ZeroShardingRules
            rules = ZeroShardingRules(self.mesh)  # stage 0: replicate except TP dims
            shardings = rules.param_shardings(params, tp_specs)
        else:
            shardings = jax.tree.map(lambda _: NamedSharding(self.mesh, P()), params)

        def put(path, a, s):
            a = jnp.asarray(a)
            # int8 payloads stay int8
            if _is_qscale(path) or not jnp.issubdtype(a.dtype, jnp.floating):
                return jax.device_put(a, s)
            return jax.device_put(a.astype(self.dtype), s)

        self.params = tree_map_with_path(put, params, shardings)

        self._fwd_jit = None
        self._prefill_jit = None
        self._decode_jit = None
        self._stream_jits = None
        self._paged_jits = None
        self._paged_alloc = None   # persistent prefix-cache allocator
        self._kv_host_pool = None  # persistent host-RAM KV tier (tiered
        # KV cache: cold prefix-cache blocks demote here instead of being
        # destroyed; content-addressed, so it outlives pool workspaces and
        # cache-off serves — only a geometry/dtype change rebuilds it)

        # ---- telemetry (serving stats + compile watchdog) ----
        tcfg = getattr(self._config, "telemetry", None)
        self._telemetry = tcfg if tcfg is not None and tcfg.enabled else None
        self._serving_tel = None
        # flight recorder: None when off, so every hot-path emit site in
        # generate_batch (and the scheduler it constructs) gates at one
        # None check and allocates nothing
        self._events = None
        self._serve_rid_base = 0   # rids unique across generate_batch calls
        self._active_session = None  # at most ONE paged serving session
        # owns the pools/jits at a time (generate_batch drain or an
        # AsyncServingEngine loop)
        if self._telemetry is not None:
            from deepspeed_tpu.inference.scheduler import ServingTelemetry
            from deepspeed_tpu.monitor.metrics import get_registry
            from deepspeed_tpu.monitor.trace import get_compile_watchdog
            reg = get_registry()
            reg.set_enabled(True)
            self._tel_reg = reg
            self._tel_watchdog = get_compile_watchdog()
            self._tel_watchdog.storm_threshold = tcfg.compile_storm_threshold
            self._serving_tel = ServingTelemetry(reg)
            if tcfg.events.enabled:
                from deepspeed_tpu.monitor.events import (TaggedRecorder,
                                                          get_flight_recorder)
                # every replica shares the ONE global ring; the per-engine
                # wrapper stamps replica= so the fleet renderer can group
                self._events = TaggedRecorder(get_flight_recorder().enable(
                    capacity=tcfg.events.capacity))

        log_dist(f"InferenceEngine ready: dtype={self.dtype.__name__}, tp={tp_size}, "
                 f"mesh={dict(self.mesh.shape)}"
                 + (", weight-streaming" if self._stream_weights else ""), ranks=[0])

    # ------------------------------------------------------------------ #

    def _watched(self, fn, name: str):
        """Route a compiled entry point through the compile watchdog when
        telemetry is on."""
        if self._telemetry is None:
            return fn
        return self._tel_watchdog.watch(fn, name)

    def telemetry_snapshot(self) -> Dict:
        """Whole-process registry snapshot plus the compile watchdog's
        summary. Empty dict when telemetry is off."""
        if self._telemetry is None:
            return {}
        from deepspeed_tpu.monitor.health import sample_memory_gauges
        sample_memory_gauges(self._tel_reg)
        snap = self._tel_reg.snapshot()
        snap["compile"] = self._tel_watchdog.summary()
        return snap

    def export_serving_trace(self, path: str) -> str:
        """Render the flight recorder's serving events as chrome-trace
        JSON (open in Perfetto / chrome://tracing): one track per request
        — its admission→retire span with prefill-chunk / decode-tick /
        COW child slices and preemption instants — plus queue-depth and
        KV-block counter tracks, so a whole ``generate_batch`` (or
        several: rids are unique across calls) is replayable. Requires
        ``telemetry.events`` on; validate the output with
        ``dscli trace --validate <path>``."""
        if self._events is None:
            raise ValueError(
                "serving trace export needs the flight recorder: set "
                "telemetry.events (e.g. telemetry={'events': True}) on "
                "init_inference")
        from deepspeed_tpu.monitor.events import export_serving_trace
        return export_serving_trace(self._events.snapshot(), path)

    def set_replica(self, name: str) -> None:
        """Name this engine's replica for observability: the tag lands on
        every flight-recorder event it emits (the fleet trace's track
        grouping) and on its ``serving/phase_ms`` / ``wasted_tokens``
        label sets. The router calls this at construction; a standalone
        engine stays ``r0``."""
        name = str(name)
        if self._events is not None:
            self._events.replica = name
        if self._serving_tel is not None:
            self._serving_tel.replica = name

    # ------------------------------------------------------------------ #

    def profile_model_time(self, use_cuda_events: bool = True) -> None:
        """Start recording per-forward model latency (reference
        profile_model_time; ``use_cuda_events`` accepted for parity — the
        timing here is a device-synchronized wall clock). Calling it again
        while already enabled is a no-op (a second enable must not silently
        drop the latencies recorded since the first)."""
        if getattr(self, "_model_profile_enabled", False):
            logger.warning("profile_model_time() called twice; model-time "
                           "profiling is already enabled — keeping the "
                           "recorded latencies (read them with model_times())")
            return
        self._model_profile_enabled = True
        self._model_times = []

    def model_times(self):
        """Drain the recorded per-forward latencies in seconds (reference
        model_times: asserts profiling was enabled first)."""
        if not getattr(self, "_model_profile_enabled", False):
            raise RuntimeError(
                "model profiling is not enabled; call profile_model_time() "
                "before forward")
        times = self._model_times
        self._model_times = []
        return times

    def forward(self, input_ids, attention_mask=None):
        """Full-sequence forward → logits."""
        if getattr(self, "_model_profile_enabled", False):
            import time as _t
            t0 = _t.perf_counter()
            out = self._forward_impl(input_ids, attention_mask)
            jax.block_until_ready(out)
            self._model_times.append(_t.perf_counter() - t0)
            return out
        return self._forward_impl(input_ids, attention_mask)

    def _forward_impl(self, input_ids, attention_mask=None):
        with self._mesh_scope():
            return self._forward_on_mesh(input_ids, attention_mask)

    def _forward_on_mesh(self, input_ids, attention_mask=None):
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if self._stream_weights:
            if input_ids.ndim == 1:
                input_ids = input_ids[None, :]
            pad_bias = None
            if attention_mask is not None:
                # [B, S] 1=keep mask → additive key-side bias over the cache
                # slots (the streamed blocks' pad_bias contract); the single
                # mask→bias producer shared by every attention path
                from deepspeed_tpu.models.transformer import key_mask_bias
                mask = jnp.asarray(attention_mask)
                if mask.ndim == 1:
                    mask = mask[None, :]
                pad_bias = key_mask_bias(mask)
            caches = self._stream_caches(input_ids.shape[0], input_ids.shape[1])
            logits, _ = self._streamed_step(input_ids, caches, jnp.int32(0),
                                            pad_bias=pad_bias)
            return logits
        if self._fwd_jit is None:
            fwd = self.module.forward if hasattr(self.module, "forward") else self.module
            if self._is_moe:
                # eval routing (eval_capacity_factor, no jitter/RTS) and the
                # aux loss dropped — serving returns logits only (reference
                # DeepSpeedMoEInference forward, moe_inference.py:300-364)
                self._fwd_jit = jax.jit(lambda p, t, m: fwd(p, t, m, train=False)[0])
            else:
                self._fwd_jit = jax.jit(lambda p, t, m: fwd(p, t, m))
            self._fwd_jit = self._watched(self._fwd_jit, "inference.forward")
        return self._fwd_jit(self.params, input_ids, attention_mask)

    # ------------------------------------------------------------------ #
    # ZeRO-Inference weight streaming: one layer on device at a time

    @staticmethod
    def _owner_marker() -> str:
        """``hostname:boot_id:pid_ns:pid`` — a pid is only meaningful inside
        its own host + boot + pid namespace (two containers can share a
        mount, a hostname, AND a boot id), so the liveness probe below
        refuses to judge markers from any other scope."""
        try:
            boot = open("/proc/sys/kernel/random/boot_id").read().strip()
        except OSError:  # non-Linux: no boot id, host scoping still applies
            boot = "-"
        try:
            pidns = os.readlink("/proc/self/ns/pid")  # e.g. pid:[4026531836]
        except OSError:
            pidns = "-"
        import socket
        return f"{socket.gethostname()}:{boot}:{pidns}:{os.getpid()}"

    @classmethod
    def _sweep_stale_swap_dirs(cls, nvme_path: str) -> None:
        """Reclaim zero_inference_* dirs whose owning process is gone. The
        weakref finalizer cleans up on normal exit, but a SIGKILLed process
        leaks a model-sized footprint; each dir carries an ``owner.pid``
        marker so the next engine init under the same nvme_path can sweep.
        Dirs owned by another host/boot/pid-namespace scope are never
        touched — os.kill(pid, 0) can't see across pid namespaces, so 'not
        found' outside our exact scope proves nothing."""
        import shutil
        me_scope, _ = cls._owner_marker().rsplit(":", 1)
        for name in os.listdir(nvme_path):
            d = os.path.join(nvme_path, name)
            if not (name.startswith("zero_inference_") and os.path.isdir(d)):
                continue
            try:
                marker = open(os.path.join(d, "owner.pid")).read().strip()
                scope, pid = marker.rsplit(":", 1)
                pid = int(pid)
            except (OSError, ValueError):
                continue  # pre-marker dir or mid-creation: leave it alone
            if scope != me_scope or pid == os.getpid():
                continue
            try:
                os.kill(pid, 0)  # signal 0: existence probe only
            except ProcessLookupError:
                logger.warning(f"sweeping stale ZeRO-Inference swap dir {d} "
                               f"(owner pid {pid} is dead)")
                shutil.rmtree(d, ignore_errors=True)
            except OSError:
                pass  # pid alive but not ours (EPERM): leave it alone

    def _put_layer(self, lp):
        """H2D copy of one layer's weights — TP-sharded when serving tp>1
        (each chip receives its slice), replicated otherwise."""
        if self._layer_put_shardings is None:
            return jax.device_put(lp)
        return jax.device_put(lp, self._layer_put_shardings)

    def _fetch_submit(self, i: int):
        """Kick off layer i's NVMe reads on the aio thread pool and return a
        handle; the data is NOT ready until :meth:`_fetch_finish`. RAM mode
        has nothing to overlap, so the handle is just the index."""
        if self._swapper is None:
            return i
        treedef, metas = self._layer_meta[i]
        # submit ALL of the layer's reads, then one barrier (in finish) —
        # per-leaf blocking swap_in would serialize the aio thread pool
        bufs = [self._swapper.swap_in(key, async_op=True)
                for key, _, _ in metas]
        return (treedef, metas, bufs)

    def _fetch_finish(self, handle):
        """Barrier the reads submitted by :meth:`_fetch_submit` and build the
        layer's weight tree. The swapper's wait() is global, so the caller
        must finish one submit before issuing the next."""
        if self._swapper is None:
            return self._host_layers[handle]
        treedef, metas, bufs = handle
        self._swapper.wait()
        leaves = []
        for buf, (key, shape, dtype) in zip(bufs, metas):
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            leaves.append(buf[:nbytes].copy().view(dtype).reshape(shape))
            self._swapper.release_buffer(buf)
        return jax.tree.unflatten(treedef, leaves)

    def _stream_caches(self, B: int, Smax: int):
        cfg = self.module.config
        shape = (B, Smax, cfg.kv_heads, cfg.head_dim)
        return [{"k": jnp.zeros(shape, self.dtype), "v": jnp.zeros(shape, self.dtype)}
                for _ in range(cfg.n_layer)]

    def _streamed_step(self, tokens, caches, pos, pad_bias=None):
        """tokens [B, T] against per-layer caches at offset pos: embed on
        device, then per layer H2D-copy the layer weights and run one jitted
        block (same compiled program for every layer — shapes match), then
        the head. The reference analogue is stage3 param fetch/release per
        module during inference forward."""
        from deepspeed_tpu.models import transformer as T
        cfg = self.module.config
        if self._stream_jits is None:
            emb = jax.jit(lambda p, t, pos: T.cached_embed(cfg, p, t, pos, self.dtype))
            blk = jax.jit(
                lambda h, lp, ck, cv, positions, pos, pb:
                T.cached_block(cfg, h, lp, ck, cv, positions, pos, pb),
                donate_argnums=(2, 3))
            head = jax.jit(lambda p, x: T.cached_head(cfg, p, x))
            self._stream_jits = (emb, blk, head)
        emb, blk, head = self._stream_jits
        x, positions = emb(self.params, tokens, pos)
        # double-buffered layer pipeline (reference analogue:
        # pipelined_optimizer_swapper.py's read-ahead): while blk(i) runs on
        # device, layer i+1's H2D copy is in flight (device_put is async) and
        # layer i+2's NVMe reads ride the aio thread pool — I/O, H2D and
        # compute all overlap at the cost of two layers resident on device.
        n = self._n_stream_layers
        pending = self._fetch_submit(0)
        host0 = self._fetch_finish(pending)
        pending = self._fetch_submit(1) if n > 1 else None
        nxt = self._put_layer(host0)
        for i in range(n):
            lp, nxt = nxt, None
            if i + 1 < n:
                # finish i+1's NVMe reads (hidden behind blk(i-1)), queue
                # i+2's, and start i+1's H2D — all before dispatching blk(i)
                host = self._fetch_finish(pending)
                pending = self._fetch_submit(i + 2) if i + 2 < n else None
                nxt = self._put_layer(host)
            x, nk, nv = blk(x, lp, caches[i]["k"], caches[i]["v"],
                            positions, pos, pad_bias)
            caches[i] = {"k": nk, "v": nv}
        return head(self.params, x), caches

    def _generate_streamed(self, input_ids, max_new, temperature, top_k, rng,
                           eos_token_id):
        B, prompt_len = input_ids.shape
        cfg = self.module.config
        Smax = self._bucket(prompt_len + max_new, cfg.max_seq)
        bucket = self._bucket(prompt_len, Smax)
        caches = self._stream_caches(B, Smax)

        if max_new <= 0:
            return input_ids
        pad = bucket - prompt_len
        toks = jnp.pad(input_ids, ((0, 0), (0, pad))) if pad else input_ids
        logits, caches = self._streamed_step(toks, caches, jnp.int32(0))
        rng, sub = jax.random.split(rng)
        nxt = self._sample_host(logits[:, prompt_len - 1].astype(jnp.float32),
                                temperature, top_k, sub)
        eos = eos_token_id
        done = (nxt == eos) if eos is not None else None
        generated = [np.asarray(nxt, np.int32)]
        for step in range(1, max_new):
            if eos is not None and bool(done.all()):
                break
            pos = prompt_len + step - 1
            logits, caches = self._streamed_step(
                nxt[:, None].astype(jnp.int32), caches, jnp.int32(pos))
            rng, sub = jax.random.split(rng)
            nxt = self._sample_host(logits[:, -1].astype(jnp.float32),
                                    temperature, top_k, sub)
            if eos is not None:
                # rows already done keep emitting eos (stable batched output,
                # same invariant as the compiled decode loop)
                nxt = jnp.where(done, eos, nxt)
                done = done | (nxt == eos)
            generated.append(np.asarray(nxt, np.int32))
        gen = jnp.asarray(np.stack(generated, axis=1), jnp.int32)
        return jnp.concatenate([input_ids, gen], axis=1)

    __call__ = forward

    def _reject_encoders(self, what: str) -> None:
        """Encoders run autoregressively emit nonsense (bidirectional
        attention, or hidden states instead of vocab logits) — reject
        loudly (the reference's engine.generate delegates to
        module.generate, which encoder models don't have either)."""
        from deepspeed_tpu.models.bert import BertModel
        from deepspeed_tpu.models.clip import (CLIPTextEncoder,
                                               CLIPVisionEncoder,
                                               DSClipEncoder)
        zoo_cfg = getattr(self.module, "zoo_cfg",
                          getattr(self.module, "config", None))
        if (isinstance(self.module, (BertModel, CLIPTextEncoder,
                                     CLIPVisionEncoder, DSClipEncoder))
                or getattr(zoo_cfg, "causal", True) is False):
            raise ValueError(
                f"{type(self.module).__name__} is an encoder; {what} "
                "requires a causal LM — use engine.forward for hidden "
                "states / MLM logits")

    def generate(self, input_ids, max_new_tokens: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0, eos_token_id: Optional[int] = None):
        """Autoregressive generation (greedy or sampled).

        This baseline path recomputes the full prefix per step (correct for
        every model in the zoo); the Pallas KV-cache decode path replaces it
        when kernel injection is enabled. ``max_out_tokens`` semantics follow
        the reference (inference/engine.py:523 token-length check).
        """
        with self._mesh_scope():
            return self._generate(input_ids, max_new_tokens, temperature,
                                  top_k, seed, eos_token_id)

    def _generate(self, input_ids, max_new_tokens, temperature, top_k, seed,
                  eos_token_id):
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if input_ids.ndim == 1:
            input_ids = input_ids[None, :]
        self._reject_encoders("generate()")
        max_new = max_new_tokens if max_new_tokens is not None else self._config.max_out_tokens
        max_len = input_ids.shape[1] + max_new
        cfg = getattr(self.module, "config", None)
        if cfg is not None and hasattr(cfg, "max_seq") and max_len > cfg.max_seq:
            raise ValueError(f"Input+generated length {max_len} exceeds model max_seq {cfg.max_seq}; "
                             f"reduce max_new_tokens (reference max_out_tokens check)")

        rng = jax.random.key(seed)
        if self._stream_weights:
            return self._generate_streamed(input_ids, max_new, temperature,
                                           top_k, rng, eos_token_id)
        if hasattr(self.module, "forward_cached") and hasattr(self.module, "init_cache"):
            return self._generate_cached(input_ids, max_new, temperature, top_k, rng, eos_token_id)

        # fallback for models without a cached forward: full-prefix recompute
        tokens = input_ids
        for _ in range(max_new):
            logits = self.forward(tokens)[:, -1, :].astype(jnp.float32)
            # split first, consume the child: sampling with `rng` and then
            # splitting the SAME consumed key correlates the next step's
            # stream with the draw already made (DS002; every other
            # generate path uses this split-then-sample order)
            rng, sub = jax.random.split(rng)
            nxt = self._sample_host(logits, temperature, top_k, sub)
            tokens = jnp.concatenate([tokens, nxt[:, None].astype(jnp.int32)], axis=1)
            if eos_token_id is not None and bool((nxt == eos_token_id).all()):
                break
        return tokens

    @staticmethod
    def _sample_host(logits, temperature, top_k, rng):
        if temperature > 0.0:
            logits = logits / temperature
            if top_k > 0:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            return jax.random.categorical(rng, logits, axis=-1)
        return jnp.argmax(logits, axis=-1)

    # ------------------------------------------------------------------ #
    # KV-cache generation: prefill + fixed-shape decode, no per-token
    # recompilation (reference workspace/KV design: inference_context.h:49,
    # softmax_context pt_binding.cpp:1668-1793)

    def _mesh_scope(self):
        """Pin the framework mesh VIEW to THIS engine's mesh for the
        duration of a serve. The transformer-level kernel dispatch
        (``_flash_mesh`` / ``_bare_pallas_legal``) reads ``dist.get_mesh``
        at trace time, so two engines with different tp degrees serving
        from one process must not trace against each other's mesh. The pin
        is a THREAD-LOCAL override (``dist.mesh_override``), never a write
        to the process-global mesh: the always-on serving loop traces from
        its own thread, and toggling the global there would race a
        training engine (or another serving engine) tracing concurrently
        on another thread."""
        return dist.mesh_override(self.mesh)

    def _kv_head_sharding(self):
        """NamedSharding for the KV workspaces — the dense cache
        [L, B, S, KV, Hd] and the paged pools [L, blocks, bs, KV, Hd] share
        the rank-5 KV-heads-at-axis-3 layout: head-sharded over ``tp``
        when the model's KV heads divide the axis (per-chip KV bytes drop
        to 1/tp; block tables stay replicated because per-shard block
        indices are identical), replicated with a rate-limited warning
        otherwise — serving stays correct, just without the memory split."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        tp = self.mesh.shape.get("tp", 1)
        if tp > 1:
            kvh = getattr(getattr(self.module, "config", None),
                          "kv_heads", None)
            if kvh is not None and kvh % tp == 0:
                return NamedSharding(self.mesh,
                                     P(None, None, None, "tp", None))
            warn_once(f"serving tp={tp} does not divide the model's "
                      f"kv_heads={kvh}: KV caches/pools replicate over the "
                      "tp axis (params still shard, but there is no KV "
                      "memory split)")
        return NamedSharding(self.mesh, P())

    def _kv_slice_sharding(self):
        """NamedSharding for ONE block's per-layer k/v slice
        ``[L, bs, KV, Hd]`` — the tiered KV cache's D2H/H2D unit. Under
        ``serving.tp`` the slice lands head-sharded exactly like the
        pools themselves (axis 2 here = axis 3 of the rank-5 pool), so a
        spill gathers each shard's local heads and a fetch scatters them
        back without ever gathering the pool."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        pool_sh = self._kv_head_sharding()
        if any(s is not None for s in pool_sh.spec):
            return NamedSharding(self.mesh, P(None, None, "tp", None))
        return NamedSharding(self.mesh, P())

    def _kv_host_pool_for(self, num_blocks: int, block_size: int,
                          caching: bool):
        """The persistent host-RAM KV tier for the current serving
        geometry, or None when ``serving.kv_host`` is off (or prefix
        caching is — the tier is keyed by the cache's hash chains).
        Content addressing makes entries valid across serves and even
        fresh pool workspaces; only a geometry/dtype change rebuilds."""
        kh = getattr(self._config.serving, "kv_host", None)
        if kh is None or not kh.enabled or not caching:
            return None
        if str(kh.spill) not in ("auto", "off"):
            raise ValueError(
                f"serving.kv_host.spill={kh.spill!r} (expected auto|off)")
        cfg = self.module.config
        shape = (cfg.n_layer, block_size, cfg.kv_heads, cfg.head_dim)
        dtype = self.dtype.__name__
        cap = int(kh.max_host_blocks) or 4 * max(num_blocks - 1, 1)
        pool = self._kv_host_pool
        if pool is not None and pool.matches_geometry(shape, dtype) \
                and pool.max_blocks == cap:
            return pool
        from deepspeed_tpu.inference.kv_host_pool import KvHostPool
        pool = KvHostPool(cap, shape, dtype, telemetry=self._serving_tel)
        self._kv_host_pool = pool
        log_dist(f"tiered KV cache: host pool of {cap} blocks "
                 f"({shape}, {dtype}) attached behind the block allocator",
                 ranks=[0])
        return pool

    def ensure_host_kv_pool(self):
        """Materialize (or return) the persistent host-RAM KV tier for
        this engine's CURRENT serving geometry without opening a session
        — the replica-router builder uses it to stand the shared pool up
        on the first replica before any session exists. None when
        ``serving.kv_host`` is off or the model cannot prefix-cache."""
        srv = self._config.serving
        bs = int(srv.block_size)
        cfg = self.module.config
        n_max = -(-cfg.max_seq // bs)
        num_blocks = int(srv.max_num_blocks) or \
            (int(srv.max_running) * n_max + 1)
        caching = (hasattr(self.module, "forward_paged_prefill_chunk")
                   and str(srv.prefix_caching) != "off")
        return self._kv_host_pool_for(num_blocks, bs, caching)

    def adopt_host_kv_pool(self, pool) -> None:
        """Share another engine's host KV tier — the dp serving axis's KV
        transport (``inference/router.py``): a prefill replica demotes a
        prompt's committed blocks into the SHARED content-addressed pool
        and a decode replica's tiered admission fetches them H2D, so
        disaggregated prefill/decode needs no new wire format. The pool
        must match this engine's serving geometry (same block slice shape
        + dtype — content addresses are only portable between identical
        layouts); subsequent serve sessions then reuse it instead of
        building a private tier."""
        if pool is None:
            self._kv_host_pool = None
            return
        cfg = self.module.config
        shape = (cfg.n_layer, int(self._config.serving.block_size),
                 cfg.kv_heads, cfg.head_dim)
        if not pool.matches_geometry(shape, self.dtype.__name__):
            raise ValueError(
                f"host KV pool geometry {pool.block_shape}/{pool.dtype} "
                f"does not match this engine's {shape}/"
                f"{self.dtype.__name__} — replicas can only share a tier "
                "when their serving geometry is identical")
        self._kv_host_pool = pool

    def _kv_workspace(self, B: int, need_len: int):
        """Persistent KV workspace (reference ``inference_context.h:49``:
        one workspace allocated once and reused across calls). Grows
        monotonically in length AND batch: a call with ``B`` smaller than
        the allocated batch runs on a sliced copy instead of reallocating
        (the larger workspace is kept for future calls — ``owned=False``
        tells the caller not to store the sliced copy back). Reuse is safe
        because the causal mask hides slots beyond the current position.
        Returns ``(cache, Smax, owned)``."""
        ws = getattr(self, "_workspace", None)
        if ws is not None and ws[0] >= B and ws[1] >= need_len:
            leaves = jax.tree.leaves(ws[2])
            if not any(getattr(a, "is_deleted", lambda: False)() for a in leaves):
                if ws[0] == B:
                    return ws[2], ws[1], True
                # smaller batch: slice rows [0, B) of the [L, B0, S, KV, Hd]
                # cache — a copy, so donating it through prefill/decode
                # leaves the full workspace intact
                return jax.tree.map(lambda a: a[:, :B], ws[2]), ws[1], False
        cfg = self.module.config
        Smax = min(cfg.max_seq, max(need_len, int(self._config.max_out_tokens)))
        cache = self.module.init_cache(B, Smax, dtype=self.dtype)
        kv_sh = self._kv_head_sharding()
        cache = jax.tree.map(lambda a: jax.device_put(a, kv_sh), cache)
        self._workspace = (B, Smax, cache)
        return cache, Smax, True

    @staticmethod
    def _bucket(n: int, cap: int) -> int:
        """Pad prompt lengths up to multiples of 128 (one compile per bucket,
        MXU-aligned), clamped to the model's max."""
        return min(-(-max(n, 1) // 128) * 128, cap)

    def _generate_cached(self, input_ids, max_new, temperature, top_k, rng, eos_token_id):
        if max_new <= 0:
            return input_ids
        B, prompt_len = input_ids.shape
        cfg = self.module.config
        cache, Smax, ws_owned = self._kv_workspace(
            B, min(cfg.max_seq, prompt_len + max_new))
        bucket = self._bucket(prompt_len, Smax)

        if self._decode_jit is None:
            def prefill(params, toks, cache, last_idx):
                # toks are RIGHT-padded to the bucket; junk cache slots are
                # overwritten by decode or masked by causality. MoE modules
                # additionally get a validity mask so bucket padding never
                # competes for expert capacity (top1 used_token)
                kw = {}
                if self._is_moe:
                    kw["valid"] = (jnp.arange(toks.shape[1])[None, :]
                                   <= last_idx).astype(jnp.float32)
                    kw["valid"] = jnp.broadcast_to(kw["valid"], toks.shape)
                logits, cache = self.module.forward_cached(
                    params, toks, cache, jnp.int32(0), **kw)
                return logits[:, last_idx, :].astype(jnp.float32), cache

            def sample(logits, rng, temperature, top_k):
                return jax.lax.cond(
                    temperature > 0.0,
                    lambda: self._sample_jit(logits, temperature, top_k, rng),
                    lambda: jnp.argmax(logits, axis=-1))

            def decode_loop(params, cache, first, pos0, max_new, rng, temperature,
                            top_k, eos, out_cap):
                """Whole decode loop on device: one host transfer per call,
                early exit when every row has emitted eos (eos < 0 = never).
                ``out_cap`` (static, the 128-bucketed max_new) bounds the
                output buffer — sizing it to the cache capacity wasted HBM
                and host-transfer bytes on every short generation."""
                Bd = first.shape[0]
                out0 = jnp.zeros((Bd, out_cap), jnp.int32)
                out0 = out0.at[:, 0].set(first)
                done0 = (first == eos) & (eos >= 0)

                def cond(st):
                    step, _, _, _, done, _ = st
                    return (step < max_new) & ~jnp.all(done)

                def body(st):
                    step, tok, pos, r, done, (cache, out) = st
                    logits, cache = self.module.forward_cached(
                        params, tok[:, None].astype(jnp.int32), cache, pos)
                    r, sub = jax.random.split(r)
                    nxt = sample(logits[:, -1, :].astype(jnp.float32), sub,
                                 temperature, top_k)
                    # rows already done keep emitting eos (stable output)
                    nxt = jnp.where(done & (eos >= 0), eos, nxt)
                    out = jax.lax.dynamic_update_slice(out, nxt[:, None].astype(jnp.int32),
                                                       (0, step))
                    done = done | ((nxt == eos) & (eos >= 0))
                    return step + 1, nxt, pos + 1, r, done, (cache, out)

                st = (jnp.int32(1), first, pos0, rng, done0, (cache, out0))
                step, _, _, _, _, (cache, out) = jax.lax.while_loop(cond, body, st)
                return out, step, cache

            self._prefill_jit = self._watched(
                jax.jit(prefill, donate_argnums=(2,)), "inference.prefill")
            self._decode_jit = self._watched(
                jax.jit(decode_loop, donate_argnums=(1,), static_argnums=(9,)),
                "inference.decode_loop")

        pad = bucket - prompt_len
        toks = jnp.pad(input_ids, ((0, 0), (0, pad))) if pad else input_ids
        logits0, cache = self._prefill_jit(self.params, toks, cache,
                                           jnp.int32(prompt_len - 1))
        rng, sub = jax.random.split(rng)
        first = jnp.asarray(self._sample_host(logits0, temperature, top_k, sub))

        eos = jnp.int32(-1 if eos_token_id is None else eos_token_id)
        # one compile per 128-bucket of max_new (max_new itself stays traced)
        out_cap = min(Smax, self._bucket(max_new, Smax))
        out, n, cache = self._decode_jit(self.params, cache, first,
                                         jnp.int32(prompt_len), jnp.int32(max_new),
                                         rng, jnp.float32(temperature),
                                         jnp.int32(top_k), eos, out_cap)
        if ws_owned:
            self._workspace = (B, Smax, cache)  # keep the donated-through workspace
        n = int(n)
        gen = np.asarray(out)[:, :n]
        return jnp.concatenate([input_ids, jnp.asarray(gen, jnp.int32)], axis=1)

    # ------------------------------------------------------------------ #
    # Paged KV cache + continuous batching (vLLM PagedAttention / Orca
    # iteration-level scheduling): KV block pools shared by every in-flight
    # request, per-request block tables, one fused decode step over ALL
    # running requests per engine step, finished rows retired and queued
    # requests admitted in their place. Memory is bounded by tokens in
    # flight (not B × Smax) and a slow request never convoys the batch.

    def _paged_supported(self) -> bool:
        return (not self._stream_weights and not self._is_moe
                and hasattr(self.module, "forward_paged_decode")
                and hasattr(self.module, "forward_paged_prefill")
                and hasattr(self.module, "init_paged_cache")
                and hasattr(self.module, "config"))

    def _paged_pools(self, num_blocks: int, block_size: int):
        """Persistent paged-pool workspace: same lifecycle contract as
        :meth:`_kv_workspace` (reuse is safe — every slot a request reads
        was written by that request, or by the request that REGISTERED the
        block in the prefix cache). Returns ``(pools, reused)`` — a fresh
        workspace has no valid cached content, so the caller must drop any
        persisted prefix-cache state alongside it."""
        pw = getattr(self, "_paged_workspace", None)
        if pw is not None and pw[0] == num_blocks and pw[1] == block_size:
            leaves = jax.tree.leaves(pw[2])
            if not any(getattr(a, "is_deleted", lambda: False)() for a in leaves):
                return pw[2], True
        pools = self.module.init_paged_cache(num_blocks, block_size,
                                             dtype=self.dtype)
        kv_sh = self._kv_head_sharding()
        pools = jax.tree.map(lambda a: jax.device_put(a, kv_sh), pools)
        self._paged_workspace = (num_blocks, block_size, pools)
        return pools, False

    def _paged_allocator(self, num_blocks: int, block_size: int,
                         caching: bool, pools_reused: bool):
        """Block allocator for one serve call. With prefix caching the
        allocator PERSISTS across ``generate_batch`` calls — its
        content-addressed table describes the persistent pool workspace, so
        later calls hit earlier calls' prefixes — as long as the workspace
        itself was reused, geometry matches, and every request of the
        previous call retired cleanly (no leaked references). A cache-off
        call writes blocks the persisted table still describes, so it also
        invalidates the persisted allocator."""
        from deepspeed_tpu.inference.block_allocator import BlockAllocator

        if not caching:
            self._paged_alloc = None
            return BlockAllocator(num_blocks, block_size)
        pa = self._paged_alloc
        if (pools_reused and pa is not None
                and pa.num_blocks == num_blocks
                and pa.block_size == block_size
                and not pa.leak_report()):
            return pa
        alloc = BlockAllocator(num_blocks, block_size, prefix_cache=True)
        self._paged_alloc = alloc
        return alloc

    def _ensure_paged_jits(self):
        if self._paged_jits is None:
            from deepspeed_tpu.models.transformer import copy_paged_block
            mod = self.module
            kv_sh = self._kv_head_sharding()
            pin_sh = kv_sh if any(s is not None for s in kv_sh.spec) else None

            def _pin(pools):
                # NamedSharding-constrained workspaces: under tp the pools
                # must come OUT of every fused step still head-sharded
                # (donation pairs the constrained output with the sharded
                # input buffer), so the row-projection psum is each layer's
                # only collective — unconstrained, the partitioner is free
                # to gather the pool on the way out
                if pin_sh is None:
                    return pools
                return jax.tree.map(
                    lambda a: jax.lax.with_sharding_constraint(a, pin_sh),
                    pools)

            def _pinned(fn):
                def run(*args):
                    logits, pools = fn(*args)
                    return logits, _pin(pools)
                return run

            chunk = None
            if hasattr(mod, "forward_paged_prefill_chunk"):
                chunk = self._watched(
                    jax.jit(lambda p, t, pools, bt, slots, sp, li:
                            _pinned(mod.forward_paged_prefill_chunk)(
                                p, t, pools, bt, slots, sp, li),
                            donate_argnums=(2,)),
                    "inference.paged_prefill_chunk")
            verify = None
            if hasattr(mod, "forward_paged_verify"):
                verify = self._watched(
                    jax.jit(lambda p, t, pools, bt, slots, pos:
                            _pinned(mod.forward_paged_verify)(
                                p, t, pools, bt, slots, pos),
                            donate_argnums=(2,)),
                    "inference.paged_verify")
            # tiered KV cache copy programs: the per-block D2H gather
            # (spill) and H2D scatter (fetch). The block index is traced,
            # so each is ONE program regardless of which block moves; the
            # slice is pinned to the pool's head sharding (under tp each
            # shard moves only its local heads). Gather does NOT donate —
            # the pools live on; scatter donates like every fused step.
            slice_sh = self._kv_slice_sharding()
            slice_pin = slice_sh if any(s is not None for s in slice_sh.spec)\
                else None

            def _pin_slice(a):
                if slice_pin is None:
                    return a
                return jax.lax.with_sharding_constraint(a, slice_pin)

            spill_gather = self._watched(
                jax.jit(lambda pools, b: {
                    "k": _pin_slice(jax.lax.dynamic_index_in_dim(
                        pools["k"], b, axis=1, keepdims=False)),
                    "v": _pin_slice(jax.lax.dynamic_index_in_dim(
                        pools["v"], b, axis=1, keepdims=False))}),
                "inference.paged_spill_gather")
            fetch_scatter = self._watched(
                jax.jit(lambda pools, b, ks, vs: _pin({
                    "k": jax.lax.dynamic_update_index_in_dim(
                        pools["k"], ks.astype(pools["k"].dtype), b, axis=1),
                    "v": jax.lax.dynamic_update_index_in_dim(
                        pools["v"], vs.astype(pools["v"].dtype), b, axis=1)}),
                        donate_argnums=(0,)),
                "inference.paged_fetch_scatter")
            self._paged_jits = (
                self._watched(
                    jax.jit(lambda p, t, pools, slots, li:
                            _pinned(mod.forward_paged_prefill)(
                                p, t, pools, slots, li),
                            donate_argnums=(2,)),
                    "inference.paged_prefill"),
                self._watched(
                    jax.jit(lambda p, t, pools, bt, pos:
                            _pinned(mod.forward_paged_decode)(
                                p, t, pools, bt, pos),
                            donate_argnums=(2,)),
                    "inference.paged_decode"),
                chunk,
                self._watched(
                    jax.jit(lambda pools, src, dst:
                            _pin(copy_paged_block(pools, src, dst)),
                            donate_argnums=(0,)),
                    "inference.paged_cow"),
                verify,
                spill_gather,
                fetch_scatter,
            )
        return self._paged_jits

    @staticmethod
    def _flat_slots(table, start, n_valid, width, bs):
        """Flat pool slot per position ``start + t`` for t in [0, width):
        the first ``n_valid`` positions write through the request's block
        table, compile-bucket pads route their junk k/v to the dummy
        block. The ONE place the slot layout lives — whole-prompt prefill
        and chunked prefill must scatter identically."""
        from deepspeed_tpu.inference.block_allocator import DUMMY_BLOCK
        t = np.arange(width)
        p_t = start + t                              # global positions
        slot = table[np.minimum(p_t // bs, table.size - 1)] * bs + p_t % bs
        return np.where(t < n_valid, slot, DUMMY_BLOCK * bs + p_t % bs)

    def generate_batch(self, prompts, max_new_tokens: Optional[int] = None,
                       temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                       eos_token_id: Optional[int] = None):
        """Serve a batch of variable-length prompts with continuous batching
        over the paged KV cache. Returns a list of 1-D int32 arrays
        (prompt + generated tokens, stopping at eos / max_new per request),
        in the order the prompts were given.

        ``config.serving`` governs the path: ``paged="auto"`` (default)
        pages whenever the model supports it, ``"on"`` requires it,
        ``"off"`` — and unsupported models under auto — falls back to the
        static ``generate`` path per request. ``prefix_caching`` (default
        auto = on) shares already-computed KV blocks across requests AND
        across calls (the pool workspace persists); ``prefill_chunk_tokens``
        interleaves prefill chunks with decode steps;
        ``speculative: {mode: "ngram", k}`` turns on draft-free
        self-speculation — verified multi-token decode steps that emit
        (accepted + 1) tokens per fused step on repetitive workloads.
        ``serving.tp`` > 0 serves tensor-parallel over a ``tp`` mesh axis:
        params column/row-sharded, KV pools split on the KV-head dim,
        the fused steps running with exactly one all-reduce per layer and
        the Pallas paged kernel dispatched per-shard via shard_map —
        token-identical to the tp=1 engine (greedy), with decode
        throughput and max model size scaling with the slice.
        """
        with self._mesh_scope():
            return self._generate_batch(prompts, max_new_tokens, temperature,
                                        top_k, seed, eos_token_id)

    def _generate_batch(self, prompts, max_new_tokens, temperature, top_k,
                        seed, eos_token_id):
        prompts = [np.asarray(p, np.int32).reshape(-1) for p in prompts]
        if not prompts:
            return []
        self._reject_encoders("generate_batch()")
        srv = self._config.serving
        mode = str(srv.paged)
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"serving.paged={mode!r} (expected auto|on|off)")
        supported = self._paged_supported()
        if mode == "on" and not supported:
            raise ValueError(
                "serving.paged='on' but this engine cannot page: the model "
                "must be a zoo causal LM (forward_paged_decode) and the "
                "engine must not be weight-streaming or MoE")
        max_new = (max_new_tokens if max_new_tokens is not None
                   else self._config.max_out_tokens)
        if mode == "off" or not supported:
            if str(srv.speculative.mode) == "ngram":
                # the same courtesy the temperature>0 case gets: the user
                # configured speculation, and silence would read as "on"
                warn_once("serving.speculative is ignored on the static "
                          "(non-paged) serving path — speculation needs "
                          "the paged engine (serving.paged)")
            # static fallback: each request through the (batched-workspace)
            # generate path, one at a time — correct for every engine mode.
            # Per-request seed offset: sampled mode must not hand every
            # request (or duplicate prompts) the same rng stream
            return [self.generate(p[None, :], max_new_tokens=max_new,
                                  temperature=temperature, top_k=top_k,
                                  seed=seed + i, eos_token_id=eos_token_id)[0]
                    for i, p in enumerate(prompts)]
        if max_new <= 0:
            return [jnp.asarray(p) for p in prompts]

        session = self.open_serve_session(
            max_new=max_new, temperature=temperature, top_k=top_k,
            seed=seed, eos_token_id=eos_token_id)
        ev = self._events
        t_serve0 = time.monotonic_ns() if ev is not None else 0
        if ev is not None:
            ev.emit("serve.begin", t_ns=t_serve0, requests=len(prompts))
        # the try/finally guards rid uniqueness: even when a serve aborts
        # (oversized prompt, pool exhaustion) the next serve's rids must
        # not collide with this one's in the shared flight-recorder ring
        try:
            for p in prompts:
                session.add(p)
            while session.step():
                pass
        finally:
            session.close()
        if ev is not None:
            ev.emit("serve.end", t_ns=t_serve0,
                    dur_ns=time.monotonic_ns() - t_serve0,
                    requests=len(prompts))
        session.end()
        sched = session.sched
        failed = [r for r in sched.finished if r.error is not None]
        if failed:
            # a silently truncated generation is worse than a loud failure:
            # this only happens when preemption grew a request's prefix past
            # what the pool can EVER hold — the same misconfiguration
            # add_request rejects up front, arising dynamically
            raise RuntimeError(
                f"{len(failed)} request(s) retired without completing "
                "(KV pool too small for the workload — raise "
                "serving.max_num_blocks): "
                + "; ".join(f"request {r.rid}: {r.error}" for r in failed))
        done = sorted(sched.finished, key=lambda r: r.rid)
        return [jnp.asarray(r.output) for r in done]

    def open_serve_session(self, *, max_new: int, temperature: float = 0.0,
                           top_k: int = 0, seed: int = 0,
                           eos_token_id: Optional[int] = None, policy=None,
                           on_tokens=None, on_finish=None,
                           retain_finished: bool = True):
        """Open one paged serving session: the scheduler, the persistent
        pool workspace, and the fused-step jit context, bundled behind a
        step API (:class:`_ServeSession`). BOTH entry points run through
        it — ``generate_batch`` adds its whole batch and drains, the
        always-on ``AsyncServingEngine`` (``inference/serve.py``) feeds
        arrivals in as they come — so the open-loop path executes exactly
        the closed-loop compiled programs (the ``serving_async_steady``
        compile-budget contract). At most one session may be active per
        engine: the pools are donated through every fused step, so a
        second concurrent user would read deleted buffers.

        ``policy`` plugs a scheduling policy (``inference/policy.py``)
        into the scheduler; ``on_tokens(req, tokens)`` streams each
        emitted burst (speculation emits multi-token bursts) and
        ``on_finish(req)`` fires once per retired request — both host-side
        callbacks on the serving thread."""
        if self._active_session is not None:
            raise RuntimeError(
                "another serving session is active on this engine (an "
                "AsyncServingEngine loop, or a generate_batch in flight); "
                "drain/shutdown it before opening a new one")
        srv = self._config.serving
        if str(srv.paged) == "off" or not self._paged_supported():
            raise ValueError(
                "a serving session needs the paged engine (zoo causal LM, "
                "not weight-streaming/MoE, serving.paged != 'off') — the "
                "serving loop has no static fallback")
        if max_new <= 0:
            raise ValueError("a serving session needs max_new >= 1")

        from deepspeed_tpu.inference.scheduler import \
            ContinuousBatchingScheduler

        cfg = self.module.config
        bs = int(srv.block_size)
        W = int(srv.max_running)
        n_max = -(-cfg.max_seq // bs)          # block-table width
        num_blocks = int(srv.max_num_blocks) or (W * n_max + 1)

        # prefix caching + chunked prefill both ride the chunk forward
        pc_mode = str(srv.prefix_caching)
        if pc_mode not in ("auto", "on", "off"):
            raise ValueError(
                f"serving.prefix_caching={pc_mode!r} (expected auto|on|off)")
        chunk_tokens = int(srv.prefill_chunk_tokens)
        if chunk_tokens < 0:
            raise ValueError("serving.prefill_chunk_tokens must be >= 0")
        chunk_ok = hasattr(self.module, "forward_paged_prefill_chunk")
        if not chunk_ok:
            if pc_mode == "on":
                raise ValueError(
                    "serving.prefix_caching='on' but the model has no "
                    "forward_paged_prefill_chunk (needed to prefill the "
                    "uncached tail against cached blocks)")
            if chunk_tokens:
                raise ValueError(
                    "serving.prefill_chunk_tokens set but the model has no "
                    "forward_paged_prefill_chunk")
        caching = chunk_ok and pc_mode != "off"

        # ---- speculative decoding (n-gram self-speculation) ----
        spec = srv.speculative
        spec_mode = str(spec.mode)
        if spec_mode not in ("off", "ngram", "auto"):
            raise ValueError(f"serving.speculative.mode={spec_mode!r} "
                             "(expected off|ngram|auto)")
        # "auto" is reserved for a future draft-model speculator: off today
        spec_on = spec_mode == "ngram"
        if spec_on and not hasattr(self.module, "forward_paged_verify"):
            raise ValueError(
                "serving.speculative.mode='ngram' but the model has no "
                "forward_paged_verify (the fused multi-position verify "
                "step); serve a zoo causal LM or set mode='off'")
        if spec_on and temperature > 0.0:
            # acceptance is greedy-argmax-exact; lossless sampled
            # speculation needs rejection sampling over the verify logits
            warn_once("serving.speculative is greedy-only: temperature > 0 "
                      "disables speculation for this call")
            spec_on = False
        spec_k = int(spec.k)
        if spec_on and spec_k < 1:
            raise ValueError("serving.speculative.k must be >= 1")
        proposer = None
        spec_wb = 0
        if spec_on:
            from deepspeed_tpu.inference.spec import NgramProposer
            proposer = NgramProposer(min_match=int(spec.min_match),
                                     max_match=int(spec.max_match))
            # verify window compile bucket: next power of two of k+1, so
            # sweeping k costs <= log2 programs (pinned by the
            # serving_speculative compile-budget contract)
            spec_wb = 1 << int(spec_k).bit_length()

        pools, pools_reused = self._paged_pools(num_blocks, bs)
        alloc = self._paged_allocator(num_blocks, bs, caching, pools_reused)
        # tiered KV cache: attach the persistent host-RAM tier (content-
        # addressed, so it survives pool/allocator rebuilds) and decide
        # whether this session demotes (spill) or only serves host hits
        host_pool = self._kv_host_pool_for(num_blocks, bs, caching)
        alloc.attach_host_pool(host_pool)
        kv_spill = (host_pool is not None
                    and str(srv.kv_host.spill) != "off")
        if self._serving_tel is not None:
            # KV gauges (blocks free/used, fragmentation) are GLOBAL per
            # slice — the allocator is replicated and block ids are shard-
            # invariant; this gauge annotates them so a head-sharded pool
            # is not misread as 1/tp of the memory
            self._serving_tel.tp.set(float(self.mesh.shape.get("tp", 1)))
        ev = self._events
        sched = ContinuousBatchingScheduler(alloc, W, n_max,
                                            telemetry=self._serving_tel,
                                            prefix_caching=caching,
                                            chunk_tokens=chunk_tokens,
                                            events=ev,
                                            rid_base=self._serve_rid_base,
                                            spec_k=spec_k if spec_on else 0,
                                            spec_proposer=proposer,
                                            policy=policy)
        session = _ServeSession(
            self, sched, pools, self._ensure_paged_jits(),
            max_new=max_new, temperature=temperature, top_k=top_k,
            rng=jax.random.key(seed), eos_token_id=eos_token_id,
            spec_wb=spec_wb, W=W, n_max=n_max, bs=bs,
            num_blocks=num_blocks, chunk_tokens=chunk_tokens, ev=ev,
            on_tokens=on_tokens, on_finish=on_finish,
            retain_finished=retain_finished, kv_spill=kv_spill)
        self._active_session = session
        return session

    @staticmethod
    def _sample_jit(logits, temperature, top_k, rng):
        """Sampling with traced temperature/top_k (so the decode step compiles
        once): logits below the top_k-th value are masked when top_k > 0."""
        logits = logits / jnp.maximum(temperature, 1e-6)
        idx = jnp.clip(top_k - 1, 0, logits.shape[-1] - 1)
        thresh = jnp.sort(logits, axis=-1)[..., ::-1][..., idx][..., None]
        logits = jnp.where((top_k > 0) & (logits < thresh), -jnp.inf, logits)
        return jax.random.categorical(rng, logits, axis=-1)

    @property
    def config(self):
        return self._config


class _ServeSession:
    """One paged serving session: scheduler + pools + jit context behind a
    step API. ``generate_batch`` (closed loop) and ``AsyncServingEngine``
    (open loop) both execute scheduler actions THROUGH this class, so an
    action compiles and dispatches identically no matter which front-end
    drove it — the ``serving_async_steady`` contract's mechanism, not just
    its test. Single-threaded by contract: every method must run on the
    thread that owns the engine's jit dispatch (the caller's thread for
    generate_batch, the serving loop thread for the async engine), under
    the engine's ``_mesh_scope``."""

    def __init__(self, engine, sched, pools, jits, *, max_new, temperature,
                 top_k, rng, eos_token_id, spec_wb, W, n_max, bs, num_blocks,
                 chunk_tokens, ev, on_tokens=None, on_finish=None,
                 retain_finished=True, kv_spill=False):
        self.engine = engine
        self.sched = sched
        self.pools = pools
        (self._prefill_jit, self._decode_jit, self._chunk_jit,
         self._cow_jit, self._verify_jit, self._spill_jit,
         self._fetch_jit) = jits
        # fault containment (serving.fault): the action a fault can be
        # attributed to, the finer-grained dispatch site for the
        # step_faults{kind=} label (an action may run cow/fetch sub-steps
        # before its own dispatch), and the retry/backoff bounds the
        # always-on loop's containment applies (see contain_fault)
        self.last_action = None
        self.fault_site = None
        fault = engine._config.serving.fault
        self.fault_max_retries = int(fault.max_request_retries)
        self.fault_backoff_steps = int(fault.retry_backoff_steps)
        self._kv_spill = kv_spill
        # tiered KV cache: the demotion hook is session-scoped — it reads
        # the LIVE (donated-through) pools, so it must never outlive this
        # session (close() clears it)
        if kv_spill:
            sched.allocator.set_spill(self._spill_block)
        else:
            sched.allocator.set_spill(None)
        self.max_new = int(max_new)
        self.temperature = temperature
        self.top_k = top_k
        self.rng = rng
        self.eos_token_id = eos_token_id
        self.spec_wb = spec_wb
        self.W = W
        self.n_max = n_max
        self.bs = bs
        self.num_blocks = num_blocks
        self.chunk_tokens = chunk_tokens
        self.ev = ev
        self.on_tokens = on_tokens
        self.on_finish = on_finish
        # closed loop reads sched.finished for its outputs; the ALWAYS-ON
        # loop must not retain every Request forever (unbounded growth) —
        # it consumes results through on_finish and sets this False
        self.retain_finished = retain_finished
        self._finished_seen = 0
        self._closed = False

    # ---- request front-end ---- #

    _UNSET = object()

    def add(self, prompt, max_new=None, eos=_UNSET, priority: int = 0,
            ttft_budget=None, t_submit=None, deadline_ms=None,
            deadline_steps=None, trace=None, parent=None):
        """Enqueue one request (any time — mid-decode arrivals are the
        point). ``max_new``/``eos`` default to the session-wide values."""
        if self._closed:
            raise RuntimeError("serving session is closed")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        mn = self.max_new if max_new is None else int(max_new)
        if mn < 1:
            # the session-level guard only covers the default; a per-
            # request 0 would still emit the prefill-sampled token
            raise ValueError(f"max_new_tokens must be >= 1, got {mn}")
        cfg = self.engine.module.config
        if prompt.size + mn > cfg.max_seq:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({mn}) exceeds "
                f"model max_seq {cfg.max_seq}")
        return self.sched.add_request(
            prompt, mn, self.eos_token_id if eos is self._UNSET else eos,
            priority=priority, ttft_budget=ttft_budget, t_submit=t_submit,
            deadline_ms=deadline_ms, deadline_steps=deadline_steps,
            trace=trace, parent=parent)

    def cancel(self, req) -> bool:
        """Cancel between engine steps; fires ``on_finish`` for the
        retired request."""
        ok = self.sched.cancel_request(req)
        self._flush_finished()
        return ok

    # ---- stepping ---- #

    def step(self) -> bool:
        """Execute ONE scheduler action (admission prefill, prefill
        chunk, fused decode or fused verify). Returns False when nothing
        is runnable — queue and running batch both empty."""
        if self._closed:
            raise RuntimeError("serving session is closed")
        self.last_action = None      # a fault in next_action itself must
        self.fault_site = None       # not be attributed to the PREVIOUS
        # step's action or dispatch site
        action = self.sched.next_action()
        if action is None:
            self._flush_finished()   # admission-time error retirements
            return False
        self.last_action = action
        self._exec(action)
        self._flush_finished()
        return True

    def _emit_tokens(self, req, tokens) -> None:
        if self.on_tokens is not None:
            self.on_tokens(req, [int(t) for t in tokens])

    def _flush_finished(self) -> None:
        fin = self.sched.finished
        while self._finished_seen < len(fin):
            r = fin[self._finished_seen]
            self._finished_seen += 1
            if self.on_finish is not None:
                self.on_finish(r)
        if not self.retain_finished and self._finished_seen:
            del fin[:self._finished_seen]
            self._finished_seen = 0

    # ---- serving fault containment (serving.fault) ---- #

    def pools_alive(self) -> bool:
        """Whether the session's pool buffers are still valid. Every
        fused step DONATES the pools, so an exception between the
        dispatch and the adoption of its outputs leaves ``self.pools``
        naming consumed buffers — the definitive engine-fatal signature
        (a pre-dispatch failure leaves them intact: per-request)."""
        return not any(getattr(a, "is_deleted", lambda: False)()
                       for a in jax.tree.leaves(self.pools))

    def contain_fault(self, exc: BaseException) -> str:
        """Classify and (when possible) contain an exception that escaped
        :meth:`step`. Returns ``"request"`` when the fault was contained
        per-request — the faulting action's request(s) re-queued with
        logical-step backoff, or quarantined with ``req.error`` after
        ``serving.fault.max_request_retries`` — ``"fatal"`` when the
        donated pools died mid-step and the caller must run
        :meth:`restart_engine` (or give up), or ``"unattributed"`` when
        nothing could be re-queued (the exception fired before an action
        was chosen, e.g. a broken scheduling policy): per-request retry
        budgets cannot bound that class, so the caller must escalate
        rather than spin on a deterministic recurrence. Either way the
        fault is recorded (``serve.fault`` event,
        ``serving/step_faults{kind=}``). The closed loop never calls
        this: ``generate_batch`` propagates, exactly like its
        :class:`PoolExhausted` contract."""
        kind, payload = self.last_action if self.last_action is not None \
            else ("unknown", None)
        # the LABEL is the finer dispatch site (a cow/fetch sub-step of a
        # prefill action attributes to cow/fetch); request attribution
        # below still follows the enclosing action's payload
        site = self.fault_site if self.fault_site is not None else kind
        msg = f"{type(exc).__name__}: {exc}"
        if self.ev is not None:
            # payload key "action", not "kind": the recorder's own kind
            # argument is the event type
            self.ev.emit("serve.fault", action=site, error=msg)
        if self.sched.telemetry is not None:
            self.sched.telemetry.step_faults.labels(kind=site).inc()
        logger.warning(f"serving step fault ({site}): {msg}")
        if not self.pools_alive():
            return "fatal"
        if kind in ("prefill", "prefill_chunk"):
            reqs = [payload]
        elif kind in ("decode", "verify"):
            # a fused step has no single culprit: every row re-queues
            # (recompute keeps each greedy-identical), so whichever
            # request is poison accrues retries until quarantine while
            # the innocent ones recompute (their retry counts reset as
            # soon as they emit a token again)
            reqs = [r for r in payload if r.state == "running"]
        else:
            reqs = []
        if not reqs:
            return "unattributed"
        # REVERSED: each requeue appendlefts, so walking the batch
        # back-to-front leaves the earliest-admitted request at the queue
        # head — the same fairness preemption and reset_pool preserve
        for r in reversed(reqs):
            self._retry_or_quarantine(r, msg)
        self._flush_finished()
        return "request"

    def _retry_or_quarantine(self, req, msg: str) -> None:
        req.retry_count += 1
        if req.retry_count > self.fault_max_retries:
            self.sched.fail_request(
                req, f"quarantined after {self.fault_max_retries} "
                     f"step-fault retries: {msg}")
            return
        backoff = self.fault_backoff_steps * (1 << (req.retry_count - 1))
        self.sched.requeue_for_retry(req, backoff, error=msg)

    def restart_engine(self) -> None:
        """Crash-safe engine recovery after an engine-fatal step fault:
        rebuild the pool workspace, the block allocator and the fused-step
        jits (each entry recompiles AT MOST once per restart — the
        ``serving_faulted_steady`` contract), then re-admit every
        in-flight request from prompt + generated tokens through
        :meth:`ContinuousBatchingScheduler.reset_pool` — the exact
        recovery recompute-preemption already proves greedy-identical.
        The content-addressed host KV tier survives (its bytes live in
        host RAM); the device prefix cache starts cold."""
        engine, sched = self.engine, self.sched
        sched.allocator.set_spill(None)      # hook captured the dead pools
        host_pool = sched.allocator.host_pool
        engine._paged_workspace = None
        engine._paged_alloc = None
        engine._paged_jits = None
        pools, _ = engine._paged_pools(self.num_blocks, self.bs)
        alloc = engine._paged_allocator(self.num_blocks, self.bs,
                                        sched.prefix_caching, False)
        alloc.attach_host_pool(host_pool)
        sched.reset_pool(alloc)
        (self._prefill_jit, self._decode_jit, self._chunk_jit,
         self._cow_jit, self._verify_jit, self._spill_jit,
         self._fetch_jit) = engine._ensure_paged_jits()
        self.pools = pools
        if self._kv_spill:
            alloc.set_spill(self._spill_block)

    # ---- tiered KV cache: demote (D2H) / re-materialize (H2D) ---- #

    def _spill_block(self, block: int, key: bytes) -> bool:
        """Allocator demotion hook: gather ``block``'s per-layer k/v
        slices (one jitted program, block index traced) and hand them to
        the host pool, which starts the async D2H copy — dispatched
        BEFORE the reclaiming owner's writes, so stream order reads the
        pre-overwrite content, and overlapping the running decode loop
        the way weight streaming overlaps layer copies. Never raises:
        any failure degrades to today's destroy-on-reclaim (the host
        pool counts and warns)."""
        sched, ev = self.sched, self.ev
        hp = sched.allocator.host_pool
        if hp is None:
            return False
        prev_site = self.fault_site
        self.fault_site = "spill"    # degraded internally below, but a
        # non-Exception escape (SimulatedCrash) should still read "spill"
        try:
            _step_fault("spill", "pre")
            t0 = time.monotonic_ns() if ev is not None else 0
            sl = self._spill_jit(self.pools, jnp.int32(block))
            ok = hp.put(key, sl["k"], sl["v"])
        except Exception as e:          # SimulatedCrash (BaseException)
            # and record_* invariants still propagate; everything else
            # must degrade — a spill is best-effort cache retention
            hp._count_error("spill (gather)", e)
            self.fault_site = prev_site
            return False
        self.fault_site = prev_site
        if ok:
            if ev is not None:
                # dur DELIBERATELY brackets only the gather dispatch +
                # async-copy kick-off: the D2H itself overlaps the next
                # fused steps (that overlap is the whole point), so a
                # sync here would serialize what the tier exists to hide
                dur = time.monotonic_ns() - t0  # dslint: disable=DS005
                ev.emit("kv.spill", t_ns=t0, dur_ns=dur,
                        blocks=1,
                        bytes=int(sl["k"].nbytes) + int(sl["v"].nbytes),
                        block=block)
                if sched.telemetry is not None:
                    sched.telemetry.phase("spill", dur / 1e6)
            if sched.telemetry is not None:
                sched.telemetry.kv_spills.inc()
        return ok

    def demote_prompt(self, tokens) -> int:
        """Force-demote ``tokens``'s committed FULL blocks into the host
        tier (:meth:`BlockAllocator.demote_chain`) — the prefill→decode
        KV handoff: a prefill replica calls this once a warm-up request
        retires, publishing the prompt's KV in the SHARED host pool where
        the decode replica's tiered admission finds it. Single-threaded
        by the session contract (the always-on loop routes it through its
        command intake); returns the number of blocks demoted (0 when
        the session has no spill hook / host tier)."""
        if self._closed:
            raise RuntimeError("serving session is closed")
        if not self._kv_spill:
            return 0
        return self.sched.allocator.demote_chain(tokens)

    def _run_fetches(self, req, pools):
        """Land the admission's host-tier hits H2D: device_put each
        demoted ``[L, bs, KV, Hd]`` slice (head-sharded under tp, like
        the pools) and scatter it into the request's freshly allocated
        block via the jitted per-block program. Runs BEFORE any of the
        request's prefill compute reads the blocks. Each promoted block
        registers under its chain key only NOW — content actually on
        device — and its host entry is dropped (a key lives in one
        tier); the COW split's private copy (key None) stays
        unregistered and keeps its host entry cached."""
        fetches = req.fetch_pending
        req.fetch_pending = []
        if not fetches:
            return pools
        prev_site = self.fault_site
        self.fault_site = "fetch"    # a fault in here labels as "fetch";
        # restored only on the success path so containment sees the site
        _step_fault("fetch", "pre")
        engine, sched, ev = self.engine, self.sched, self.ev
        alloc = sched.allocator
        sh = engine._kv_slice_sharding()
        t0 = time.monotonic_ns() if ev is not None else 0
        nbytes = 0
        ntokens = 0
        for dst, key, k_np, v_np, tokens in fetches:
            ks = jax.device_put(jnp.asarray(k_np), sh)
            vs = jax.device_put(jnp.asarray(v_np), sh)
            out = self._fetch_jit(pools, jnp.int32(dst), ks, vs)
            _step_fault("fetch", "post")
            pools = out
            nbytes += int(k_np.nbytes) + int(v_np.nbytes)
            ntokens += int(tokens)
            if key is not None:
                alloc.register(dst, key)
                if alloc.host_pool is not None:
                    alloc.host_pool.remove(key)
        if sched.telemetry is not None:
            # observed at LANDING, not admission: a preempt-before-fetch
            # re-admission must not double-count an H2D that never ran
            sched.telemetry.kv_fetch_hits.inc(len(fetches))
            if ntokens:
                sched.telemetry.kv_fetch_tokens.inc(ntokens)
        if ev is not None:
            # the scatters are async dispatches: sync so the slice covers
            # device work, not µs of dispatch (the DS005 rule)
            jax.block_until_ready(pools)
            dur = time.monotonic_ns() - t0
            ev.emit("kv.fetch", rid=req.rid, t_ns=t0, dur_ns=dur,
                    blocks=len(fetches), bytes=nbytes)
            if sched.telemetry is not None:
                sched.telemetry.phase("fetch", dur / 1e6, rid=req.rid)
        self.fault_site = prev_site
        return pools

    def _exec(self, action) -> None:
        engine, sched, ev = self.engine, self.sched, self.ev
        cfg = engine.module.config
        bs, W, n_max, spec_wb = self.bs, self.W, self.n_max, self.spec_wb
        temperature, top_k = self.temperature, self.top_k
        pools = self.pools
        kind, payload = action
        # serving fault injection (utils/fault_injection.fail_step): ONE
        # None check per consult; "pre" fires before any device dispatch
        # (per-request containable — the pools are intact), "post" fires
        # between the donating dispatch and the adoption of its outputs
        # (the local `pools` still names the consumed buffers, so the
        # exception leaves the session exactly as a mid-step device death
        # would: engine-fatal). The top consult ticks the injector's
        # deterministic step counter. fault_site tracks the finer dispatch
        # site (cow/fetch sub-steps update it) for step_faults{kind=}.
        self.fault_site = kind
        _step_fault(kind, "pre", tick=True)
        try:
            if kind == "wait":
                # retry-backoff idle tick: no device work, clock advanced
                return
            if kind == "prefill":
                req = payload
                pools = self._run_fetches(req, pools)
                prefix = req.prefix()
                L = prefix.size
                Tb = engine._bucket(L, cfg.max_seq)
                toks = np.zeros((1, Tb), np.int32)
                toks[0, :L] = prefix
                table = np.asarray(req.blocks, np.int32)
                slots = engine._flat_slots(table, 0, L, Tb, bs)
                t0 = time.monotonic_ns() if ev is not None else 0
                out = self._prefill_jit(
                    engine.params, jnp.asarray(toks), pools,
                    jnp.asarray(slots, jnp.int32), jnp.int32(L - 1))
                _step_fault("prefill", "post")
                logits, pools = out
                self.rng, sub = jax.random.split(self.rng)
                # fetch the sampled token BEFORE emitting: _sample_host
                # is device-only (argmax/categorical), so the np.asarray
                # here is the sync — emitting first would clock async
                # dispatch while the device work lands later (DS005)
                tok = np.asarray(engine._sample_host(
                    logits.astype(jnp.float32), temperature, top_k, sub))
                if ev is not None:
                    dur = time.monotonic_ns() - t0
                    ev.emit("req.prefill", rid=req.rid, t_ns=t0,
                            dur_ns=dur, tokens=L)
                    if sched.telemetry is not None:
                        sched.telemetry.phase("prefill", dur / 1e6,
                                              rid=req.rid)
                sched.record_prefill(req, int(tok[0]))
                self._emit_tokens(req, [int(tok[0])])
            elif kind == "prefill_chunk":
                req = payload
                pools = self._run_fetches(req, pools)
                if req.cow_pending is not None:
                    # copy-on-write split: the request restarts mid-block
                    # inside a SHARED cached block — give it a private
                    # device copy before any of its writes land
                    src, dst = req.cow_pending
                    self.fault_site = "cow"
                    _step_fault("cow", "pre")
                    t0 = time.monotonic_ns() if ev is not None else 0
                    out = self._cow_jit(pools, jnp.int32(src),
                                        jnp.int32(dst))
                    _step_fault("cow", "post")
                    pools = out
                    self.fault_site = kind
                    if ev is not None:
                        # dispatch is async: wait for the copy so the
                        # span covers device work, not µs of dispatch
                        jax.block_until_ready(pools)
                        dur = time.monotonic_ns() - t0
                        ev.emit("req.cow_copy", rid=req.rid, t_ns=t0,
                                dur_ns=dur, src=src, dst=dst)
                        if sched.telemetry is not None:
                            sched.telemetry.phase("cow", dur / 1e6,
                                                  rid=req.rid)
                    req.cow_pending = None
                start = req.pos
                remaining = req.prefill_target - start
                step = min(self.chunk_tokens, remaining) \
                    if self.chunk_tokens else remaining
                Tb = engine._bucket(step, cfg.max_seq)
                prefix = req.prefix()
                toks = np.zeros((1, Tb), np.int32)
                toks[0, :step] = prefix[start:start + step]
                table = np.asarray(req.blocks, np.int32)
                slots = engine._flat_slots(table, start, step, Tb, bs)
                # the chunk attends over the gathered table, so its cost is
                # O(table width × block_size) per layer — bucket the width
                # to the next power of two of the request's OWN block count
                # (≤ log2(n_max) compiles) instead of paying n_max (=
                # max_seq worth of KV) for every short cache-hit tail
                nb = min(n_max, 1 << max(int(table.size) - 1, 0).bit_length())
                bt = np.zeros((1, nb), np.int32)
                bt[0, :table.size] = table
                t0 = time.monotonic_ns() if ev is not None else 0
                out = self._chunk_jit(
                    engine.params, jnp.asarray(toks), pools, jnp.asarray(bt),
                    jnp.asarray(slots, jnp.int32), jnp.int32(start),
                    jnp.int32(step - 1))
                _step_fault("prefill_chunk", "post")
                logits, pools = out
                if ev is not None:
                    # non-final chunks never fetch a result, so the
                    # dispatch alone would clock near-zero: sync first
                    # (tracing-only cost) so the slice is device time
                    jax.block_until_ready(logits)
                    dur = time.monotonic_ns() - t0
                    ev.emit("req.prefill_chunk", rid=req.rid, t_ns=t0,
                            dur_ns=dur, start=start, tokens=step)
                    if sched.telemetry is not None:
                        sched.telemetry.phase("prefill_chunk", dur / 1e6,
                                              rid=req.rid)
                if start + step == req.prefill_target:
                    self.rng, sub = jax.random.split(self.rng)
                    tok = engine._sample_host(logits.astype(jnp.float32),
                                              temperature, top_k, sub)
                    sched.record_prefill_chunk(req, step,
                                               int(np.asarray(tok)[0]))
                    self._emit_tokens(req, [int(np.asarray(tok)[0])])
                else:
                    sched.record_prefill_chunk(req, step)
            elif kind == "verify":
                # speculative multi-token step: the fused decode math
                # over each request's window (pending token + proposed
                # candidates) at once, then greedy argmax acceptance —
                # the accepted candidate prefix plus the first-mismatch
                # token is exactly what token-by-token decode would emit
                reqs = payload
                bt = np.zeros((W, n_max), np.int32)       # zeros → dummy
                pos = np.zeros((W,), np.int32)
                toks = np.zeros((W, spec_wb), np.int32)
                slotm = np.zeros((W, spec_wb), np.int32)
                zt = np.zeros((1,), np.int32)
                for i in range(W):
                    if i >= len(reqs):
                        # inactive rows: junk routed to the dummy block
                        slotm[i] = engine._flat_slots(zt, 0, 0, spec_wb, bs)
                        continue
                    r = reqs[i]
                    nv = 1 + len(r.spec_tokens)
                    toks[i, 0] = r.last_token
                    toks[i, 1:nv] = r.spec_tokens
                    table = np.asarray(r.blocks, np.int32)
                    bt[i, :table.size] = table
                    pos[i] = r.pos
                    slotm[i] = engine._flat_slots(table, r.pos, nv,
                                                  spec_wb, bs)
                t0 = time.monotonic_ns() if ev is not None else 0
                out = self._verify_jit(
                    engine.params, jnp.asarray(toks), pools,
                    jnp.asarray(bt), jnp.asarray(slotm), jnp.asarray(pos))
                _step_fault("verify", "post")
                logits, pools = out
                # same argmax the decode path's _sample_host runs, at
                # every window position; the fetch is the sync point,
                # so the spec_verify slices below clock device time
                greedy = np.asarray(jnp.argmax(
                    logits.astype(jnp.float32), axis=-1))
                dur = time.monotonic_ns() - t0 if ev is not None else 0
                if ev is not None and sched.telemetry is not None:
                    # one ledger sample per fused verify step (the
                    # per-rid spec_verify events below carry identity)
                    sched.telemetry.phase("verify", dur / 1e6)
                for i, r in enumerate(reqs):
                    cands = r.spec_tokens
                    n_acc = 0
                    while n_acc < len(cands) \
                            and int(greedy[i, n_acc]) == cands[n_acc]:
                        n_acc += 1
                    emitted = list(cands[:n_acc]) + [int(greedy[i, n_acc])]
                    # truncate at eos HERE so the event's accepted=
                    # matches what record_verify will commit (its own
                    # truncation stays as the invariant check)
                    eos_r = r.eos
                    if eos_r is not None and int(eos_r) in emitted:
                        emitted = emitted[:emitted.index(int(eos_r)) + 1]
                    if ev is not None:
                        # emitted BEFORE record_verify so a retirement
                        # this step triggers lands after its slice
                        ev.emit("req.spec_verify", rid=r.rid, t_ns=t0,
                                dur_ns=dur, window=1 + len(cands),
                                accepted=len(emitted) - 1)
                    sched.record_verify(r, emitted)
                    self._emit_tokens(r, emitted)
            else:
                reqs = payload
                bt = np.zeros((W, n_max), np.int32)       # zeros → dummy
                pos = np.zeros((W,), np.int32)
                toks = np.zeros((W, 1), np.int32)
                for i, r in enumerate(reqs):
                    bt[i, :len(r.blocks)] = r.blocks
                    pos[i] = r.pos
                    toks[i, 0] = r.last_token
                t0 = time.monotonic_ns() if ev is not None else 0
                out = self._decode_jit(
                    engine.params, jnp.asarray(toks), pools,
                    jnp.asarray(bt), jnp.asarray(pos))
                _step_fault("decode", "post")
                logits, pools = out
                self.rng, sub = jax.random.split(self.rng)
                tok = np.asarray(engine._sample_host(
                    logits.astype(jnp.float32), temperature, top_k, sub))
                if ev is not None:
                    # emitted BEFORE record_decode so a retirement this
                    # tick triggers lands after its final decode slice
                    dur = time.monotonic_ns() - t0
                    ev.emit("decode.tick", t_ns=t0, dur_ns=dur,
                            rids=[r.rid for r in reqs], n=len(reqs))
                    if sched.telemetry is not None:
                        sched.telemetry.phase("decode", dur / 1e6)
                for i, r in enumerate(reqs):
                    sched.record_decode(r, int(tok[i]))
                    self._emit_tokens(r, [int(tok[i])])
        finally:
            # rebind even when a record_* invariant raised: the donated
            # input buffers are gone either way, and close()/end() must
            # see the live pools
            self.pools = pools

    # ---- lifecycle ---- #

    def close(self) -> None:
        """Always-run bookkeeping (the closed loop runs this in its
        ``finally``): rid uniqueness across serves — even an aborted serve
        must not let the next one reuse rids in the shared flight-recorder
        ring — the serve-stats stash, and releasing the engine's
        active-session slot. Idempotent."""
        if self._closed:
            return
        self._closed = True
        engine = self.engine
        # the demotion hook captures THIS session's live pools: a stale
        # hook on the persistent allocator would gather freed buffers
        self.sched.allocator.set_spill(None)
        engine._serve_rid_base = self.sched._next_rid
        # step accounting for the serve that just ran (plain host
        # counters, kept even when the metrics registry is off):
        # accepted_tokens_per_step > 1 is the speculation win
        engine._last_serve_stats = dict(self.sched.stats)
        if engine._active_session is self:
            engine._active_session = None

    def end(self) -> None:
        """Success-path epilogue: serving memory gauges and the hand-back
        of the (donated-through) pools into the engine's persistent
        workspace, so the next session — or ``generate_batch`` call —
        reuses them and, with prefix caching, re-hits this session's
        registered blocks."""
        engine = self.engine
        if engine._telemetry is not None:
            # HBM live/peak + host RSS after the serve (the pools and the
            # decode workspace are the serving memory story)
            from deepspeed_tpu.monitor.health import sample_memory_gauges
            sample_memory_gauges(engine._tel_reg)
        engine._paged_workspace = (self.num_blocks, self.bs, self.pools)
