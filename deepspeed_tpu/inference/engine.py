"""Inference engine.

Reference parity: ``deepspeed/inference/engine.py:35`` — ``InferenceEngine``
wraps a model for serving: dtype conversion, tensor-parallel sharding of the
weights, checkpoint loading, and a ``generate`` loop. The reference's three
injection modes (user policy / kernel injection / AutoTP,
``inference/engine.py:120-144``) map here to:

- models from ``deepspeed_tpu.models``: TP sharding comes from the model's
  own ``tp_specs()`` (policy equivalent);
- arbitrary param pytrees: ``AutoShard`` heuristics
  (``deepspeed_tpu.inference.auto_tp``) pick specs by name/shape, the AutoTP
  analogue;
- kernel injection = swapping the attention op for the Pallas decode kernel
  with KV cache (``deepspeed_tpu.ops``), enabled when available.

CUDA-graph capture/replay (reference ``:435-463``) is subsumed by ``jit``:
the decode step is one compiled program with a donated KV cache.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu.comm as dist
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.utils.logging import log_dist, logger


class InferenceEngine:

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None, params=None):
        self.module = model
        self._config = config or DeepSpeedInferenceConfig()
        self.dtype = self._config.dtype.jnp if hasattr(self._config.dtype, "jnp") else jnp.bfloat16

        tp_size = self._config.tensor_parallel.tp_size
        if not dist.has_mesh():
            axes = {"tp": tp_size, "dp": -1} if tp_size > 1 else {"dp": -1}
            dist.init_mesh(axes)
        self.mesh = dist.get_mesh()

        if params is None and hasattr(model, "init_params"):
            params = model.init_params(jax.random.key(0))
        if params is None:
            raise ValueError("InferenceEngine needs params (or a model with init_params)")

        tp_specs = None
        if hasattr(model, "tp_specs"):
            tp_specs = model.tp_specs() if callable(model.tp_specs) else model.tp_specs
        elif tp_size > 1:
            from deepspeed_tpu.inference.auto_tp import auto_tp_specs
            tp_specs = auto_tp_specs(params)

        from jax.sharding import NamedSharding, PartitionSpec as P
        if tp_specs is not None:
            from deepspeed_tpu.runtime.zero.partition import ZeroShardingRules
            rules = ZeroShardingRules(self.mesh)  # stage 0: replicate except TP dims
            shardings = rules.param_shardings(params, tp_specs)
        else:
            shardings = jax.tree.map(lambda _: NamedSharding(self.mesh, P()), params)
        self.params = jax.tree.map(lambda a, s: jax.device_put(jnp.asarray(a, self.dtype), s), params, shardings)

        self._fwd_jit = None
        self._prefill_jit = None
        self._decode_jit = None
        log_dist(f"InferenceEngine ready: dtype={self.dtype.__name__}, tp={tp_size}, "
                 f"mesh={dict(self.mesh.shape)}", ranks=[0])

    # ------------------------------------------------------------------ #

    def forward(self, input_ids, attention_mask=None):
        """Full-sequence forward → logits."""
        if self._fwd_jit is None:
            fwd = self.module.forward if hasattr(self.module, "forward") else self.module
            self._fwd_jit = jax.jit(lambda p, t, m: fwd(p, t, m))
        input_ids = jnp.asarray(input_ids, jnp.int32)
        return self._fwd_jit(self.params, input_ids, attention_mask)

    __call__ = forward

    def generate(self, input_ids, max_new_tokens: Optional[int] = None, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0, eos_token_id: Optional[int] = None):
        """Autoregressive generation (greedy or sampled).

        This baseline path recomputes the full prefix per step (correct for
        every model in the zoo); the Pallas KV-cache decode path replaces it
        when kernel injection is enabled. ``max_out_tokens`` semantics follow
        the reference (inference/engine.py:523 token-length check).
        """
        input_ids = jnp.asarray(input_ids, jnp.int32)
        if input_ids.ndim == 1:
            input_ids = input_ids[None, :]
        max_new = max_new_tokens if max_new_tokens is not None else self._config.max_out_tokens
        max_len = input_ids.shape[1] + max_new
        cfg = getattr(self.module, "config", None)
        if cfg is not None and hasattr(cfg, "max_seq") and max_len > cfg.max_seq:
            raise ValueError(f"Input+generated length {max_len} exceeds model max_seq {cfg.max_seq}; "
                             f"reduce max_new_tokens (reference max_out_tokens check)")

        rng = jax.random.key(seed)
        if hasattr(self.module, "forward_cached") and hasattr(self.module, "init_cache"):
            return self._generate_cached(input_ids, max_new, temperature, top_k, rng, eos_token_id)

        # fallback for models without a cached forward: full-prefix recompute
        tokens = input_ids
        for _ in range(max_new):
            logits = self.forward(tokens)[:, -1, :].astype(jnp.float32)
            nxt = self._sample_host(logits, temperature, top_k, rng)
            rng, _ = jax.random.split(rng)
            tokens = jnp.concatenate([tokens, nxt[:, None].astype(jnp.int32)], axis=1)
            if eos_token_id is not None and bool((nxt == eos_token_id).all()):
                break
        return tokens

    @staticmethod
    def _sample_host(logits, temperature, top_k, rng):
        if temperature > 0.0:
            logits = logits / temperature
            if top_k > 0:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            return jax.random.categorical(rng, logits, axis=-1)
        return jnp.argmax(logits, axis=-1)

    # ------------------------------------------------------------------ #
    # KV-cache generation: prefill + fixed-shape decode, no per-token
    # recompilation (reference workspace/KV design: inference_context.h:49,
    # softmax_context pt_binding.cpp:1668-1793)

    def _generate_cached(self, input_ids, max_new, temperature, top_k, rng, eos_token_id):
        from jax.sharding import NamedSharding, PartitionSpec as P

        B, prompt_len = input_ids.shape
        max_len = prompt_len + max_new
        cache = self.module.init_cache(B, max_len, dtype=self.dtype)
        # KV heads ride the tp axis like the attention weights that feed them
        kv_spec = (P(None, None, None, "tp", None)
                   if self.mesh.shape.get("tp", 1) > 1 else P())
        cache = jax.tree.map(
            lambda a: jax.device_put(a, NamedSharding(self.mesh, kv_spec)), cache)

        if self._prefill_jit is None:
            def prefill(params, toks, cache):
                logits, cache = self.module.forward_cached(params, toks, cache, jnp.int32(0))
                return logits[:, -1, :].astype(jnp.float32), cache

            def decode(params, tok, cache, pos, rng, temperature, top_k):
                logits, cache = self.module.forward_cached(params, tok, cache, pos)
                logits = logits[:, -1, :].astype(jnp.float32)
                nxt = jax.lax.cond(
                    temperature > 0.0,
                    lambda: self._sample_jit(logits, temperature, top_k, rng),
                    lambda: jnp.argmax(logits, axis=-1))
                return nxt, cache

            self._prefill_jit = jax.jit(prefill, donate_argnums=(2,))
            self._decode_jit = jax.jit(decode, donate_argnums=(2,))

        logits0, cache = self._prefill_jit(self.params, input_ids, cache)
        rng, sub = jax.random.split(rng)
        nxt = self._sample_host(logits0, temperature, top_k, sub)

        out = [nxt]
        pos = prompt_len
        t = jnp.float32(temperature)
        k = jnp.int32(top_k)
        for _ in range(max_new - 1):
            if eos_token_id is not None and bool((nxt == eos_token_id).all()):
                break
            rng, sub = jax.random.split(rng)
            nxt, cache = self._decode_jit(self.params, nxt[:, None].astype(jnp.int32),
                                          cache, jnp.int32(pos), sub, t, k)
            out.append(nxt)
            pos += 1
        gen = jnp.stack(out, axis=1).astype(jnp.int32)
        return jnp.concatenate([input_ids, gen], axis=1)

    @staticmethod
    def _sample_jit(logits, temperature, top_k, rng):
        """Sampling with traced temperature/top_k (so the decode step compiles
        once): logits below the top_k-th value are masked when top_k > 0."""
        logits = logits / jnp.maximum(temperature, 1e-6)
        idx = jnp.clip(top_k - 1, 0, logits.shape[-1] - 1)
        thresh = jnp.sort(logits, axis=-1)[..., ::-1][..., idx][..., None]
        logits = jnp.where((top_k > 0) & (logits < thresh), -jnp.inf, logits)
        return jax.random.categorical(rng, logits, axis=-1)

    @property
    def config(self):
        return self._config
