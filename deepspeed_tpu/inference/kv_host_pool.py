"""Host-memory KV tier behind the paged block allocator (tiered KV cache).

The prefix cache's cold LRU list preserves zero-ref blocks only until
allocation pressure reclaims them — reclaiming DESTROYS content a
returning user would re-hit, so at scale cache capacity (not compute)
bounds hit rate and TTFT. Host RAM is ~10x HBM: instead of destroying a
cold block, the allocator *demotes* it here — an async D2H copy of the
block's per-layer ``[L, bs, KV, Hd]`` k/v slices, keyed by the block's
blake2b hash chain (the same content address the device table uses) —
and a later admission whose prefix walks onto a demoted chain
*re-materializes* the block H2D into a freshly allocated device block
(``engine._ServeSession._run_fetches``) instead of recomputing its
prefill. The reference's ``swap_tensor`` / ZeRO-Infinity tiering applied
to serving.

Tier discipline (the conftest ``_no_kv_block_leaks`` fixture asserts it):

- a chain key lives in AT MOST ONE tier — a host entry is removed when
  its content is promoted back to a device block (fetch) and discarded
  when a device re-registration lands under the same key (recompute of
  identical content supersedes the host copy);
- the pool is bounded by ``max_blocks`` with its own LRU — a ``put``
  over capacity evicts the oldest entries (host eviction loses only a
  *cache* copy, never live state);
- entries are immutable once stored: content addressing means the bytes
  under a key can never change, so a host copy made at demotion time is
  valid forever (across serves, cache-off calls, even fresh device
  pools) until geometry/dtype changes rebuild the pool.

Async D2H: ``put`` stores the gathered device slices and kicks off
``copy_to_host_async`` — the demotion overlaps the running decode loop
the way the weight-streaming path overlaps layer H2D copies. A bounded
pending queue (``pending_limit``) materializes the oldest in-flight
copies to numpy so at most a few block-sized device buffers are ever
held by the tier; ``get`` materializes on demand.

Fault injection: every D2H/H2D byte movement consults
``utils.fault_injection.guarded_io`` under virtual paths
``kv_host_pool/spill`` and ``kv_host_pool/fetch``. An injected
``OSError`` degrades gracefully — a faulted ``put`` skips the spill
(today's destroy-on-reclaim), a faulted ``get`` drops the entry and
reports a miss (the admission recomputes the tail) — with a rate-limited
warning and the ``serving/kv_host_errors`` counter; the serving loop
never wedges. ``SimulatedCrash`` (process death) propagates by design.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils import fault_injection as _fi
from deepspeed_tpu.utils.logging import warn_once


class _HostBlock:
    """One demoted block: k/v slices ``[L, bs, KV, Hd]``. Until
    :meth:`materialize` runs they are the gather program's device arrays
    with an async host copy in flight; after, plain numpy."""

    __slots__ = ("k", "v", "nbytes", "pending")

    def __init__(self, k, v):
        self.k = k
        self.v = v
        self.nbytes = int(k.nbytes) + int(v.nbytes)
        self.pending = True

    def materialize(self) -> None:
        if not self.pending:
            return
        self.k = np.asarray(self.k)
        self.v = np.asarray(self.v)
        self.pending = False


class KvHostPool:
    """LRU-bounded host pool of demoted KV blocks, keyed by the
    allocator's content-address chain keys. Thread-safe (the always-on
    serving loop demotes from its own thread while telemetry snapshots
    read the gauges)."""

    def __init__(self, max_blocks: int, block_shape: Tuple[int, ...],
                 dtype: str, pending_limit: int = 4, telemetry=None):
        if max_blocks < 1:
            raise ValueError("max_blocks must be >= 1")
        if len(block_shape) != 4:
            raise ValueError("block_shape must be [L, bs, KV, Hd], got "
                             f"{block_shape}")
        self.max_blocks = int(max_blocks)
        self.block_shape = tuple(int(s) for s in block_shape)
        self.dtype = str(dtype)
        # in-flight D2H copies: at most pending_limit block-sized device
        # buffers held before the oldest is forced down to numpy
        self.pending_limit = max(int(pending_limit), 1)
        self.telemetry = telemetry
        self._lock = threading.RLock()
        self._entries: "OrderedDict[bytes, _HostBlock]" = OrderedDict()
        self._pending: deque = deque()           # keys awaiting materialize
        self._nbytes = 0
        # plain host counters, always on (tests and the fault-degradation
        # path read these even with the metrics registry disabled)
        self.stats = {"spills": 0, "fetches": 0, "evictions": 0, "errors": 0}

    # ------------------------------------------------------------------ #
    # capacity / introspection

    @property
    def num_blocks(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._nbytes

    def contains(self, key: bytes) -> bool:
        """Read-only probe (no LRU refresh) — the allocator's tiered
        match walk uses this so probing never reorders eviction."""
        with self._lock:
            return key in self._entries

    def keys(self) -> List[bytes]:
        with self._lock:
            return list(self._entries)

    def matches_geometry(self, block_shape, dtype) -> bool:
        """Entries are only valid for one ``[L, bs, KV, Hd]`` + dtype —
        the engine rebuilds the pool when serving geometry changes."""
        return (self.block_shape == tuple(int(s) for s in block_shape)
                and self.dtype == str(dtype))

    # ------------------------------------------------------------------ #
    # tier transitions

    def _count_error(self, what: str, err: Exception) -> None:
        self.stats["errors"] += 1
        if self.telemetry is not None:
            self.telemetry.kv_host_errors.inc()
        warn_once(f"KV host pool {what} failed ({err}); degrading to "
                  "destroy-on-reclaim for the affected block(s) — serving "
                  "continues, the content will be recomputed on re-hit")

    def put(self, key: bytes, k_dev, v_dev) -> bool:
        """Demote one block: store the gathered device slices and start
        their async host copies. Returns True when a NEW entry was
        stored (the caller counts it as a spill); a duplicate key only
        refreshes LRU recency. Over-capacity puts evict the LRU tail.
        Injected I/O faults degrade to a no-op (destroy-on-reclaim)."""
        if tuple(k_dev.shape) != self.block_shape:
            raise ValueError(
                f"demoted slice shape {tuple(k_dev.shape)} does not match "
                f"the pool geometry {self.block_shape}")
        nbytes = int(k_dev.nbytes) + int(v_dev.nbytes)
        try:
            _fi.guarded_io("kv_host_pool/spill", nbytes)
        except OSError as e:                      # SimulatedCrash propagates
            self._count_error("spill (D2H)", e)
            return False
        # overlap with the serving loop: the copies ride the transfer
        # stream while the next fused step computes
        for a in (k_dev, v_dev):
            fn = getattr(a, "copy_to_host_async", None)
            if fn is not None:
                fn()
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return False
            ent = _HostBlock(k_dev, v_dev)
            self._entries[key] = ent
            self._nbytes += ent.nbytes
            self._pending.append(key)
            while len(self._pending) > self.pending_limit:
                old = self._entries.get(self._pending.popleft())
                if old is not None:
                    old.materialize()
            while len(self._entries) > self.max_blocks:
                _, dropped = self._entries.popitem(last=False)   # LRU
                self._nbytes -= dropped.nbytes
                self.stats["evictions"] += 1
        return True

    def get(self, key: bytes) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Materialized ``(k, v)`` for a host hit (LRU refreshed), or
        None on a miss. The entry STAYS in the pool — the scheduler calls
        :meth:`remove` only once the fetch actually lands on device, so a
        preemption between admission and fetch loses nothing. Injected
        faults drop the entry and report a miss (the admission recomputes
        that block's tail instead of wedging)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return None
            try:
                _fi.guarded_io("kv_host_pool/fetch", ent.nbytes)
                ent.materialize()
            except Exception as e:
                # injected OSError AND real failures (MemoryError on the
                # host copy, backend transfer errors) all degrade to a
                # miss — the admission recomputes the block; only
                # SimulatedCrash (BaseException) may propagate
                del self._entries[key]
                self._nbytes -= ent.nbytes
                self._count_error("fetch (H2D)", e)
                return None
            self._entries.move_to_end(key)
            self.stats["fetches"] += 1
            return ent.k, ent.v

    def remove(self, key: bytes) -> bool:
        """Drop an entry (content promoted back to a device block — a
        chain key lives in at most one tier). No-op on a miss."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is None:
                return False
            self._nbytes -= ent.nbytes
            return True

    discard = remove   # device re-registration superseding the host copy

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._pending.clear()
            self._nbytes = 0

    def drain(self) -> None:
        """Force every in-flight D2H copy down to numpy (test/shutdown
        barrier; steady state bounds itself via ``pending_limit``)."""
        with self._lock:
            for ent in self._entries.values():
                ent.materialize()
            self._pending.clear()

    # ------------------------------------------------------------------ #
    # invariants (the conftest fixture's host-side assertions)

    def consistency_report(self) -> List[str]:
        """Internal-invariant violations (empty = consistent): the LRU is
        within its bound, byte accounting matches the entries, and every
        entry carries the pool geometry."""
        probs: List[str] = []
        with self._lock:
            if len(self._entries) > self.max_blocks:
                probs.append(
                    f"host pool holds {len(self._entries)} blocks over its "
                    f"bound of {self.max_blocks}")
            total = sum(e.nbytes for e in self._entries.values())
            if total != self._nbytes:
                probs.append(
                    f"host pool byte accounting drifted: tracked "
                    f"{self._nbytes}, actual {total}")
            for key, ent in self._entries.items():
                shape = tuple(getattr(ent.k, "shape", ()))
                if shape != self.block_shape:
                    probs.append(
                        f"host entry {key.hex()[:12]} has slice shape "
                        f"{shape}, pool geometry {self.block_shape}")
        return probs
