"""Inference package: the paged continuous-batching engine and its
serving surfaces. Heavy modules load lazily — importing the package must
not drag in jax before the caller configures platforms."""

_LAZY = {
    "InferenceEngine": ("deepspeed_tpu.inference.engine", "InferenceEngine"),
    "AsyncServingEngine": ("deepspeed_tpu.inference.serve",
                           "AsyncServingEngine"),
    "RequestHandle": ("deepspeed_tpu.inference.serve", "RequestHandle"),
    "SchedulingPolicy": ("deepspeed_tpu.inference.policy",
                         "SchedulingPolicy"),
    "get_policy": ("deepspeed_tpu.inference.policy", "get_policy"),
    "KvHostPool": ("deepspeed_tpu.inference.kv_host_pool", "KvHostPool"),
}


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    return getattr(importlib.import_module(mod_name), attr)


__all__ = sorted(_LAZY)
