"""Replica scale-out: the ``dp`` serving axis behind a deterministic
affinity router.

One tensor-parallel slice is a throughput ceiling; this module fans
serving out over N engine replicas — same weights (one shared pytree),
same scheduler/policy machinery, each replica a clean fault domain
behind its own always-on :class:`~deepspeed_tpu.inference.serve.
AsyncServingEngine` loop — and fronts them with a :class:`ReplicaRouter`
that presents the single-engine surface (``add_request`` handles, HTTP
``/healthz`` + ``/metrics``, ``drain``/``shutdown``) so ``dscli serve
--replicas N`` is a drop-in swap.

Routing is DETERMINISTIC given a request trace, exactly like the
scheduler: every decision is a pure function of (session key, the
router's own outstanding-request counts, each replica's restart count)
— no wall clock, no randomness — so a replayed trace yields an
identical ``decisions`` list and the unit suite pins assignments
byte-for-byte. The three rules, in order:

- **session affinity**: a request carrying a ``session`` key hashes
  (blake2b) onto a stable replica so multi-turn traffic re-hits the
  prefix cache it built on earlier turns;
- **least-loaded tiebreak** for fresh sessions: the healthy replica
  with the smallest (queue depth, burn, index) key — queue depth is the
  router's outstanding count, burn is the replica's engine-restart
  count (a replica burning its error budget loses ties);
- **failover**: an unhealthy preferred replica falls through to the
  least-loaded healthy one.

Role split (disaggregated prefill/decode): replicas tagged ``prefill``
warm long prompts — run the prefill, commit the blocks, then
force-demote them into the shared content-addressed
:class:`~deepspeed_tpu.inference.kv_host_pool.KvHostPool` — and the
``decode`` replica's admission probe re-materializes the chain H2D
(the PR-12 fetch path; the host tier IS the KV transport, no new wire
format). Token identity is unchanged: a fetched block is bit-identical
to what recompute would produce.

Fault drain: a replica tripping its crash-loop breaker fails its
in-flight requests; the router observes the failure, replays each on a
healthy sibling from the prompt (the recompute-preemption argument:
greedy decode re-derives the same tokens) and forwards only the suffix
the client has not seen — token-identical through the drain. Every
decision emits ``serve.route`` flight-recorder events; drains emit
``serve.drain`` with the replica label; per-replica ``router/*``
metrics feed the ``dscli top`` replicas pane.
"""

from __future__ import annotations

import hashlib
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.inference.serve import (
    CANCELLED, ERROR, FINISHED, REJECTED, TIMEOUT, RequestFailed)

#: replica role tags ("serving.replicas.roles")
ROLES = ("any", "prefill", "decode")


class RouterHandle:
    """One routed request's streaming surface — mirrors
    :class:`~deepspeed_tpu.inference.serve.RequestHandle` (``generated``
    / ``stream`` / ``result`` / ``cancel`` / terminal ``status``) so the
    HTTP front door and client code are replica-count-agnostic. The
    router may move the request between replicas underneath (prefill
    warm-up, breaker-drain failover); the handle's token stream stays
    contiguous — on a failover replay the already-forwarded prefix is
    skipped, never re-emitted."""

    def __init__(self, router: "ReplicaRouter", prompt: np.ndarray,
                 max_new: Optional[int], eos: Optional[int], priority: int,
                 ttft_budget: Optional[int], deadline_ms: Optional[float],
                 deadline_steps: Optional[int], session: Optional[str]):
        self._router = router
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.priority = priority
        self.ttft_budget = ttft_budget
        self.deadline_ms = deadline_ms
        self.deadline_steps = deadline_steps
        self.session = session
        self.rid: Optional[int] = None     # the CURRENT replica's rid
        self.replica: Optional[str] = None  # current serving replica name
        self.status = "pending"
        self.error: Optional[str] = None
        self.retry_after: Optional[float] = None
        self._tokens: List[int] = []
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._lock = threading.RLock()
        # handoff/failover state machine: "warm" (prefill replica runs
        # the prompt) -> "demote" (blocks shipping into the host tier)
        # -> "running" (decode replica streams) ; non-handoff requests
        # start at "running"
        self._stage = "running"
        self.trace: Optional[str] = None   # router-minted causal trace id
        #                                    (deterministic: decision seq)
        self._inner = None                 # current RequestHandle
        self._inner_idx: Optional[int] = None
        self._warm = None                  # prefill warm-up handle
        self._warm_idx: Optional[int] = None
        self._demote_evt: Optional[threading.Event] = None
        self._demote_t0: Optional[float] = None   # handoff phase clock
        self._target_idx: Optional[int] = None   # decode-side target
        self._skip = 0          # failover replay: tokens already forwarded
        self._failovers = 0
        self._cancelled = False

    # ---- router side ---- #

    def _push(self, burst: List[int]) -> None:
        self._tokens.extend(burst)
        if self.status in ("pending", "queued"):
            self.status = "running"
        self._q.put(("tokens", burst))

    def _finish(self, status: str, error: Optional[str] = None) -> None:
        if self._done.is_set():
            return
        self.status = status
        self.error = error
        self._done.set()
        self._q.put(("done", status, error))

    # ---- consumer side (any thread) ---- #

    @property
    def generated(self) -> List[int]:
        """Tokens streamed so far (a snapshot copy)."""
        return list(self._tokens)

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> None:
        """Cancel wherever the request currently lives (idempotent)."""
        with self._lock:
            self._cancelled = True
            if self._warm is not None and not self._warm.done():
                self._warm.cancel()
            if self._inner is not None:
                self._inner.cancel()
        self._router._advance(self)

    def stream(self, timeout: Optional[float] = None):
        """Iterate token bursts in emission order (the
        ``RequestHandle.stream`` contract: StopIteration on any terminal
        status except ``error`` -> :class:`RequestFailed`; ``timeout``
        is per burst -> ``queue.Empty``). Pumps the router between
        waits so prefill handoffs and failovers make progress even when
        nothing else drives it."""
        while True:
            waited = 0.0
            while True:
                self._router._advance(self)
                slice_s = 0.02 if timeout is None else \
                    min(0.02, max(timeout - waited, 0.001))
                try:
                    item = self._q.get(timeout=slice_s)
                    break
                except queue.Empty:
                    if timeout is not None:
                        waited += slice_s
                        if waited >= timeout:
                            raise
            if item[0] == "tokens":
                yield item[1]
                continue
            _, status, error = item
            if status == ERROR:
                raise RequestFailed(error or "request failed")
            return

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until terminal; full sequence (prompt + generated) as
        1-D int32. Raises :class:`RequestFailed` on
        ``error``/``rejected``/``timeout`` status."""
        t0 = time.monotonic()
        while not self._done.is_set():
            self._router._advance(self)
            if self._done.wait(0.02):
                break
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError(f"request {self.rid} still in flight "
                                   f"after {timeout}s")
        if self.status in (ERROR, REJECTED, TIMEOUT):
            raise RequestFailed(
                f"request {self.rid} {self.status}: {self.error}")
        if not self._tokens:
            return self.prompt.copy()
        return np.concatenate(
            [self.prompt, np.asarray(self._tokens, np.int32)])


class ReplicaRouter:
    """Deterministic affinity router over N
    :class:`~deepspeed_tpu.inference.serve.AsyncServingEngine` replicas.

    ``replicas`` share one weight pytree (build the extra engines with
    ``params=engine.params``) and — for the prefill/decode role split —
    one host KV tier (``engine.ensure_host_kv_pool()`` +
    ``adopt_host_kv_pool``). ``roles`` tags each replica ``"any"`` |
    ``"prefill"`` | ``"decode"``; ``prefill`` replicas never serve
    decode traffic, they warm prompts and ship the blocks host-side.
    ``affinity=False`` disables session hashing (every request takes the
    least-loaded path); ``handoff=False`` disables the disaggregated
    prefill path even when a prefill replica exists. Defaults resolve
    from the first engine's ``serving.replicas`` config section.

    The router presents the single-engine serving surface
    (``add_request`` / ``drain`` / ``shutdown`` / ``health_state`` /
    ``engine`` / ``policy``), so :func:`~deepspeed_tpu.inference.serve.
    build_http_server` fronts it unchanged: ``/healthz`` aggregates (503
    only when NO replica can serve), ``/metrics`` carries per-replica
    ``router/*`` series. Synchronous replicas (``start=False``) are
    driven with :meth:`step`, giving trace-replay determinism; threaded
    replicas pump through the handles' wait loops.

    ``decisions`` records every routing choice — ``{"seq", "replica",
    "reason", "session"}`` with reason one of ``affinity`` |
    ``least_loaded`` | ``failover`` | ``handoff`` | ``prefill`` — and is
    replay-identical for a replayed trace (the unit suite pins this).
    """

    def __init__(self, replicas, *, names: Optional[List[str]] = None,
                 roles: Optional[List[str]] = None,
                 affinity: Optional[bool] = None,
                 handoff: Optional[bool] = None, registry=None):
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.replicas = list(replicas)
        n = len(self.replicas)
        self.names = list(names) if names is not None else \
            [f"r{i}" for i in range(n)]
        if len(self.names) != n or len(set(self.names)) != n:
            raise ValueError(f"need {n} unique replica names, "
                             f"got {self.names}")
        rep_cfg = getattr(self.replicas[0].engine.config.serving,
                          "replicas", None)
        if roles is None:
            roles = list(getattr(rep_cfg, "roles", None) or [])
        roles = list(roles) + ["any"] * (n - len(roles))
        if len(roles) != n or any(r not in ROLES for r in roles):
            raise ValueError(f"roles must be {n} of {ROLES}, got {roles}")
        self.roles = roles
        if affinity is None:
            affinity = str(getattr(rep_cfg, "affinity", "session")) != "off"
        self.affinity = bool(affinity)
        # decode-capable replicas, in index order — the stable hash ring
        # for session affinity (membership never changes with health, so
        # a recovered replica gets its sessions back)
        self._serving_idx = [i for i in range(n)
                             if self.roles[i] != "prefill"]
        self._prefill_idx = [i for i in range(n)
                             if self.roles[i] == "prefill"]
        if not self._serving_idx:
            raise ValueError("at least one replica must be decode-capable "
                             "(role 'any' or 'decode')")
        if handoff is None:
            handoff = str(getattr(rep_cfg, "handoff", "auto")) != "off"
        self._handoff = bool(handoff) and bool(self._prefill_idx)
        # tag each replica's recorder + phase-ledger telemetry so fleet
        # merges and serving/phase_ms{replica=} carry the router's names
        for i, name in enumerate(self.names):
            self.replicas[i].engine.set_replica(name)
        self.decisions: List[Dict[str, Any]] = []
        self._seq = 0
        self._lock = threading.RLock()
        self._outstanding = [0] * n
        self._handles: List[RouterHandle] = []
        self._tripped: set = set()
        self._events = self.replicas[0].engine._events
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry
            registry = get_registry()
        self._m_requests = registry.counter(
            "router/requests",
            "requests routed to each replica (reason-agnostic; includes "
            "prefill warm-ups and failover replays)", ("replica",))
        self._m_drained = registry.counter(
            "router/drained_requests",
            "requests drained AWAY from a breaker-tripped/unhealthy "
            "replica and replayed on a sibling", ("replica",))
        self._m_handoffs = registry.counter(
            "router/handoffs",
            "disaggregated prefill->decode handoffs completed (blocks "
            "shipped through the host KV tier)")
        self._m_healthy = registry.gauge(
            "router/healthy", "1 when the replica can serve (not stopped/"
            "crashed/breaker-tripped/draining)", ("replica",))
        self._m_depth = registry.gauge(
            "router/queue_depth",
            "router-tracked outstanding requests per replica (the "
            "least-loaded tiebreak's queue-depth signal)", ("replica",))
        for i, name in enumerate(self.names):
            self._m_requests.labels(replica=name)
            self._m_drained.labels(replica=name)
            self._m_healthy.labels(replica=name).set(
                1.0 if self._replica_healthy(i) else 0.0)
            self._m_depth.labels(replica=name).set(0.0)

    # ------------------------------------------------------------------ #
    # single-engine surface compatibility

    @property
    def engine(self):
        """The first replica's engine (model identity, config access)."""
        return self.replicas[0].engine

    @property
    def policy(self):
        return self.replicas[0].policy

    @property
    def _stopped(self) -> bool:
        return all(r._stopped for r in self.replicas)

    @property
    def error(self):
        """A loop crash, surfaced only once NO replica can serve — the
        aggregate stays scrapeable (/metrics 200) while any sibling
        still works."""
        if any(self._replica_healthy(i) for i in range(len(self.replicas))):
            return None
        for r in self.replicas:
            if r.error is not None:
                return r.error
        return None

    @property
    def restarts(self) -> int:
        return sum(r.restarts for r in self.replicas)

    # ------------------------------------------------------------------ #
    # routing (deterministic)

    def _replica_healthy(self, i: int) -> bool:
        r = self.replicas[i]
        return not (r._stopped or r.error is not None or r._crash_loop
                    or r._draining)

    def _load_key(self, i: int):
        # queue depth (router-tracked outstanding — deterministic, unlike
        # a cross-thread sched peek), then burn (engine restarts: a
        # replica burning its error budget loses ties), then index
        return (self._outstanding[i], self.replicas[i].restarts, i)

    def _affine_idx(self, session: str) -> int:
        ring = self._serving_idx
        d = hashlib.blake2b(session.encode("utf-8"), digest_size=8).digest()
        return ring[int.from_bytes(d, "big") % len(ring)]

    def _pick_serving(self, exclude=()) -> Optional[int]:
        cands = [i for i in self._serving_idx
                 if i not in exclude and self._replica_healthy(i)]
        if not cands:
            # availability over specialization: with every decode-capable
            # replica down, a healthy prefill replica still serves
            cands = [i for i in range(len(self.replicas))
                     if i not in exclude and self._replica_healthy(i)]
        return min(cands, key=self._load_key) if cands else None

    def _pick_prefill(self) -> Optional[int]:
        cands = [i for i in self._prefill_idx if self._replica_healthy(i)]
        return min(cands, key=self._load_key) if cands else None

    def _record(self, reason: str, idx: int,
                session: Optional[str]) -> None:
        # caller holds self._lock
        d = {"seq": self._seq, "replica": self.names[idx],
             "reason": reason, "session": session or ""}
        self._seq += 1
        self.decisions.append(d)
        self._m_requests.labels(replica=self.names[idx]).inc()
        if self._events is not None:
            self._events.emit("serve.route", seq=d["seq"],
                              replica=d["replica"], reason=reason,
                              session=d["session"])

    # ------------------------------------------------------------------ #
    # front-end (any thread)

    def add_request(self, prompt, max_new_tokens: Optional[int] = None,
                    eos_token_id: Optional[int] = None, priority: int = 0,
                    ttft_budget: Optional[int] = None,
                    deadline_ms: Optional[float] = None,
                    deadline_steps: Optional[int] = None,
                    session: Optional[str] = None) -> RouterHandle:
        """Route and submit one request; returns its streaming handle.
        ``session`` is the affinity key (multi-turn clients pass a
        stable id so follow-up turns re-hit the replica that cached
        their prefix); everything else matches
        ``AsyncServingEngine.add_request``. Raises RuntimeError when no
        replica can accept work (-> HTTP 503)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        h = RouterHandle(self, prompt, max_new=max_new_tokens,
                         eos=eos_token_id, priority=int(priority),
                         ttft_budget=ttft_budget,
                         deadline_ms=deadline_ms,
                         deadline_steps=deadline_steps, session=session)
        with self._lock:
            if session is not None and self.affinity:
                pref = self._affine_idx(str(session))
                if self._replica_healthy(pref):
                    idx, reason = pref, "affinity"
                else:
                    idx, reason = self._pick_serving(), "failover"
            else:
                idx, reason = self._pick_serving(), "least_loaded"
            if idx is None:
                raise RuntimeError(
                    "no healthy replica: every serving loop is stopped, "
                    "draining, or parked in its crash-loop breaker")
            h._target_idx = idx
            # causal trace id, minted from the first decision's seq —
            # deterministic under replay, unique per routed request
            h.trace = f"t{self._seq}"
            pidx = None
            if (self._handoff and idx not in self._prefill_idx
                    and prompt.size >= int(self.replicas[0].engine
                                           .config.serving.block_size)):
                pidx = self._pick_prefill()
            if pidx is not None:
                # disaggregated path: decision for the decode target is
                # recorded NOW (routing is a function of submission-time
                # state, replay-identical), the prefill warm-up gets its
                # own decision line
                self._record("handoff", idx, session)
                self._record("prefill", pidx, session)
            else:
                self._record(reason, idx, session)
            self._handles.append(h)
        with h._lock:
            if pidx is not None:
                self._submit_warm(h, pidx)
            else:
                self._submit_inner(h)
        return h

    def _submit_warm(self, h: RouterHandle, pidx: int) -> None:
        try:
            h._warm = self.replicas[pidx].add_request(
                h.prompt, max_new_tokens=1, priority=h.priority,
                trace=h.trace)
        except (RuntimeError, ValueError):
            # prefill replica refused (raced into drain/breaker, or the
            # prompt is never-admittable there): fall back to the plain
            # path — handoff is an optimization, not a correctness gate
            self._submit_inner(h)
            return
        h._warm_idx = pidx
        h._stage = "warm"
        with self._lock:
            self._outstanding[pidx] += 1

    def _submit_inner(self, h: RouterHandle,
                      exclude: tuple = ()) -> None:
        """Submit (or re-submit) the real request to its target replica,
        walking to the least-loaded healthy sibling when the target
        cannot take it. Terminal-fails the handle when nothing can."""
        idx = h._target_idx
        tried = set(exclude)
        while True:
            if idx is None or idx in tried or not self._replica_healthy(idx):
                with self._lock:
                    idx = self._pick_serving(exclude=tried)
                if idx is None:
                    h._finish(ERROR, h.error or
                              "no healthy replica to serve the request")
                    return
            try:
                inner = self.replicas[idx].add_request(
                    h.prompt, max_new_tokens=h.max_new,
                    eos_token_id=h.eos, priority=h.priority,
                    ttft_budget=h.ttft_budget, deadline_ms=h.deadline_ms,
                    deadline_steps=h.deadline_steps, trace=h.trace,
                    parent=(h._warm.rid if h._warm is not None
                            else None))
            except RuntimeError:
                # raced into drain/breaker between the health check and
                # the intake append — try the next healthy sibling
                tried.add(idx)
                idx = None
                continue
            h._inner = inner
            h._inner_idx = idx
            h.replica = self.names[idx]
            h._stage = "running"
            with self._lock:
                self._outstanding[idx] += 1
            return

    # ------------------------------------------------------------------ #
    # the pump: move each handle's state machine forward

    def _advance(self, h: RouterHandle) -> None:
        """Drain the handle's current inner queue(s) and run its
        handoff/failover transitions. Called from :meth:`step` (sync
        replay) and from the handle's own wait loops (threaded mode);
        idempotent and cheap when there is nothing to do."""
        if h._done.is_set():
            return
        with h._lock:
            if h._done.is_set():
                return
            if h._stage == "warm":
                self._pump_warm(h)
            if h._stage == "demote":
                if h._demote_evt is not None and h._demote_evt.is_set():
                    with self._lock:
                        self._m_handoffs.inc()
                    self._note_handoff(h)
                    self._submit_inner(h)
            if h._stage == "running" and h._inner is not None:
                self._pump_running(h)
        self._refresh_gauges()

    def _pump_warm(self, h: RouterHandle) -> None:
        w = h._warm
        if w is None or not w.done():
            return
        with self._lock:
            self._outstanding[h._warm_idx] -= 1
        if h._cancelled:
            h._finish(CANCELLED)
            return
        if w.status == FINISHED:
            # prompt blocks are committed cold on the prefill replica:
            # push them into the shared host tier, then hold the decode
            # submission until the demotion ran (the event) so the decode
            # admission probe finds the chain host-resident
            h._demote_evt = self.replicas[h._warm_idx].request_demote(
                h.prompt)
            h._demote_t0 = time.perf_counter()
            h._stage = "demote"
        else:
            # warm-up failed (rejected under pressure, faulted, timed
            # out): serve the plain way — the decode replica recomputes
            self._submit_inner(h)

    def _note_handoff(self, h: RouterHandle) -> None:
        """Handoff completed: the warmed blocks are host-resident and the
        decode-side submission goes out next. Emits the cross-replica
        ``serve.handoff`` flow anchor (rid = the prefill-side rid, so the
        fleet merge can pin the hop) and books the demote wall time as
        the ``handoff`` phase on the prefill replica's ledger."""
        wrid = h._warm.rid if h._warm is not None else None
        if self._events is not None:
            self._events.emit(
                "serve.handoff", rid=wrid, trace=h.trace,
                from_replica=self.names[h._warm_idx],
                to_replica=(self.names[h._target_idx]
                            if h._target_idx is not None else ""),
                replica=self.names[h._warm_idx])
        tel = self.replicas[h._warm_idx].engine._serving_tel
        if tel is not None and h._demote_t0 is not None:
            tel.phase("handoff",
                      max(time.perf_counter() - h._demote_t0, 0.0) * 1e3,
                      rid=wrid)

    def _pump_running(self, h: RouterHandle) -> None:
        inner = h._inner
        if h.rid is None and inner.rid is not None:
            h.rid = inner.rid
        while True:
            try:
                item = inner._q.get_nowait()
            except queue.Empty:
                return
            if item[0] == "tokens":
                burst = item[1]
                if h._skip:
                    # failover replay: the sibling re-derives the full
                    # greedy stream; drop the prefix the client already
                    # has and splice the continuation in seamlessly
                    take = burst[h._skip:]
                    h._skip = max(h._skip - len(burst), 0)
                    burst = take
                if burst:
                    h._push(burst)
                continue
            _, status, err = item
            with self._lock:
                self._outstanding[h._inner_idx] -= 1
            if (status in (ERROR, REJECTED)
                    and not self._replica_healthy(h._inner_idx)
                    and not h._cancelled):
                # the replica died under the request (breaker trip, loop
                # crash) — that is the replica's fault, not the
                # request's: drain it to a sibling
                self._failover(h, err)
                if h._inner is inner:
                    return           # no sibling: handle already failed
                inner = h._inner     # pump the replay immediately
                continue
            h.retry_after = inner.retry_after
            h._finish(status, err)
            return

    def _failover(self, h: RouterHandle, err: Optional[str]) -> None:
        from_idx = h._inner_idx
        name = self.names[from_idx]
        with self._lock:
            self._m_drained.labels(replica=name).inc()
            if from_idx not in self._tripped:
                self._tripped.add(from_idx)
                if self._events is not None:
                    sched = self.replicas[from_idx]._session.sched
                    self._events.emit(
                        "serve.drain", replica=name,
                        waiting=len(sched.waiting),
                        running=len(sched.running), pending=0)
            idx = self._pick_serving(exclude={from_idx})
            if idx is not None:
                h._target_idx = idx
                self._record("failover", idx, h.session)
        if idx is None:
            h._finish(ERROR, err or f"replica {name} failed and no "
                                    "healthy sibling remains")
            return
        # wasted-work ledger: every token the failed replica produced is
        # recomputed by the sibling's replay (booked on the FAILED
        # replica — the waste is its fault domain's)
        tel = self.replicas[from_idx].engine._serving_tel
        if tel is not None and h._tokens:
            tel.waste("failover", len(h._tokens))
        h._skip = len(h._tokens)
        h._failovers += 1
        h.rid = None
        self._submit_inner(h, exclude=(from_idx,))

    def _refresh_gauges(self) -> None:
        with self._lock:
            for i, name in enumerate(self.names):
                self._m_healthy.labels(replica=name).set(
                    1.0 if self._replica_healthy(i) else 0.0)
                self._m_depth.labels(replica=name).set(
                    float(self._outstanding[i]))

    # ------------------------------------------------------------------ #
    # lifecycle

    def step(self) -> bool:
        """Synchronous replay driver (every replica built with
        ``start=False``): one ``step()`` per live replica, then one pump
        per live handle. Returns False once every replica is idle and
        every handle is terminal — ``while router.step(): pass`` runs a
        trace to completion deterministically."""
        busy = False
        for r in self.replicas:
            if r._thread is None and not r._stopped:
                if r.step():
                    busy = True
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            self._advance(h)
            if h._done.is_set():
                with self._lock:
                    if h in self._handles:
                        self._handles.remove(h)
            else:
                busy = True
        self._refresh_gauges()
        return busy

    def export_fleet_trace(self, path: str) -> str:
        """Merge every replica's serving events plus the router's own
        decision/handoff markers onto ONE Perfetto timeline (chrome
        trace JSON) with flow arrows across prefill→decode handoffs.
        Replicas share the process-global flight-recorder ring, so the
        first replica's snapshot already covers the fleet."""
        from deepspeed_tpu.monitor.events import export_fleet_trace
        if self._events is None:
            raise RuntimeError("flight recorder disabled "
                               "(telemetry.events.enable)")
        return export_fleet_trace(self._events.snapshot(), path)

    def health_state(self):
        """Aggregate ``(status_code, body)`` for ``/healthz``: 503 only
        when NO replica can serve; the body carries the single-engine
        keys (summed) plus a per-replica breakdown."""
        reps: Dict[str, Any] = {}
        n_ok = 0
        depth = running = restarts = 0
        ticks = 0
        for i, r in enumerate(self.replicas):
            code, body = r.health_state()
            body["role"] = self.roles[i]
            reps[self.names[i]] = body
            if code == 200:
                n_ok += 1
            depth += body["queue_depth"]
            running += body["running"]
            restarts += body["restarts"]
            ticks = max(ticks, body["uptime_ticks"])
        state = ("serving" if n_ok else
                 "stopped" if self._stopped else "crash_loop")
        return (200 if n_ok else 503), {
            "state": state, "stopped": self._stopped,
            "queue_depth": depth, "running": running,
            "restarts": restarts, "uptime_ticks": ticks,
            "healthy_replicas": n_ok,
            "total_replicas": len(self.replicas), "replicas": reps}

    def drain(self) -> None:
        for r in self.replicas:
            r.drain()

    def join(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        ok = True
        for r in self.replicas:
            left = None if deadline is None else \
                max(deadline - time.monotonic(), 0.0)
            ok = r.join(left) and ok
        return ok

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop every replica. In synchronous mode a draining shutdown
        first pumps the router to completion (handoffs still need NEW
        submissions, which a draining replica would reject), then drains
        each loop; re-raises the first replica crash encountered."""
        if drain and all(r._thread is None for r in self.replicas):
            while self.step():
                pass
        first: Optional[BaseException] = None
        for r in self.replicas:
            try:
                r.shutdown(drain=drain, timeout=timeout)
            except Exception as e:  # noqa: BLE001 — stop the REST first
                if first is None:
                    first = e
        with self._lock:
            handles = list(self._handles)
            self._handles.clear()
        for h in handles:
            self._advance(h)
            h._finish(CANCELLED, "serving loop shut down")
        self._refresh_gauges()      # the pane flips to DOWN immediately
        if first is not None:
            raise first

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
