"""Per-architecture HF -> zoo parameter policies.

Reference parity: ``deepspeed/module_inject/containers/{gpt2,gptneox,opt,
bloom,llama}.py`` + ``replace_policy.py`` — each policy knows the
architecture's tensor names, fused-qkv layout, and module config.

Conventions of the zoo layout (``models/transformer.py``):
- linear weights are [in, out] (HF ``nn.Linear`` stores [out, in] and is
  transposed; GPT-2's ``Conv1D`` already stores [in, out]);
- per-layer weights are stacked with a leading ``n_layer`` dim;
- fused query_key_value tensors are de-interleaved with the architecture's
  actual head layout ([H, 3, Hd] for bloom/neox — a plain reshape would
  silently interleave q/k/v, reference ``qkv_copy``/containers).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig


def _stack(get, names, transform=None):
    arrs = [np.asarray(get(n)) for n in names]
    if transform is not None:
        arrs = [transform(a) for a in arrs]
    return np.stack(arrs)


def _t(a):
    return np.ascontiguousarray(a.T)


class HFPolicy:
    """Base policy: subclasses define ``model_type``, ``zoo_config`` and
    ``map_params``; non-decoder families also override ``build_model``."""

    model_type: str = ""

    def zoo_config(self, hf: Dict[str, Any]) -> TransformerConfig:
        raise NotImplementedError

    def map_params(self, get: Callable[[str], np.ndarray], cfg: TransformerConfig) -> Dict:
        raise NotImplementedError

    def build_model(self, cfg: TransformerConfig, hf: Dict[str, Any], params: Dict):
        """Model instance for the mapped params; None = ``CausalLM(cfg)``
        (decoder families). Encoder families (DistilBERT) return their own
        zoo model here."""
        return None


class GPT2Policy(HFPolicy):
    """HF ``gpt2`` (reference ``containers/gpt2.py``). Conv1D weights are
    already [in, out]; c_attn is [D, 3D] fused q|k|v (block concat)."""

    model_type = "gpt2"

    def zoo_config(self, hf):
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["n_layer"], n_head=hf["n_head"],
            d_model=hf["n_embd"], max_seq=hf["n_positions"], pos_embedding="learned",
            norm="layernorm", activation="gelu", tie_embeddings=True, attn_bias=True,
            norm_eps=hf.get("layer_norm_epsilon", 1e-5))

    def map_params(self, raw_get, cfg):
        L, D = cfg.n_layer, cfg.d_model
        ls = range(L)

        def get(name):  # files may carry a "transformer." prefix
            try:
                return raw_get(name)
            except KeyError:
                return raw_get("transformer." + name)

        def qkv_w(i):  # [D, 3D] -> 3 x [D, D]
            return np.split(np.asarray(get(f"h.{i}.attn.c_attn.weight")), 3, axis=1)

        def qkv_b(i):
            return np.split(np.asarray(get(f"h.{i}.attn.c_attn.bias")), 3, axis=0)

        qw, kw, vw = zip(*[qkv_w(i) for i in ls])
        qb, kb, vb = zip(*[qkv_b(i) for i in ls])
        return {
            "embed": {"tokens": np.asarray(get("wte.weight")),
                      "positions": np.asarray(get("wpe.weight"))},
            "layers": {
                "ln_attn": {"scale": _stack(get, [f"h.{i}.ln_1.weight" for i in ls]),
                            "bias": _stack(get, [f"h.{i}.ln_1.bias" for i in ls])},
                "attn": {"wq": np.stack(qw), "wk": np.stack(kw), "wv": np.stack(vw),
                         "bq": np.stack(qb), "bk": np.stack(kb), "bv": np.stack(vb),
                         "wo": _stack(get, [f"h.{i}.attn.c_proj.weight" for i in ls]),
                         "bo": _stack(get, [f"h.{i}.attn.c_proj.bias" for i in ls])},
                "ln_mlp": {"scale": _stack(get, [f"h.{i}.ln_2.weight" for i in ls]),
                           "bias": _stack(get, [f"h.{i}.ln_2.bias" for i in ls])},
                "mlp": {"w_up": _stack(get, [f"h.{i}.mlp.c_fc.weight" for i in ls]),
                        "b_up": _stack(get, [f"h.{i}.mlp.c_fc.bias" for i in ls]),
                        "w_down": _stack(get, [f"h.{i}.mlp.c_proj.weight" for i in ls]),
                        "b_down": _stack(get, [f"h.{i}.mlp.c_proj.bias" for i in ls])},
            },
            "ln_f": {"scale": np.asarray(get("ln_f.weight")),
                     "bias": np.asarray(get("ln_f.bias"))},
        }


class LlamaPolicy(HFPolicy):
    """HF ``llama`` (reference ``containers/llama.py``). nn.Linear weights
    [out, in] -> transpose; separate q/k/v; GQA via num_key_value_heads."""

    model_type = "llama"

    def zoo_config(self, hf):
        scaling = hf.get("rope_scaling")
        if scaling is not None:
            # configs can spell plain rope explicitly: rope_type/type
            # "default", or linear with factor 1.0 — those are no-ops
            kind = scaling.get("rope_type", scaling.get("type", "default"))
            noop = kind == "default" or (kind == "linear"
                                         and float(scaling.get("factor", 1.0)) == 1.0)
            if not noop:
                # e.g. Llama-3.1 llama3/longrope scaling — silently loading it
                # as plain rope would give wrong logits at long positions
                raise NotImplementedError(
                    f"llama rope_scaling={scaling!r}: scaled rope variants "
                    "are not represented in the zoo transformer")
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["num_hidden_layers"],
            n_head=hf["num_attention_heads"], d_model=hf["hidden_size"],
            d_ff=hf["intermediate_size"], max_seq=hf.get("max_position_embeddings", 2048),
            n_kv_head=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            pos_embedding="rope", norm="rmsnorm", activation="swiglu",
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            norm_eps=hf.get("rms_norm_eps", 1e-6))

    def map_params(self, get, cfg):
        L = cfg.n_layer
        ls = range(L)
        p = "model.layers"
        out = {
            "embed": {"tokens": np.asarray(get("model.embed_tokens.weight"))},
            "layers": {
                "ln_attn": {"scale": _stack(get, [f"{p}.{i}.input_layernorm.weight" for i in ls])},
                "attn": {"wq": _stack(get, [f"{p}.{i}.self_attn.q_proj.weight" for i in ls], _t),
                         "wk": _stack(get, [f"{p}.{i}.self_attn.k_proj.weight" for i in ls], _t),
                         "wv": _stack(get, [f"{p}.{i}.self_attn.v_proj.weight" for i in ls], _t),
                         "wo": _stack(get, [f"{p}.{i}.self_attn.o_proj.weight" for i in ls], _t)},
                "ln_mlp": {"scale": _stack(get, [f"{p}.{i}.post_attention_layernorm.weight" for i in ls])},
                "mlp": {"w_gate": _stack(get, [f"{p}.{i}.mlp.gate_proj.weight" for i in ls], _t),
                        "w_up": _stack(get, [f"{p}.{i}.mlp.up_proj.weight" for i in ls], _t),
                        "w_down": _stack(get, [f"{p}.{i}.mlp.down_proj.weight" for i in ls], _t)},
            },
            "ln_f": {"scale": np.asarray(get("model.norm.weight"))},
        }
        if not cfg.tie_embeddings:
            out["lm_head"] = _t(np.asarray(get("lm_head.weight")))
        return out


def _split_headwise_qkv(w, H, Hd):
    """[3*H*Hd, D] fused with [H, 3, Hd] output layout (bloom/neox) ->
    three [D, H*Hd] (zoo orientation)."""
    D = w.shape[1]
    w = w.reshape(H, 3, Hd, D)
    return tuple(np.ascontiguousarray(w[:, j].reshape(H * Hd, D).T) for j in range(3))


def _split_headwise_qkv_bias(b, H, Hd):
    b = b.reshape(H, 3, Hd)
    return tuple(np.ascontiguousarray(b[:, j].reshape(H * Hd)) for j in range(3))


class BloomPolicy(HFPolicy):
    """HF ``bloom`` (reference ``containers/bloom.py``): alibi positions,
    word-embeddings layernorm, per-head-interleaved fused qkv."""

    model_type = "bloom"

    def zoo_config(self, hf):
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["n_layer"], n_head=hf["n_head"],
            d_model=hf["hidden_size"], max_seq=2048, pos_embedding="alibi",
            norm="layernorm", activation="gelu", tie_embeddings=True,
            embed_layernorm=True, attn_bias=True,
            norm_eps=hf.get("layer_norm_epsilon", 1e-5))

    def map_params(self, get, cfg):
        L, H, Hd = cfg.n_layer, cfg.n_head, cfg.head_dim
        ls = range(L)
        p = "h"

        def strip(name):  # files may carry a "transformer." prefix
            try:
                return get(name)
            except KeyError:
                return get("transformer." + name)

        qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
        for i in ls:
            w3 = np.asarray(strip(f"{p}.{i}.self_attention.query_key_value.weight"))
            b3 = np.asarray(strip(f"{p}.{i}.self_attention.query_key_value.bias"))
            a, b, c = _split_headwise_qkv(w3, H, Hd)
            qw.append(a); kw.append(b); vw.append(c)
            a, b, c = _split_headwise_qkv_bias(b3, H, Hd)
            qb.append(a); kb.append(b); vb.append(c)

        g = lambda n: strip(n)
        return {
            "embed": {"tokens": np.asarray(g("word_embeddings.weight")),
                      "ln": {"scale": np.asarray(g("word_embeddings_layernorm.weight")),
                             "bias": np.asarray(g("word_embeddings_layernorm.bias"))}},
            "layers": {
                "ln_attn": {"scale": _stack(g, [f"{p}.{i}.input_layernorm.weight" for i in ls]),
                            "bias": _stack(g, [f"{p}.{i}.input_layernorm.bias" for i in ls])},
                "attn": {"wq": np.stack(qw), "wk": np.stack(kw), "wv": np.stack(vw),
                         "bq": np.stack(qb), "bk": np.stack(kb), "bv": np.stack(vb),
                         "wo": _stack(g, [f"{p}.{i}.self_attention.dense.weight" for i in ls], _t),
                         "bo": _stack(g, [f"{p}.{i}.self_attention.dense.bias" for i in ls])},
                "ln_mlp": {"scale": _stack(g, [f"{p}.{i}.post_attention_layernorm.weight" for i in ls]),
                           "bias": _stack(g, [f"{p}.{i}.post_attention_layernorm.bias" for i in ls])},
                "mlp": {"w_up": _stack(g, [f"{p}.{i}.mlp.dense_h_to_4h.weight" for i in ls], _t),
                        "b_up": _stack(g, [f"{p}.{i}.mlp.dense_h_to_4h.bias" for i in ls]),
                        "w_down": _stack(g, [f"{p}.{i}.mlp.dense_4h_to_h.weight" for i in ls], _t),
                        "b_down": _stack(g, [f"{p}.{i}.mlp.dense_4h_to_h.bias" for i in ls])},
            },
            "ln_f": {"scale": np.asarray(g("ln_f.weight")),
                     "bias": np.asarray(g("ln_f.bias"))},
        }


class OPTPolicy(HFPolicy):
    """HF ``opt`` (reference ``containers/opt.py``): learned positions with
    a +2 offset, separate q/k/v with biases, relu MLP."""

    model_type = "opt"

    def zoo_config(self, hf):
        if not hf.get("do_layer_norm_before", True):
            # opt-350m style post-LN — the zoo transformer is pre-LN only;
            # loading it anyway would produce silently wrong logits
            raise NotImplementedError(
                "opt do_layer_norm_before=False (post-layernorm variant, e.g. "
                "opt-350m) is not supported by the pre-LN zoo transformer")
        if hf.get("_remove_final_layer_norm", False):
            raise NotImplementedError(
                "opt _remove_final_layer_norm=True checkpoints are not supported")
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["num_hidden_layers"],
            n_head=hf["num_attention_heads"], d_model=hf["hidden_size"],
            d_ff=hf["ffn_dim"], max_seq=hf["max_position_embeddings"],
            pos_embedding="learned", norm="layernorm",
            activation=hf.get("activation_function", "relu"),
            tie_embeddings=True, attn_bias=True)

    def map_params(self, get, cfg):
        L = cfg.n_layer
        ls = range(L)
        p = "model.decoder.layers"

        def g(n):
            try:
                return get(n)
            except KeyError:
                return get(n.replace("model.decoder.", "decoder."))

        return {
            # OPT's embed_positions carries a 2-slot offset pad in front
            "embed": {"tokens": np.asarray(g("model.decoder.embed_tokens.weight")),
                      "positions": np.asarray(g("model.decoder.embed_positions.weight"))[2:]},
            "layers": {
                "ln_attn": {"scale": _stack(g, [f"{p}.{i}.self_attn_layer_norm.weight" for i in ls]),
                            "bias": _stack(g, [f"{p}.{i}.self_attn_layer_norm.bias" for i in ls])},
                "attn": {"wq": _stack(g, [f"{p}.{i}.self_attn.q_proj.weight" for i in ls], _t),
                         "wk": _stack(g, [f"{p}.{i}.self_attn.k_proj.weight" for i in ls], _t),
                         "wv": _stack(g, [f"{p}.{i}.self_attn.v_proj.weight" for i in ls], _t),
                         "bq": _stack(g, [f"{p}.{i}.self_attn.q_proj.bias" for i in ls]),
                         "bk": _stack(g, [f"{p}.{i}.self_attn.k_proj.bias" for i in ls]),
                         "bv": _stack(g, [f"{p}.{i}.self_attn.v_proj.bias" for i in ls]),
                         "wo": _stack(g, [f"{p}.{i}.self_attn.out_proj.weight" for i in ls], _t),
                         "bo": _stack(g, [f"{p}.{i}.self_attn.out_proj.bias" for i in ls])},
                "ln_mlp": {"scale": _stack(g, [f"{p}.{i}.final_layer_norm.weight" for i in ls]),
                           "bias": _stack(g, [f"{p}.{i}.final_layer_norm.bias" for i in ls])},
                "mlp": {"w_up": _stack(g, [f"{p}.{i}.fc1.weight" for i in ls], _t),
                        "b_up": _stack(g, [f"{p}.{i}.fc1.bias" for i in ls]),
                        "w_down": _stack(g, [f"{p}.{i}.fc2.weight" for i in ls], _t),
                        "b_down": _stack(g, [f"{p}.{i}.fc2.bias" for i in ls])},
            },
            "ln_f": {"scale": np.asarray(g("model.decoder.final_layer_norm.weight")),
                     "bias": np.asarray(g("model.decoder.final_layer_norm.bias"))},
        }


class GPTNeoXPolicy(HFPolicy):
    """HF ``gpt_neox`` (reference ``containers/gptneox.py``): parallel
    residual, rotary (optionally partial via ``rotary_pct``), per-head-
    interleaved fused qkv with biases."""

    model_type = "gpt_neox"

    def zoo_config(self, hf):
        pct = float(hf.get("rotary_pct", 1.0))
        head_dim = hf["hidden_size"] // hf["num_attention_heads"]
        rope_dim = int(head_dim * pct)
        if pct != 1.0 and rope_dim % 2:
            raise NotImplementedError(
                f"gpt_neox rotary_pct={pct}: odd rotary dim {rope_dim}")
        return TransformerConfig(
            rope_dim=0 if pct == 1.0 else rope_dim,
            vocab_size=hf["vocab_size"], n_layer=hf["num_hidden_layers"],
            n_head=hf["num_attention_heads"], d_model=hf["hidden_size"],
            d_ff=hf["intermediate_size"], max_seq=hf["max_position_embeddings"],
            pos_embedding="rope", norm="layernorm", activation="gelu",
            parallel_residual=bool(hf.get("use_parallel_residual", True)),
            tie_embeddings=bool(hf.get("tie_word_embeddings", False)), attn_bias=True,
            # newer HF configs serialize the base as "rope_theta", older as
            # "rotary_emb_base" — honor both so the base is never silently lost
            rope_theta=float(hf.get("rotary_emb_base",
                                    hf.get("rope_theta", 10000.0))),
            norm_eps=hf.get("layer_norm_eps", 1e-5))

    def map_params(self, get, cfg):
        L, H, Hd = cfg.n_layer, cfg.n_head, cfg.head_dim
        ls = range(L)
        p = "gpt_neox.layers"

        qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
        for i in ls:
            w3 = np.asarray(get(f"{p}.{i}.attention.query_key_value.weight"))
            b3 = np.asarray(get(f"{p}.{i}.attention.query_key_value.bias"))
            a, b, c = _split_headwise_qkv(w3, H, Hd)
            qw.append(a); kw.append(b); vw.append(c)
            a, b, c = _split_headwise_qkv_bias(b3, H, Hd)
            qb.append(a); kb.append(b); vb.append(c)

        out = {
            "embed": {"tokens": np.asarray(get("gpt_neox.embed_in.weight"))},
            "layers": {
                "ln_attn": {"scale": _stack(get, [f"{p}.{i}.input_layernorm.weight" for i in ls]),
                            "bias": _stack(get, [f"{p}.{i}.input_layernorm.bias" for i in ls])},
                "attn": {"wq": np.stack(qw), "wk": np.stack(kw), "wv": np.stack(vw),
                         "bq": np.stack(qb), "bk": np.stack(kb), "bv": np.stack(vb),
                         "wo": _stack(get, [f"{p}.{i}.attention.dense.weight" for i in ls], _t),
                         "bo": _stack(get, [f"{p}.{i}.attention.dense.bias" for i in ls])},
                "ln_mlp": {"scale": _stack(get, [f"{p}.{i}.post_attention_layernorm.weight" for i in ls]),
                           "bias": _stack(get, [f"{p}.{i}.post_attention_layernorm.bias" for i in ls])},
                "mlp": {"w_up": _stack(get, [f"{p}.{i}.mlp.dense_h_to_4h.weight" for i in ls], _t),
                        "b_up": _stack(get, [f"{p}.{i}.mlp.dense_h_to_4h.bias" for i in ls]),
                        "w_down": _stack(get, [f"{p}.{i}.mlp.dense_4h_to_h.weight" for i in ls], _t),
                        "b_down": _stack(get, [f"{p}.{i}.mlp.dense_4h_to_h.bias" for i in ls])},
            },
            "ln_f": {"scale": np.asarray(get("gpt_neox.final_layer_norm.weight")),
                     "bias": np.asarray(get("gpt_neox.final_layer_norm.bias"))},
        }
        if not cfg.tie_embeddings:
            out["lm_head"] = _t(np.asarray(get("embed_out.weight")))
        return out


class GPTJPolicy(HFPolicy):
    """HF ``gptj`` (reference ``containers/gptj.py``): single-LN parallel
    residual (attn and mlp both read ln_1 — mapped by aliasing ln_attn and
    ln_mlp to the same weights), partial INTERLEAVED rotary (``rotary_dim``,
    rotate-every-two pairing), bias-free separate q/k/v, untied lm_head
    WITH bias."""

    model_type = "gptj"

    def zoo_config(self, hf):
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["n_layer"],
            n_head=hf["n_head"], d_model=hf["n_embd"],
            d_ff=hf.get("n_inner") or 4 * hf["n_embd"],
            max_seq=hf.get("n_positions", 2048),
            pos_embedding="rope", norm="layernorm", activation="gelu",
            parallel_residual=True, tie_embeddings=False, attn_bias=False,
            rope_dim=int(hf.get("rotary_dim") or hf["n_embd"] // hf["n_head"]),
            rope_interleaved=True, lm_head_bias=True,
            norm_eps=hf.get("layer_norm_epsilon", 1e-5))

    def map_params(self, raw_get, cfg):
        L = cfg.n_layer
        ls = range(L)
        p = "transformer.h"

        def get(name):
            try:
                return raw_get(name)
            except KeyError:
                return raw_get(name[len("transformer."):]
                               if name.startswith("transformer.") else
                               "transformer." + name)

        ln_scale = _stack(get, [f"{p}.{i}.ln_1.weight" for i in ls])
        ln_bias = _stack(get, [f"{p}.{i}.ln_1.bias" for i in ls])
        return {
            "embed": {"tokens": np.asarray(get("transformer.wte.weight"))},
            "layers": {
                # GPT-J has ONE pre-LN feeding both branches: alias it
                "ln_attn": {"scale": ln_scale, "bias": ln_bias},
                "ln_mlp": {"scale": ln_scale.copy(), "bias": ln_bias.copy()},
                "attn": {"wq": _stack(get, [f"{p}.{i}.attn.q_proj.weight" for i in ls], _t),
                         "wk": _stack(get, [f"{p}.{i}.attn.k_proj.weight" for i in ls], _t),
                         "wv": _stack(get, [f"{p}.{i}.attn.v_proj.weight" for i in ls], _t),
                         "wo": _stack(get, [f"{p}.{i}.attn.out_proj.weight" for i in ls], _t)},
                "mlp": {"w_up": _stack(get, [f"{p}.{i}.mlp.fc_in.weight" for i in ls], _t),
                        "b_up": _stack(get, [f"{p}.{i}.mlp.fc_in.bias" for i in ls]),
                        "w_down": _stack(get, [f"{p}.{i}.mlp.fc_out.weight" for i in ls], _t),
                        "b_down": _stack(get, [f"{p}.{i}.mlp.fc_out.bias" for i in ls])},
            },
            "ln_f": {"scale": np.asarray(get("transformer.ln_f.weight")),
                     "bias": np.asarray(get("transformer.ln_f.bias"))},
            "lm_head": _t(np.asarray(get("lm_head.weight"))),
            "lm_head_bias": np.asarray(get("lm_head.bias")),
        }


class GPTNeoPolicy(HFPolicy):
    """HF ``gpt_neo`` (reference ``containers/gptneo.py``): GPT-2-style
    block with UNSCALED attention (attn_scale=1.0), gelu_new MLP, and
    q/k/v projections without biases (out_proj keeps one; the zoo's
    all-or-nothing attn_bias rides with zero q/k/v biases).

    Local-attention layers (``attention_types`` containing "local") are
    window-limited at window_size tokens; at sequence lengths <= the window
    local == global attention, so ingestion caps ``max_seq`` to the window
    and the model is exact there. Longer contexts would need the banded
    mask and are rejected by max_seq."""

    model_type = "gpt_neo"

    @staticmethod
    def _has_local(hf) -> bool:
        def leaves(x):
            if isinstance(x, (list, tuple)):
                for e in x:
                    yield from leaves(e)
            else:
                yield x
        return any(l == "local" for l in leaves(hf.get("attention_types", [])))

    def zoo_config(self, hf):
        max_seq = hf.get("max_position_embeddings", 2048)
        if self._has_local(hf):
            window = int(hf.get("window_size", 256))
            if window < max_seq:
                from deepspeed_tpu.utils.logging import warn_once
                warn_once(
                    f"gpt_neo has local-attention layers: max_seq capped to "
                    f"window_size={window} (local == global there); longer "
                    "contexts need banded attention")
                max_seq = window
        act = {"gelu_new": "gelu", "gelu": "gelu_exact",
               "relu": "relu"}.get(hf.get("activation_function", "gelu_new"))
        if act is None:
            raise ValueError(f"unsupported gpt_neo activation_function "
                             f"{hf.get('activation_function')!r}")
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["num_layers"],
            n_head=hf["num_heads"], d_model=hf["hidden_size"],
            d_ff=hf.get("intermediate_size") or 4 * hf["hidden_size"],
            max_seq=max_seq, pos_embedding="learned", norm="layernorm",
            activation=act, tie_embeddings=True, attn_bias=True,
            attn_scale=1.0, norm_eps=hf.get("layer_norm_epsilon", 1e-5))

    def map_params(self, raw_get, cfg):
        L, D = cfg.n_layer, cfg.d_model
        ls = range(L)

        def get(name):
            try:
                return raw_get(name)
            except KeyError:
                return raw_get("transformer." + name)

        def zeros_like_rows(n):
            return np.zeros((L, n), np.float32)

        att = "h.{}.attn.attention"
        return {
            "embed": {"tokens": np.asarray(get("wte.weight")),
                      "positions": np.asarray(get("wpe.weight"))[:cfg.max_seq]},
            "layers": {
                "ln_attn": {"scale": _stack(get, [f"h.{i}.ln_1.weight" for i in ls]),
                            "bias": _stack(get, [f"h.{i}.ln_1.bias" for i in ls])},
                "attn": {
                    "wq": _stack(get, [att.format(i) + ".q_proj.weight" for i in ls], _t),
                    "wk": _stack(get, [att.format(i) + ".k_proj.weight" for i in ls], _t),
                    "wv": _stack(get, [att.format(i) + ".v_proj.weight" for i in ls], _t),
                    "wo": _stack(get, [att.format(i) + ".out_proj.weight" for i in ls], _t),
                    # q/k/v carry no biases in gpt-neo; out_proj does
                    "bq": zeros_like_rows(D), "bk": zeros_like_rows(D),
                    "bv": zeros_like_rows(D),
                    "bo": _stack(get, [att.format(i) + ".out_proj.bias" for i in ls]),
                },
                "ln_mlp": {"scale": _stack(get, [f"h.{i}.ln_2.weight" for i in ls]),
                           "bias": _stack(get, [f"h.{i}.ln_2.bias" for i in ls])},
                "mlp": {"w_up": _stack(get, [f"h.{i}.mlp.c_fc.weight" for i in ls], _t),
                        "b_up": _stack(get, [f"h.{i}.mlp.c_fc.bias" for i in ls]),
                        "w_down": _stack(get, [f"h.{i}.mlp.c_proj.weight" for i in ls], _t),
                        "b_down": _stack(get, [f"h.{i}.mlp.c_proj.bias" for i in ls])},
            },
            "ln_f": {"scale": np.asarray(get("ln_f.weight")),
                     "bias": np.asarray(get("ln_f.bias"))},
        }


class DistilBertPolicy(HFPolicy):
    """HF ``distilbert`` (reference ``containers/distil_bert.py``): a BERT
    encoder without token-type embeddings or pooler; the MLM head
    (vocab_transform + vocab_layer_norm + tied vocab_projector) maps onto
    the zoo BertModel's mlm block. Serves through the BertModel fill-mask
    surface."""

    model_type = "distilbert"

    def zoo_config(self, hf):
        act = {"gelu": "gelu_exact", "relu": "relu"}.get(
            hf.get("activation", "gelu"))
        if act is None:
            raise ValueError(f"unsupported distilbert activation "
                             f"{hf.get('activation')!r}")
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["n_layers"],
            n_head=hf["n_heads"], d_model=hf["dim"], d_ff=hf["hidden_dim"],
            max_seq=hf.get("max_position_embeddings", 512),
            pos_embedding="learned", norm="layernorm", norm_position="post",
            activation=act, causal=False, attn_bias=True,
            tie_embeddings=True, norm_eps=1e-12)

    def build_model(self, cfg, hf, params):
        from deepspeed_tpu.models.bert import BertConfig, BertModel
        bc = BertConfig(vocab_size=cfg.vocab_size, max_seq=cfg.max_seq,
                        n_layer=cfg.n_layer, n_head=cfg.n_head,
                        d_model=cfg.d_model, d_ff=cfg.d_ff,
                        type_vocab_size=1, norm_eps=1e-12,
                        activation=cfg.activation)
        return BertModel(bc, with_mlm_head="mlm" in params)

    def map_params(self, raw_get, cfg):
        L, D = cfg.n_layer, cfg.d_model
        ls = range(L)

        def get(name):
            try:
                return raw_get(name)
            except KeyError:
                return raw_get("distilbert." + name)

        lp = "transformer.layer.{}"
        out = {
            "embed": {
                "tokens": np.asarray(get("embeddings.word_embeddings.weight")),
                "positions": np.asarray(get("embeddings.position_embeddings.weight")),
                # distilbert has no token types: one all-zero row (index 0)
                "token_type": np.zeros((1, D), np.float32),
                "ln": {"scale": np.asarray(get("embeddings.LayerNorm.weight")),
                       "bias": np.asarray(get("embeddings.LayerNorm.bias"))},
            },
            "layers": {
                "ln_attn": {"scale": _stack(get, [lp.format(i) + ".sa_layer_norm.weight" for i in ls]),
                            "bias": _stack(get, [lp.format(i) + ".sa_layer_norm.bias" for i in ls])},
                "attn": {
                    "wq": _stack(get, [lp.format(i) + ".attention.q_lin.weight" for i in ls], _t),
                    "wk": _stack(get, [lp.format(i) + ".attention.k_lin.weight" for i in ls], _t),
                    "wv": _stack(get, [lp.format(i) + ".attention.v_lin.weight" for i in ls], _t),
                    "wo": _stack(get, [lp.format(i) + ".attention.out_lin.weight" for i in ls], _t),
                    "bq": _stack(get, [lp.format(i) + ".attention.q_lin.bias" for i in ls]),
                    "bk": _stack(get, [lp.format(i) + ".attention.k_lin.bias" for i in ls]),
                    "bv": _stack(get, [lp.format(i) + ".attention.v_lin.bias" for i in ls]),
                    "bo": _stack(get, [lp.format(i) + ".attention.out_lin.bias" for i in ls]),
                },
                "ln_mlp": {"scale": _stack(get, [lp.format(i) + ".output_layer_norm.weight" for i in ls]),
                           "bias": _stack(get, [lp.format(i) + ".output_layer_norm.bias" for i in ls])},
                "mlp": {"w_up": _stack(get, [lp.format(i) + ".ffn.lin1.weight" for i in ls], _t),
                        "b_up": _stack(get, [lp.format(i) + ".ffn.lin1.bias" for i in ls]),
                        "w_down": _stack(get, [lp.format(i) + ".ffn.lin2.weight" for i in ls], _t),
                        "b_down": _stack(get, [lp.format(i) + ".ffn.lin2.bias" for i in ls])},
            },
            # no pooler in distilbert: zero weights make pooled = tanh(0)
            "pooler": {"w": np.zeros((D, D), np.float32),
                       "b": np.zeros((D,), np.float32)},
        }
        try:
            out["mlm"] = {
                "w": _t(raw_get("vocab_transform.weight")),
                "b": np.asarray(raw_get("vocab_transform.bias")),
                "ln": {"scale": np.asarray(raw_get("vocab_layer_norm.weight")),
                       "bias": np.asarray(raw_get("vocab_layer_norm.bias"))},
                "decoder_bias": np.asarray(raw_get("vocab_projector.bias")),
            }
        except KeyError:
            pass  # plain DistilBertModel checkpoint: no fill-mask head
        return out


class BertPolicy(HFPolicy):
    """HF ``bert`` (reference ``containers/bert.py`` HFBertLayerPolicy):
    post-LN encoder with token-type embeddings, optional pooler, optional
    MLM head (``cls.predictions.*``, decoder tied to the word embeddings).
    Serves through the zoo BertModel's fill-mask / feature surface."""

    model_type = "bert"

    _ACTS = {"gelu": "gelu_exact", "gelu_new": "gelu",
             "gelu_pytorch_tanh": "gelu", "relu": "relu"}

    def zoo_config(self, hf):
        pet = hf.get("position_embedding_type", "absolute")
        if pet != "absolute":
            raise ValueError(f"unsupported BERT position_embedding_type {pet!r}")
        act = self._ACTS.get(hf.get("hidden_act", "gelu"))
        if act is None:
            raise ValueError(f"unsupported BERT hidden_act {hf.get('hidden_act')!r}")
        return TransformerConfig(
            vocab_size=hf["vocab_size"], n_layer=hf["num_hidden_layers"],
            n_head=hf["num_attention_heads"], d_model=hf["hidden_size"],
            d_ff=hf["intermediate_size"],
            max_seq=hf.get("max_position_embeddings", 512),
            pos_embedding="learned", norm="layernorm", norm_position="post",
            activation=act, causal=False, attn_bias=True, tie_embeddings=True,
            norm_eps=hf.get("layer_norm_eps", 1e-12))

    def build_model(self, cfg, hf, params):
        from deepspeed_tpu.models.bert import BertConfig, BertModel
        bc = BertConfig(vocab_size=cfg.vocab_size, max_seq=cfg.max_seq,
                        n_layer=cfg.n_layer, n_head=cfg.n_head,
                        d_model=cfg.d_model, d_ff=cfg.d_ff,
                        type_vocab_size=hf.get("type_vocab_size", 2),
                        norm_eps=cfg.norm_eps, activation=cfg.activation)
        return BertModel(bc, with_mlm_head="mlm" in params)

    def map_params(self, raw_get, cfg):
        L, D = cfg.n_layer, cfg.d_model
        ls = range(L)

        def get(name):  # task-head checkpoints carry a "bert." prefix
            try:
                return raw_get("bert." + name)
            except KeyError:
                return raw_get(name)

        lp = "encoder.layer.{}"
        out = {
            "embed": {
                "tokens": np.asarray(get("embeddings.word_embeddings.weight")),
                "positions": np.asarray(get("embeddings.position_embeddings.weight")),
                "token_type": np.asarray(get("embeddings.token_type_embeddings.weight")),
                "ln": {"scale": np.asarray(get("embeddings.LayerNorm.weight")),
                       "bias": np.asarray(get("embeddings.LayerNorm.bias"))},
            },
            "layers": {
                "ln_attn": {"scale": _stack(get, [lp.format(i) + ".attention.output.LayerNorm.weight" for i in ls]),
                            "bias": _stack(get, [lp.format(i) + ".attention.output.LayerNorm.bias" for i in ls])},
                "attn": {
                    "wq": _stack(get, [lp.format(i) + ".attention.self.query.weight" for i in ls], _t),
                    "wk": _stack(get, [lp.format(i) + ".attention.self.key.weight" for i in ls], _t),
                    "wv": _stack(get, [lp.format(i) + ".attention.self.value.weight" for i in ls], _t),
                    "wo": _stack(get, [lp.format(i) + ".attention.output.dense.weight" for i in ls], _t),
                    "bq": _stack(get, [lp.format(i) + ".attention.self.query.bias" for i in ls]),
                    "bk": _stack(get, [lp.format(i) + ".attention.self.key.bias" for i in ls]),
                    "bv": _stack(get, [lp.format(i) + ".attention.self.value.bias" for i in ls]),
                    "bo": _stack(get, [lp.format(i) + ".attention.output.dense.bias" for i in ls]),
                },
                "ln_mlp": {"scale": _stack(get, [lp.format(i) + ".output.LayerNorm.weight" for i in ls]),
                           "bias": _stack(get, [lp.format(i) + ".output.LayerNorm.bias" for i in ls])},
                "mlp": {"w_up": _stack(get, [lp.format(i) + ".intermediate.dense.weight" for i in ls], _t),
                        "b_up": _stack(get, [lp.format(i) + ".intermediate.dense.bias" for i in ls]),
                        "w_down": _stack(get, [lp.format(i) + ".output.dense.weight" for i in ls], _t),
                        "b_down": _stack(get, [lp.format(i) + ".output.dense.bias" for i in ls])},
            },
        }
        try:  # headless / MLM-only checkpoints ship no pooler
            out["pooler"] = {"w": _t(get("pooler.dense.weight")),
                             "b": np.asarray(get("pooler.dense.bias"))}
        except KeyError:
            from deepspeed_tpu.utils.logging import warn_once
            warn_once("BERT checkpoint has no pooler (add_pooling_layer="
                      "False / MLM-only); pooled output will be tanh(0) "
                      "zeros — use the hidden states or the MLM head")
            out["pooler"] = {"w": np.zeros((D, D), np.float32),
                             "b": np.zeros((D,), np.float32)}
        try:
            out["mlm"] = {
                "w": _t(raw_get("cls.predictions.transform.dense.weight")),
                "b": np.asarray(raw_get("cls.predictions.transform.dense.bias")),
                "ln": {"scale": np.asarray(raw_get("cls.predictions.transform.LayerNorm.weight")),
                       "bias": np.asarray(raw_get("cls.predictions.transform.LayerNorm.bias"))},
                "decoder_bias": np.asarray(raw_get("cls.predictions.bias")),
            }
        except KeyError:
            pass  # plain BertModel checkpoint: no fill-mask head
        return out


class CLIPPolicy(HFPolicy):
    """HF ``clip`` / ``clip_text_model`` / ``clip_vision_model`` (reference
    ``containers/clip.py`` HFCLIPLayerPolicy + ``model_implementations/
    transformers/clip_encoder.py``).

    Which towers exist is probed from the checkpoint itself: a full
    ``CLIPModel`` maps to ``DSClipEncoder`` with params
    ``{"text": ..., "vision": ..., ["logit_scale"]}``; a standalone
    ``CLIPTextModel(WithProjection)`` / ``CLIPVisionModel(WithProjection)``
    maps to the bare encoder with its own params tree."""

    model_type = "clip"

    _ACTS = {"quick_gelu": "quick_gelu", "gelu": "gelu_exact",
             "gelu_new": "gelu", "gelu_pytorch_tanh": "gelu"}

    @classmethod
    def _act(cls, sub):
        act = cls._ACTS.get(sub.get("hidden_act", "quick_gelu"))
        if act is None:
            raise ValueError(f"unsupported CLIP hidden_act {sub.get('hidden_act')!r}")
        return act

    @classmethod
    def _text_cfg(cls, tc, projection_dim=None):
        from deepspeed_tpu.models.clip import CLIPTextConfig
        return CLIPTextConfig(
            vocab_size=tc["vocab_size"],
            max_seq=tc.get("max_position_embeddings", 77),
            n_layer=tc["num_hidden_layers"], n_head=tc["num_attention_heads"],
            d_model=tc["hidden_size"], d_ff=tc["intermediate_size"],
            norm_eps=tc.get("layer_norm_eps", 1e-5), activation=cls._act(tc),
            projection_dim=projection_dim,
            eos_token_id=tc.get("eos_token_id", 2))

    @classmethod
    def _vision_cfg(cls, vc, projection_dim=None):
        from deepspeed_tpu.models.clip import CLIPVisionConfig
        return CLIPVisionConfig(
            image_size=vc.get("image_size", 224),
            patch_size=vc.get("patch_size", 32),
            n_layer=vc["num_hidden_layers"], n_head=vc["num_attention_heads"],
            d_model=vc["hidden_size"], d_ff=vc["intermediate_size"],
            norm_eps=vc.get("layer_norm_eps", 1e-5), activation=cls._act(vc),
            projection_dim=projection_dim)

    def zoo_config(self, hf):
        # the text tower governs the TransformerConfig handed to
        # config_overrides (vision dims are consumed by build_model
        # directly); a standalone tower checkpoint carries its fields at the
        # top level — text is recognised by vocab_size, vision by patch_size
        tc = hf.get("text_config")
        vc = hf.get("vision_config")
        if tc is None and vc is None:
            tc = hf if "vocab_size" in hf else None
            vc = hf if tc is None else None
        if tc is not None:
            return self._text_cfg(tc).zoo()
        return self._vision_cfg(vc).zoo()

    def build_model(self, cfg, hf, params):
        from deepspeed_tpu.models.clip import (CLIPTextEncoder,
                                               CLIPVisionEncoder, DSClipEncoder)
        proj = hf.get("projection_dim")
        text = vision = None
        tparams = params.get("text", params if "layers" in params else None)
        if tparams is not None and "embed" in tparams:
            text = CLIPTextEncoder(self._text_cfg(
                hf.get("text_config", hf),
                proj if "text_projection" in tparams else None))
        vparams = params.get("vision", params if "patch_embed" in params else None)
        if vparams is not None and "patch_embed" in vparams:
            vision = CLIPVisionEncoder(self._vision_cfg(
                hf.get("vision_config", hf),
                proj if "visual_projection" in vparams else None))
        if text is not None and vision is not None:
            return DSClipEncoder(text, vision)
        return text if text is not None else vision

    @staticmethod
    def _probe_layers(get, fmt):
        n = 0
        while True:
            try:
                get(fmt.format(n))
            except KeyError:
                return n
            n += 1

    def _map_tower(self, get, pre):
        """One encoder tower (same HF layer schema for text and vision)."""
        lp = pre + "encoder.layers.{}"
        L = self._probe_layers(get, lp + ".layer_norm1.weight")
        ls = range(L)
        return {
            "ln_attn": {"scale": _stack(get, [lp.format(i) + ".layer_norm1.weight" for i in ls]),
                        "bias": _stack(get, [lp.format(i) + ".layer_norm1.bias" for i in ls])},
            "attn": {
                "wq": _stack(get, [lp.format(i) + ".self_attn.q_proj.weight" for i in ls], _t),
                "wk": _stack(get, [lp.format(i) + ".self_attn.k_proj.weight" for i in ls], _t),
                "wv": _stack(get, [lp.format(i) + ".self_attn.v_proj.weight" for i in ls], _t),
                "wo": _stack(get, [lp.format(i) + ".self_attn.out_proj.weight" for i in ls], _t),
                "bq": _stack(get, [lp.format(i) + ".self_attn.q_proj.bias" for i in ls]),
                "bk": _stack(get, [lp.format(i) + ".self_attn.k_proj.bias" for i in ls]),
                "bv": _stack(get, [lp.format(i) + ".self_attn.v_proj.bias" for i in ls]),
                "bo": _stack(get, [lp.format(i) + ".self_attn.out_proj.bias" for i in ls]),
            },
            "ln_mlp": {"scale": _stack(get, [lp.format(i) + ".layer_norm2.weight" for i in ls]),
                       "bias": _stack(get, [lp.format(i) + ".layer_norm2.bias" for i in ls])},
            "mlp": {"w_up": _stack(get, [lp.format(i) + ".mlp.fc1.weight" for i in ls], _t),
                    "b_up": _stack(get, [lp.format(i) + ".mlp.fc1.bias" for i in ls]),
                    "w_down": _stack(get, [lp.format(i) + ".mlp.fc2.weight" for i in ls], _t),
                    "b_down": _stack(get, [lp.format(i) + ".mlp.fc2.bias" for i in ls])},
        }

    def _map_text(self, get):
        pre = "text_model."
        return {
            "embed": {"tokens": np.asarray(get(pre + "embeddings.token_embedding.weight")),
                      "positions": np.asarray(get(pre + "embeddings.position_embedding.weight"))},
            "layers": self._map_tower(get, pre),
            "ln_f": {"scale": np.asarray(get(pre + "final_layer_norm.weight")),
                     "bias": np.asarray(get(pre + "final_layer_norm.bias"))},
        }

    def _map_vision(self, get):
        pre = "vision_model."
        # HF conv patch embed [D, C, ps, ps] -> [ps*ps*C, D], matching the
        # patchify + matmul lowering's (ps_h, ps_w, C) flattening order
        w = np.asarray(get(pre + "embeddings.patch_embedding.weight"))
        D = w.shape[0]
        return {
            "patch_embed": np.ascontiguousarray(
                w.transpose(2, 3, 1, 0).reshape(-1, D)),
            "class_token": np.asarray(get(pre + "embeddings.class_embedding")),
            "positions": np.asarray(get(pre + "embeddings.position_embedding.weight")),
            # sic: HF's attribute really is spelled "pre_layrnorm"
            "ln_pre": {"scale": np.asarray(get(pre + "pre_layrnorm.weight")),
                       "bias": np.asarray(get(pre + "pre_layrnorm.bias"))},
            "layers": self._map_tower(get, pre),
            "ln_f": {"scale": np.asarray(get(pre + "post_layernorm.weight")),
                     "bias": np.asarray(get(pre + "post_layernorm.bias"))},
        }

    def map_params(self, get, cfg):
        def has(name):
            try:
                get(name)
                return True
            except KeyError:
                return False

        has_text = has("text_model.embeddings.token_embedding.weight")
        has_vision = has("vision_model.embeddings.class_embedding")
        if not (has_text or has_vision):
            raise KeyError("neither text_model.* nor vision_model.* weights found")
        if has_text and has_vision:      # full CLIPModel
            out = {"text": self._map_text(get), "vision": self._map_vision(get)}
            if has("text_projection.weight"):
                out["text"]["text_projection"] = _t(get("text_projection.weight"))
            if has("visual_projection.weight"):
                out["vision"]["visual_projection"] = _t(get("visual_projection.weight"))
            if has("logit_scale"):
                out["logit_scale"] = np.asarray(get("logit_scale"))
            return out
        if has_text:                     # CLIPTextModel(WithProjection)
            out = self._map_text(get)
            if has("text_projection.weight"):
                out["text_projection"] = _t(get("text_projection.weight"))
            return out
        out = self._map_vision(get)      # CLIPVisionModel(WithProjection)
        if has("visual_projection.weight"):
            out["visual_projection"] = _t(get("visual_projection.weight"))
        return out


POLICIES: Dict[str, HFPolicy] = {
    p.model_type: p() for p in (GPT2Policy, LlamaPolicy, BloomPolicy, OPTPolicy,
                                GPTNeoXPolicy, GPTJPolicy, GPTNeoPolicy,
                                DistilBertPolicy, BertPolicy, CLIPPolicy)
}
# standalone HF tower checkpoints carry their own model_type strings
POLICIES["clip_text_model"] = POLICIES["clip"]
POLICIES["clip_vision_model"] = POLICIES["clip"]


def policy_for(model_type: str) -> HFPolicy:
    try:
        return POLICIES[model_type]
    except KeyError:
        raise ValueError(
            f"no ingestion policy for HF model_type={model_type!r}; "
            f"supported: {sorted(POLICIES)}") from None
