"""Streaming HF checkpoint loader.

Reference parity: ``deepspeed/module_inject/load_checkpoint.py`` (sharded
checkpoint loading into injected modules) + ``replace_module.py:271``
(policy dispatch by architecture).

Streaming design: multi-file safetensors checkpoints are accessed through a
name -> (file, lazy handle) index; tensors are read on demand with
``safetensors.safe_open`` so at most one assembling parameter stack plus
one shard mapping is resident — the reference's ``sd_loader`` keeps whole
rank files in memory.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


def _read_config(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "config.json")) as f:
        return json.load(f)


class _TensorSource:
    """Lazy name->tensor access over single-file or index-sharded HF
    checkpoints (safetensors preferred, torch .bin supported)."""

    def __init__(self, path: str):
        self.path = path
        self._handles: Dict[str, Any] = {}
        self._torch_cache: Dict[str, Dict[str, np.ndarray]] = {}
        self.name_to_file: Dict[str, str] = {}

        if os.path.isfile(path):
            files = [path]
            self._index_file(path)
        else:
            idx = None
            for cand in ("model.safetensors.index.json", "pytorch_model.bin.index.json"):
                p = os.path.join(path, cand)
                if os.path.exists(p):
                    idx = p
                    break
            if idx is not None:
                with open(idx) as f:
                    weight_map = json.load(f)["weight_map"]
                for name, fname in weight_map.items():
                    self.name_to_file[name] = os.path.join(path, fname)
            else:
                for cand in ("model.safetensors", "pytorch_model.bin"):
                    p = os.path.join(path, cand)
                    if os.path.exists(p):
                        self._index_file(p)
                        break
                else:
                    raise FileNotFoundError(
                        f"no model.safetensors / pytorch_model.bin / *.index.json under {path}")

    def _index_file(self, fpath: str) -> None:
        if fpath.endswith(".safetensors"):
            from safetensors import safe_open
            with safe_open(fpath, framework="numpy") as f:
                for name in f.keys():
                    self.name_to_file[name] = fpath
        else:
            for name in self._torch_file(fpath):
                self.name_to_file[name] = fpath

    def _torch_file(self, fpath: str) -> Dict[str, np.ndarray]:
        if fpath not in self._torch_cache:
            from deepspeed_tpu.checkpoint.state_dict_factory import _load_torch_file
            self._torch_cache = {fpath: _load_torch_file(fpath)}  # keep ONE file
        return self._torch_cache[fpath]

    def __contains__(self, name: str) -> bool:
        return name in self.name_to_file

    def get(self, name: str) -> np.ndarray:
        fpath = self.name_to_file.get(name)
        if fpath is None:
            raise KeyError(name)
        if fpath.endswith(".safetensors"):
            from safetensors import safe_open
            h = self._handles.get(fpath)
            if h is None:
                h = self._handles[fpath] = safe_open(fpath, framework="numpy")
            t = h.get_tensor(name)
            if t.dtype == np.uint16:  # bf16 riding as raw uint16
                import ml_dtypes
                t = t.view(ml_dtypes.bfloat16)
            return np.asarray(t)
        return self._torch_file(fpath)[name]


def load_hf_checkpoint(path: str, model_type: Optional[str] = None,
                       dtype=np.float32, config_overrides: Optional[Dict] = None
                       ) -> Tuple[Any, Dict]:
    """Load an HF checkpoint directory (or single weights file + config.json
    next to it) into ``(CausalLM, params)``.

    ``model_type`` defaults to ``config.json``'s. Weights stream shard by
    shard via the name index. ``config_overrides`` tweak the zoo config
    (e.g. ``{"remat": "dots"}``)."""
    from deepspeed_tpu.models import CausalLM
    from deepspeed_tpu.module_inject.policies import policy_for

    d = path if os.path.isdir(path) else os.path.dirname(path)
    hf_cfg = _read_config(d)
    mt = model_type or hf_cfg.get("model_type")
    policy = policy_for(mt)
    cfg = policy.zoo_config(hf_cfg)
    if config_overrides:
        import dataclasses
        cfg = dataclasses.replace(cfg, **config_overrides)

    src = _TensorSource(path)

    def get(name: str) -> np.ndarray:
        a = src.get(name)
        return np.asarray(a, dtype=dtype) if a.dtype != dtype else a

    params = policy.map_params(get, cfg)
    params = _jnp_tree(params)
    model = policy.build_model(cfg, hf_cfg, params)
    if model is None:
        model = CausalLM(cfg)
    return model, params


def _jnp_tree(tree):
    import jax.numpy as jnp
    import jax
    return jax.tree.map(jnp.asarray, tree)
