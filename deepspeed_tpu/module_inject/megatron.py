"""Megatron-GPT ds-inference checkpoint ingestion.

Reference parity: ``deepspeed/module_inject/containers/megatron_gpt.py``
(MegatronLayerPolicy) + ``deepspeed/runtime/state_dict_factory.py``
(``MegatronSDLoader`` — per-TP-rank file merge with version-aware fused-qkv
handling) + the ds_inference meta-json checkpoint branch
(``deepspeed/inference/engine.py:354-419``).

Flow: the meta json lists per-TP-rank files → :class:`MegatronSDLoader`
merges them (qkv-aware, ``checkpoint/state_dict_factory.py``) → this module
maps Megatron tensor names to the zoo layout for the model's
``TransformerConfig``. The fused qkv layout depends on the checkpoint
version (reference ``merge_query_key_value`` doc):

- v0:   ``[3·np·hn, h]`` — after the loader's qkv-aware merge the full
  tensor is ``[q | k | v]`` block-concat;
- v1.0: ``[np·hn·3, h]`` — per head, per head-dim, (q,k,v) interleaved;
- v2.0: ``[np·3·hn, h]`` — per head ``[q_h | k_h | v_h]`` (NeoX-style).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

def megatron_merge_strategies(version=0) -> Dict[str, Any]:
    """Per-tensor TP merge strategy (Megatron column-parallel weights shard
    dim 0 in the torch [out, in] layout, row-parallel dim 1, vocab-parallel
    embedding dim 0; row-parallel biases and layernorms replicate).

    Fused qkv: version 0 ranks hold contiguous ``[q_i | k_i | v_i]`` blocks,
    so the merge must be q/k/v-aware; v1.0/v2.0 lay q/k/v out per HEAD, so
    rank shards concat plainly (reference ``merge_query_key_value``).
    """
    qkv = (0, "qkv") if version == 0 else 0
    return {
        "attention.query_key_value.weight": qkv,
        "attention.query_key_value.bias": qkv,
        "attention.dense.weight": 1,
        # no "mlp." prefix: the same column/row-parallel split applies to the
        # dense mlp AND the MoE expert FFNs
        # (...mlp.deepspeed_moe.experts.deepspeed_experts.{e}.dense_h_to_4h...)
        "dense_h_to_4h.weight": 0,
        "dense_h_to_4h.bias": 0,
        "dense_4h_to_h.weight": 1,
        "word_embeddings.weight": 0,
    }


def _split_fused_qkv(w3, H: int, Hd: int, version) -> tuple:
    """Version-aware de-fuse of a MERGED qkv tensor (weight [3D, D] or bias
    [3D]) into (q, k, v), each transposed to the zoo's [in, out] layout."""
    D3 = w3.shape[0]
    D = D3 // 3
    if version == 0:
        q, k, v = np.split(w3, 3, axis=0)                  # [q | k | v]
    elif float(version) == 1.0:
        r = w3.reshape((H, Hd, 3) + w3.shape[1:])          # per-dim triples
        q, k, v = (r[:, :, i].reshape((D,) + w3.shape[1:]) for i in range(3))
    elif float(version) == 2.0:
        r = w3.reshape((H, 3, Hd) + w3.shape[1:])          # per-head blocks
        q, k, v = (r[:, i].reshape((D,) + w3.shape[1:]) for i in range(3))
    else:
        raise ValueError(f"unsupported Megatron checkpoint version {version!r}")
    if w3.ndim == 2:  # torch [out, in] -> zoo [in, out]
        q, k, v = q.T, k.T, v.T
    return (np.ascontiguousarray(q), np.ascontiguousarray(k),
            np.ascontiguousarray(v))


def map_megatron_params(sd: Dict[str, np.ndarray], cfg, version=0) -> Dict[str, Any]:
    """Merged Megatron-GPT state dict → zoo params for ``cfg``."""
    def g(name):
        for pre in ("", "module.", "model.", "language_model."):
            if pre + name in sd:
                return np.asarray(sd[pre + name])
        # embedding/transformer scoping variants
        for pre in ("language_model.embedding.", "embedding."):
            if pre + name in sd:
                return np.asarray(sd[pre + name])
        raise KeyError(name)

    L, H, Hd = cfg.n_layer, cfg.n_head, cfg.head_dim
    lp = None
    for cand in ("transformer.layers", "language_model.transformer.layers",
                 "encoder.layers", "language_model.encoder.layers"):
        if any(k.startswith(cand) or k.startswith("module." + cand) for k in sd):
            lp = cand
            break
    if lp is None:
        raise KeyError("no Megatron transformer layers found in state dict")

    def t(a):
        return np.ascontiguousarray(np.asarray(a).T)

    def stack(fmt, tr=False):
        return np.stack([(t(g(fmt.format(i))) if tr else np.asarray(g(fmt.format(i))))
                         for i in range(L)])

    qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
    for i in range(L):
        a, b, c = _split_fused_qkv(
            g(f"{lp}.{i}.attention.query_key_value.weight"), H, Hd, version)
        qw.append(a); kw.append(b); vw.append(c)
        a, b, c = _split_fused_qkv(
            g(f"{lp}.{i}.attention.query_key_value.bias"), H, Hd, version)
        qb.append(a); kb.append(b); vb.append(c)

    fl = "final_layernorm"
    for cand in (f"{lp.rsplit('.layers', 1)[0]}.final_layernorm",):
        try:
            g(cand + ".weight")
            fl = cand
            break
        except KeyError:
            pass

    # Megatron-DeepSpeed MoE layers (reference policy
    # module_inject/containers/megatron_gpt_moe.py:57-82 'standard' type):
    # per-layer gate ``mlp.deepspeed_moe.gate.wg`` and experts
    # ``mlp.deepspeed_moe.experts.deepspeed_experts.{e}.dense_{h_to_4h,4h_to_h}``
    # → zoo MoE layout [L, E, ...] (every layer must be MoE; the zoo model
    # has no mixed dense/MoE stacking)
    # standard MoE nests under mlp.deepspeed_moe; residual (PR-)MoE under
    # mlp.moe.deepspeed_moe with a dense mlp.mlp branch + mlp.coefficient
    # (reference megatron_gpt_moe.py:57-82 moe_type dispatch)
    is_residual = any(".mlp.moe.deepspeed_moe." in k for k in sd)
    is_moe = is_residual or any(".mlp.deepspeed_moe." in k for k in sd)
    if is_moe:
        moe_root = "mlp.moe.deepspeed_moe" if is_residual else "mlp.deepspeed_moe"
        ex = f"{lp}.{{}}.{moe_root}.experts.deepspeed_experts.{{}}"

        def has_expert(i):
            try:
                g(ex.format(i, 0) + ".dense_h_to_4h.weight")
                return True
            except KeyError:
                return False

        dense_layers = [i for i in range(L) if not has_expert(i)]
        if dense_layers:
            # e.g. Megatron-DeepSpeed --moe-layer-freq 2 alternating stacking
            raise NotImplementedError(
                f"mixed dense/MoE layer stacking is not supported (layers "
                f"{dense_layers} of {L} have no deepspeed_moe experts, e.g. "
                "a --moe-layer-freq > 1 checkpoint); the zoo MoECausalLM "
                "stacks an MoE MLP in every layer")
        E = 0
        while True:
            try:
                g(ex.format(0, E) + ".dense_h_to_4h.weight")
                E += 1
            except KeyError:
                break

        def estack(suffix, tr=False):
            # [L, E, ...]; missing expert keys on ANY layer raise loudly
            return np.stack([
                np.stack([(t(g(ex.format(i, e) + suffix)) if tr
                           else np.asarray(g(ex.format(i, e) + suffix)))
                          for e in range(E)])
                for i in range(L)])

        mlp = {
            # torch Linear wg [E, D] → gate_w [D, E]
            "gate_w": stack(lp + ".{}." + moe_root + ".gate.wg.weight", tr=True),
            "w_up": estack(".dense_h_to_4h.weight", tr=True),
            "b_up": estack(".dense_h_to_4h.bias"),
            "w_down": estack(".dense_4h_to_h.weight", tr=True),
            "b_down": estack(".dense_4h_to_h.bias"),
        }
        if is_residual:
            mlp.update({
                "res_w_up": stack(lp + ".{}.mlp.mlp.dense_h_to_4h.weight", tr=True),
                "res_b_up": stack(lp + ".{}.mlp.mlp.dense_h_to_4h.bias"),
                "res_w_down": stack(lp + ".{}.mlp.mlp.dense_4h_to_h.weight", tr=True),
                "res_b_down": stack(lp + ".{}.mlp.mlp.dense_4h_to_h.bias"),
                "coef_w": stack(lp + ".{}.mlp.coefficient.weight", tr=True),
                "coef_b": stack(lp + ".{}.mlp.coefficient.bias"),
            })
    else:
        mlp = {"w_up": stack(lp + ".{}.mlp.dense_h_to_4h.weight", tr=True),
               "b_up": stack(lp + ".{}.mlp.dense_h_to_4h.bias"),
               "w_down": stack(lp + ".{}.mlp.dense_4h_to_h.weight", tr=True),
               "b_down": stack(lp + ".{}.mlp.dense_4h_to_h.bias")}

    return {
        "embed": {"tokens": np.asarray(g("word_embeddings.weight")),
                  "positions": np.asarray(g("position_embeddings.weight"))},
        "layers": {
            "ln_attn": {"scale": stack(lp + ".{}.input_layernorm.weight"),
                        "bias": stack(lp + ".{}.input_layernorm.bias")},
            "attn": {"wq": np.stack(qw), "wk": np.stack(kw), "wv": np.stack(vw),
                     "bq": np.stack(qb), "bk": np.stack(kb), "bv": np.stack(vb),
                     "wo": stack(lp + ".{}.attention.dense.weight", tr=True),
                     "bo": stack(lp + ".{}.attention.dense.bias")},
            "ln_mlp": {"scale": stack(lp + ".{}.post_attention_layernorm.weight"),
                       "bias": stack(lp + ".{}.post_attention_layernorm.bias")},
            "mlp": mlp,
        },
        "ln_f": {"scale": np.asarray(g(fl + ".weight")),
                 "bias": np.asarray(g(fl + ".bias"))},
    }


def load_megatron_checkpoint(ckpt_json, cfg, quantize: bool = False,
                             quantize_bits: int = 8, quantize_groups: int = 64,
                             mlp_extra_grouping: bool = True) -> Dict[str, Any]:
    """ds_inference meta json (``{"type": "Megatron", "checkpoints": [...],
    "version": V}``) → zoo params for the model config ``cfg``.

    ``quantize`` flags mirror the reference SD loader's quantize-on-load
    surface; quantization runs AFTER name-mapping so the per-group scales
    line up with the zoo's [in, out] layout (see runtime/weight_quantizer)."""
    from deepspeed_tpu.checkpoint.state_dict_factory import SDLoaderFactory

    sd_type, paths, version = SDLoaderFactory.get_sd_loader_json(ckpt_json)
    if str(sd_type).lower() not in ("megatron", "ds_model"):
        raise ValueError(f"unsupported ds_inference checkpoint type {sd_type!r}")
    loader = SDLoaderFactory.get_sd_loader(paths, sd_type, version)
    merged = loader.load(mp_world_size=1,
                         merge_strategies=megatron_merge_strategies(version))
    params = map_megatron_params(merged, cfg, version=version)
    if quantize:
        from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization
        wq = WeightQuantization(mlp_extra_grouping=mlp_extra_grouping)
        params = wq.quantize_params(params, quantize_bits, quantize_groups,
                                    include_head=not cfg.tie_embeddings)
    return params
