"""HF-checkpoint ingestion: per-architecture policies + shard streaming.

Reference parity: ``deepspeed/module_inject/`` — ``replace_module.py:271``
(per-architecture policy dispatch), ``containers/*.py`` (gpt2, gptneox,
opt, bloom, llama parameter containers), ``load_checkpoint.py`` (sharded
checkpoint loading into the injected modules).

TPU redesign: instead of monkey-patching ``nn.Module`` trees, a policy maps
HF tensor *names* to the zoo's stacked-layer pytree layout (weights arrive
in [L, in, out] orientation, fused qkv de-interleaved per head), and the
loader streams multi-file safetensors/torch checkpoints shard by shard so
only one HF shard plus the assembling parameter is resident at a time.
"""

from deepspeed_tpu.module_inject.loader import load_hf_checkpoint
from deepspeed_tpu.module_inject.policies import POLICIES, policy_for

__all__ = ["load_hf_checkpoint", "POLICIES", "policy_for"]
