"""TPU-native autotuner.

Reference parity: ``deepspeed/autotuning/autotuner.py:39`` (``Autotuner``:
model-info profiling, ZeRO-stage x micro-batch tuning spaces generated from
``config_templates/``, grid/random tuners with early stopping, experiment
scheduler, ``ds_config_optimal.json`` output) and ``tuner/base_tuner.py``.

TPU redesign (not a port): the reference must *launch* each experiment to
discover whether it OOMs — its scheduler, resource manager, and exps/
directories exist to manage those processes. On TPU/XLA the compiled program
declares its exact memory up front, so:

- phase 1 **static prune**: AOT-compile each candidate (``jit -> lower ->
  compile``) against abstract inputs and read ``memory_analysis()``;
  candidates whose live bytes exceed the HBM budget are discarded without
  running a step. ZeRO sharding divides the state bytes analytically.
- phase 2 **measure**: survivors run ``end_profile_step`` real steps through
  ``deepspeed_tpu.initialize``; the tuner (grid or random, with
  early-stopping) ranks by throughput (tokens/s) or latency and writes
  ``ds_config_optimal.json`` + ``autotuning_results.json``.

The search axes extend the reference's (stage, micro-batch) with the TPU
memory policies that matter here: remat policy and loss-chunk size.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.autotuning.config import (AutotuningConfig, METRIC_LATENCY,
                                             METRIC_THROUGHPUT,
                                             TUNER_MODELBASED, TUNER_RANDOM)
from deepspeed_tpu.utils.logging import logger

_GIB = 1024**3


@dataclasses.dataclass
class Candidate:
    stage: int
    micro_batch: int
    remat: Any
    loss_chunk: int
    # None = keep the model's setting (dimension not searched)
    scan_layers: Any = None
    # 0 = kernel-default flash blocks; else attn_block_q == attn_block_k
    attn_block: int = 0

    def config_overrides(self) -> Dict[str, Any]:
        return {
            "train_micro_batch_size_per_gpu": self.micro_batch,
            "zero_optimization": {"stage": self.stage},
        }

    def apply_to(self, base: Dict[str, Any]) -> Dict[str, Any]:
        """Merged config with the batch triad made consistent: the tuned
        micro-batch wins; a pinned train_batch_size would otherwise trip the
        triad assertion for most candidates."""
        cfg = _merge(dict(base), self.config_overrides())
        cfg.pop("train_batch_size", None)
        return cfg

    def name(self) -> str:
        n = f"z{self.stage}_mbs{self.micro_batch}_remat-{self.remat}_chunk{self.loss_chunk}"
        if self.scan_layers is not None:
            n += f"_scan{int(bool(self.scan_layers))}"
        if self.attn_block:
            n += f"_blk{self.attn_block}"
        return n

    def model_override_extras(self, model_cfg) -> Dict[str, Any]:
        """The optional model-config overrides this candidate carries, keyed
        by the dataclass fields the model actually has — the single source
        for both the measured variant and ds_config_optimal.json."""
        extra: Dict[str, Any] = {}
        if self.scan_layers is not None and hasattr(model_cfg, "scan_layers"):
            extra["scan_layers"] = bool(self.scan_layers)
        if self.attn_block and hasattr(model_cfg, "attn_block_q"):
            extra["attn_block_q"] = self.attn_block
            extra["attn_block_k"] = self.attn_block
        return extra


@dataclasses.dataclass
class Record:
    candidate: Candidate
    pruned: bool
    est_bytes: int
    metric_val: Optional[float] = None  # tokens/s (throughput) or s/step (latency)


class Autotuner:
    """Search (zero stage, micro-batch, remat policy, loss chunk) for a model.

    ``model``: a zoo model (``CausalLM``-like: ``.config`` dataclass with
    ``remat``/``loss_chunk`` fields, ``.loss``, ``.init_params``) or any
    ``loss_fn(params, batch)`` — plain callables tune stage x micro-batch
    only. ``batch_fn(mbs) -> batch pytree`` supplies one micro-batch; zoo
    causal LMs get a synthetic-token default.
    """

    def __init__(self, model, model_parameters=None, base_config: Optional[Dict] = None,
                 autotuning_config: Optional[AutotuningConfig] = None,
                 batch_fn: Optional[Callable[[int], Any]] = None,
                 seq_len: Optional[int] = None):
        self.model = model
        self.base_config = dict(base_config or {})
        at = dict(self.base_config.get("autotuning", {}))
        at.pop("enabled", None)
        self.config = autotuning_config or AutotuningConfig(**at)
        self.params = (model_parameters if model_parameters is not None
                       else model.init_params(jax.random.key(0)))
        self._records: List[Record] = []

        mcfg = getattr(model, "config", None)
        self._tunable_model = (mcfg is not None and dataclasses.is_dataclass(mcfg)
                               and hasattr(mcfg, "remat") and hasattr(mcfg, "loss_chunk"))
        self.seq_len = seq_len or (getattr(mcfg, "max_seq", None) or 128)
        self.vocab = getattr(mcfg, "vocab_size", 32000)
        self.batch_fn = batch_fn or self._default_batch_fn

    # ------------------------------------------------------------------ #

    def _default_batch_fn(self, mbs: int):
        rng = np.random.default_rng(0)
        return {"input_ids": rng.integers(0, self.vocab, size=(mbs, self.seq_len)).astype(np.int32)}

    def _variant(self, cand: Candidate):
        """Model with the candidate's remat/loss_chunk (and, when searched,
        scan_layers / flash block) applied."""
        if not self._tunable_model:
            return self.model
        remat = {"none": False, "full": True}.get(cand.remat, cand.remat)
        cfg = dataclasses.replace(self.model.config, remat=remat,
                                  loss_chunk=cand.loss_chunk,
                                  **cand.model_override_extras(self.model.config))
        return type(self.model)(cfg)

    def _loss_fn(self, model):
        return model.loss if hasattr(model, "loss") else model

    # --------------------------- phase 1: prune --------------------------- #

    def hbm_budget(self) -> int:
        if self.config.hbm_budget_bytes:
            return int(self.config.hbm_budget_bytes * self.config.hbm_fraction)
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            limit = stats.get("bytes_limit")
            if limit:
                return int(limit * self.config.hbm_fraction)
        except Exception:  # pragma: no cover - device-dependent
            pass
        return int(16 * _GIB * self.config.hbm_fraction)

    def _shard_factor(self, stage: int, what: str) -> int:
        """How many ways ZeRO divides this state class at a given stage.
        Data-parallel world size from the base config mesh (defaults to 1)."""
        mesh_axes = self.base_config.get("mesh") or {}
        dp = 1
        for ax in ("dp", "fsdp"):
            v = mesh_axes.get(ax, 1)
            if v and v > 0:
                dp *= v
        if dp <= 1:
            dp = 1
        gates = {"master_opt": 1, "grads": 2, "params": 3}
        return dp if stage >= gates[what] else 1

    def estimate_bytes(self, cand: Candidate) -> int:
        """Live bytes for one train step: analytic state bytes (with ZeRO
        shard division) + compiled activation temps from AOT memory analysis."""
        model = self._variant(cand)
        loss_fn = self._loss_fn(model)
        psize = sum(a.size for a in jax.tree.leaves(self.params))

        n_param_bytes = 2 * psize      # bf16 compute params
        n_master_bytes = 4 * psize     # fp32 master
        n_opt_bytes = 8 * psize        # adam m+v fp32
        n_grad_bytes = 4 * psize       # fp32 grads
        state = (n_param_bytes // self._shard_factor(cand.stage, "params")
                 + (n_master_bytes + n_opt_bytes) // self._shard_factor(cand.stage, "master_opt")
                 + n_grad_bytes // self._shard_factor(cand.stage, "grads"))

        abstract_params = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, jnp.bfloat16), self.params)
        batch = jax.tree.map(lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape, np.asarray(a).dtype),
                             self.batch_fn(cand.micro_batch))
        compiled = jax.jit(jax.grad(lambda p, b: self._loss_fn(model)(p, b))).lower(
            abstract_params, batch).compile()
        temps = compiled.memory_analysis().temp_size_in_bytes
        return state + temps

    def prune(self, cand: Candidate) -> Tuple[bool, int]:
        """(fits, estimated_bytes). Compile failures count as pruned."""
        try:
            est = self.estimate_bytes(cand)
        except Exception as e:  # noqa: BLE001 - any compile failure = unusable config
            logger.warning(f"autotuning: {cand.name()} failed to compile ({e}); pruned")
            return False, 1 << 62
        return est <= self.hbm_budget(), est

    # -------------------------- phase 2: measure -------------------------- #

    def measure(self, cand: Candidate) -> float:
        """Run the candidate through the real engine; returns the metric
        (tokens/s for throughput, s/step for latency)."""
        import deepspeed_tpu

        model = self._variant(cand)
        config = cand.apply_to(self.base_config)
        config.setdefault("steps_per_print", 0)
        config.pop("autotuning", None)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=jax.tree.map(jnp.asarray, self.params),
            config=config)
        batch = self.batch_fn(cand.micro_batch)
        gas = engine.gradient_accumulation_steps()
        dp = max(1, engine.train_batch_size() // max(1, engine.train_micro_batch_size_per_gpu() * gas))
        full = jax.tree.map(lambda x: np.concatenate([x] * (gas * dp), axis=0), batch)

        warm = self.config.start_profile_step
        steps = max(1, self.config.end_profile_step - warm)
        for _ in range(max(1, warm)):
            loss = engine.train_batch(full)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(full)
        float(loss)
        dt = (time.perf_counter() - t0) / steps
        tokens = cand.micro_batch * self.seq_len * gas * dp
        return (tokens / dt) if self.config.metric == METRIC_THROUGHPUT else dt

    # ------------------------------ search ------------------------------ #

    def _mbs_list(self) -> List[int]:
        lo = self.config.min_train_micro_batch_size_per_gpu
        hi = self.config.max_train_micro_batch_size_per_gpu or max(lo, 512)
        out, m = [], lo
        while m <= hi:
            out.append(m)
            m *= 2
        return out

    def candidates(self) -> List[Candidate]:
        remats = ["none"] if self.config.fast or not self._tunable_model \
            else list(self.config.remat_policies)
        chunks = [0] if self.config.fast or not self._tunable_model \
            else list(self.config.loss_chunks)
        if len(chunks) > 1:
            # loss_chunk only matters on the XLA streaming path: with the
            # fused Pallas CE kernel FORCED on, the chunk values produce
            # byte-identical programs and the axis would silently multiply
            # the grid (see config.py tuner_num_trials note) for
            # meaningless candidates. "auto" keeps the axis: whether the
            # kernel engages there depends on the TRIAL's mesh/backend
            # (each trial builds its own mesh), which plan time cannot see.
            mcfg = getattr(self.model, "config", None)
            if getattr(mcfg, "fused_cross_entropy", None) == "on":
                chunks = [0]
        scans = [None] if self.config.fast or not self._tunable_model \
            or not hasattr(getattr(self.model, "config", None), "scan_layers") \
            else list(self.config.scan_layers_options)
        blocks = [0] if self.config.fast or not self._tunable_model \
            or not hasattr(getattr(self.model, "config", None), "attn_block_q") \
            else list(self.config.attn_blocks)
        cands = [Candidate(stage=s, micro_batch=m, remat=r, loss_chunk=c,
                           scan_layers=sc, attn_block=b)
                 for s in self.config.zero_stages
                 for m in self._mbs_list()
                 for r in remats
                 for c in chunks
                 for sc in scans
                 for b in blocks]
        if self.config.tuner_type == TUNER_RANDOM and len(cands) > self.config.tuner_num_trials:
            cands = random.Random(0).sample(cands, self.config.tuner_num_trials)
        # gridsearch is NOT truncated by tuner_num_trials — a stage-major cut
        # would silently drop whole ZeRO stages; early stopping bounds work
        return cands

    def tune(self) -> Dict[str, Any]:
        """Run the search; returns the optimal merged config dict and writes
        ``ds_config_optimal.json`` / ``autotuning_results.json``."""
        budget = self.hbm_budget()
        logger.info(f"autotuning: HBM budget {budget / _GIB:.2f} GiB, "
                    f"metric={self.config.metric}, tuner={self.config.tuner_type}")

        if self.config.tuner_type == TUNER_MODELBASED:
            best = self._search_model_based()
        else:
            best = self._search_sequential()

        if best is None:
            raise RuntimeError("autotuning: no candidate fit the memory budget")
        optimal = self.optimal_config(best.candidate)
        self._write_results(optimal)
        return optimal

    def _prune_record(self, cand: Candidate) -> Record:
        fits, est = self.prune(cand)
        rec = Record(candidate=cand, pruned=not fits, est_bytes=est)
        self._records.append(rec)
        if not fits:
            logger.info(f"autotuning: prune {cand.name()} "
                        f"(~{est / _GIB:.2f} GiB > budget)")
        return rec

    def _measure_record(self, rec: Record) -> bool:
        """Measure one survivor in place; False (and ``pruned``) on failure."""
        try:
            rec.metric_val = self.measure(rec.candidate)
        except Exception as e:  # noqa: BLE001 - record + keep searching
            logger.warning(f"autotuning: {rec.candidate.name()} failed to run "
                           f"({e}); skipped")
            rec.pruned = True
            return False
        logger.info(f"autotuning: {rec.candidate.name()} -> {rec.metric_val:.1f} "
                    f"({self.config.metric})")
        return True

    def _search_sequential(self) -> Optional[Record]:
        """Grid/random order: prune + measure candidates as they come."""
        best: Optional[Record] = None
        stale = 0
        for cand in self.candidates():
            rec = self._prune_record(cand)
            if rec.pruned or not self._measure_record(rec):
                continue
            if best is None or self._better(rec.metric_val, best.metric_val):
                best, stale = rec, 0
            else:
                stale += 1
                if stale >= self.config.tuner_early_stopping:
                    logger.info("autotuning: early stopping")
                    break
        return best

    def _search_model_based(self) -> Optional[Record]:
        """Cost-model-steered measure order (reference
        ``autotuning/tuner/model_based_tuner.py`` capability): AOT-prune the
        whole space, measure a few spread-out seeds, then repeatedly fit the
        model on everything measured and measure the best-predicted
        survivor next — reaching the winner in fewer measured trials than
        walking the grid."""
        from deepspeed_tpu.autotuning.cost_model import CostModel, featurize

        survivors = [r for r in (self._prune_record(c) for c in self.candidates())
                     if not r.pruned]
        if not survivors:
            return None

        best: Optional[Record] = None

        def run(rec: Record) -> bool:
            nonlocal best
            if not self._measure_record(rec):
                return False
            if best is None or self._better(rec.metric_val, best.metric_val):
                best = rec
                return True
            return False

        n_seed = min(self.config.tuner_num_seed_trials, len(survivors))
        seed_idx = sorted({round(i * (len(survivors) - 1) / max(1, n_seed - 1))
                           for i in range(n_seed)})
        for i in seed_idx:
            run(survivors[i])

        model = CostModel()
        stale, trials = 0, sum(r.metric_val is not None for r in survivors)
        while trials < self.config.tuner_num_trials:
            done = [r for r in survivors if r.metric_val is not None]
            pending = [r for r in survivors
                       if r.metric_val is None and not r.pruned]
            if not done or not pending:
                break
            model.fit([featurize(r.candidate, r.est_bytes) for r in done],
                      [r.metric_val for r in done])
            preds = model.predict([featurize(r.candidate, r.est_bytes)
                                   for r in pending])
            pick = int(np.argmax(preds) if self.config.metric == METRIC_THROUGHPUT
                       else np.argmin(preds))
            improved = run(pending[pick])
            trials += 1
            stale = 0 if improved else stale + 1
            if stale >= self.config.tuner_early_stopping:
                logger.info("autotuning: early stopping (model-based)")
                break
        return best

    def _better(self, a: float, b: float) -> bool:
        return a > b if self.config.metric == METRIC_THROUGHPUT else a < b

    def optimal_config(self, cand: Candidate) -> Dict[str, Any]:
        cfg = cand.apply_to(self.base_config)
        cfg.pop("autotuning", None)
        if self._tunable_model:
            cfg["model_overrides"] = {"remat": cand.remat, "loss_chunk": cand.loss_chunk,
                                      **cand.model_override_extras(self.model.config)}
        return cfg

    def _write_results(self, optimal: Dict[str, Any]) -> None:
        d = self.config.results_dir
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "ds_config_optimal.json"), "w") as f:
            json.dump(optimal, f, indent=2)
        rows = [{"candidate": dataclasses.asdict(r.candidate), "pruned": r.pruned,
                 "est_bytes": int(r.est_bytes), "metric": r.metric_val}
                for r in self._records]
        with open(os.path.join(d, "autotuning_results.json"), "w") as f:
            json.dump({"metric": self.config.metric, "records": rows}, f, indent=2)

    @property
    def records(self) -> List[Record]:
        return self._records


def _merge(base: Dict, over: Dict) -> Dict:
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            base[k] = _merge(dict(base[k]), v)
        else:
            base[k] = v
    return base


def autotune(model, model_parameters=None, config: Optional[Dict] = None, **kw) -> Dict[str, Any]:
    """One-call tuning: returns the optimal config dict (reference
    ``deepspeed.autotuner`` CLI flow as a library call)."""
    return Autotuner(model, model_parameters, config, **kw).tune()
