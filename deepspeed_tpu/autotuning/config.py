"""``"autotuning"`` config section.

Reference parity: ``deepspeed/autotuning/config.py``
(``DeepSpeedAutotuningConfig``) and ``constants.py`` — same key names where
the concept carries over (enabled/fast/metric/tuner_type/num_trials/
early-stopping/mbs bounds/results_dir), plus the TPU-native search axes
(remat policies, loss-chunk sizes) the reference does not have.
"""

from __future__ import annotations

from typing import List, Optional

from pydantic import Field

from deepspeed_tpu.config.config_utils import ConfigModel

AUTOTUNING = "autotuning"

METRIC_THROUGHPUT = "throughput"
METRIC_LATENCY = "latency"

TUNER_GRIDSEARCH = "gridsearch"
TUNER_RANDOM = "random"
TUNER_MODELBASED = "model_based"


class AutotuningConfig(ConfigModel):
    enabled: bool = False
    fast: bool = True                      # fast mode: micro-batch only, fixed policies
    metric: str = Field(METRIC_THROUGHPUT, pattern="^(throughput|latency)$")
    tuner_type: str = Field(TUNER_GRIDSEARCH,
                            pattern="^(gridsearch|random|model_based)$")
    # model_based: how many spread-out survivors seed the cost model before
    # prediction starts steering the measure order
    tuner_num_seed_trials: int = Field(3, ge=1)
    # trial cap for random/model_based tuners. gridsearch deliberately
    # IGNORES it (a stage-major cut would drop whole ZeRO stages) and
    # measures the full cross product zero_stages × micro-batches ×
    # remat_policies × loss_chunks × scan_layers_options × attn_blocks —
    # every extra option in any axis MULTIPLIES wall-time, so widen one
    # axis at a time (early stopping only bounds the tail, not the grid)
    tuner_num_trials: int = Field(50, ge=1)
    tuner_early_stopping: int = Field(5, ge=1)
    results_dir: str = "autotuning_results"
    overwrite: bool = True

    # measurement window (reference start/end_profile_step)
    start_profile_step: int = Field(2, ge=0)
    end_profile_step: int = Field(6, ge=1)

    # search-space bounds
    min_train_micro_batch_size_per_gpu: int = Field(1, ge=1)
    max_train_micro_batch_size_per_gpu: Optional[int] = None  # None = probe upward
    zero_stages: List[int] = [1, 2, 3]
    remat_policies: List[str] = ["none", "dots", "selective", "full"]
    loss_chunks: List[int] = [0, 2048]
    # layer-stacking search: the default [None] keeps the model's setting
    # out of the grid — searching it DOUBLES every gridsearch (see
    # tuner_num_trials above), which silently doubled wall-time for every
    # tunable model when [True, False] was the default. Opt in with
    # [True, False] to re-discover the chip-measured ~12% unrolled win.
    scan_layers_options: List = [None]
    # flash-attention block override candidates (0 = the kernel's default);
    # e.g. [0, 512, 1024] re-discovers the measured 1024-block win at S=2048
    attn_blocks: List[int] = [0]

    # per-device HBM budget for the static prune; None = ask the device,
    # fall back to 16 GiB
    hbm_budget_bytes: Optional[int] = None
    # fraction of the budget usable by one step's live buffers (leaves room
    # for fragmentation + runtime overheads)
    hbm_fraction: float = Field(0.9, gt=0, le=1)
