"""Learned cost model steering the autotuner's measure phase.

Reference parity: ``deepspeed/autotuning/tuner/model_based_tuner.py`` +
``tuner/cost_model.py`` — the reference fits an XGBoost regressor over
measured experiments and measures the best-predicted config next.

TPU redesign: the search space here is small and smooth (stage,
log-micro-batch, remat policy, loss chunk), so a ridge-regularised linear
least-squares model over ordinal features gives the same
predict-then-measure loop with zero extra dependencies; the static AOT
memory prune has already removed every config the reference's model would
have had to learn to avoid.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

_REMAT_ORD = {"none": 0.0, False: 0.0, "dots": 1.0, "selective": 2.0,
              "full": 3.0, True: 3.0}


def featurize(cand, est_bytes: int) -> List[float]:
    """Ordinal feature vector for one candidate (bias term included)."""
    return [
        1.0,
        float(cand.stage),
        float(np.log2(max(1, cand.micro_batch))),
        _REMAT_ORD.get(cand.remat, 1.5),
        float(np.log2(cand.loss_chunk + 1)),
        est_bytes / float(1024**3),
        # scan_layers: None (not searched) sits between True/False so the
        # model stays indifferent until the dimension is actually in play
        0.5 if getattr(cand, "scan_layers", None) is None
        else float(bool(cand.scan_layers)),
        float(np.log2(getattr(cand, "attn_block", 0) + 1)),
    ]


class CostModel:
    """Ridge-regularised least squares: refit after every measurement."""

    def __init__(self, l2: float = 1e-3):
        self.l2 = l2
        self._w = None

    def fit(self, feats: Sequence[Sequence[float]], metrics: Sequence[float]) -> None:
        X = np.asarray(feats, np.float64)
        y = np.asarray(metrics, np.float64)
        A = X.T @ X + self.l2 * np.eye(X.shape[1])
        self._w = np.linalg.solve(A, X.T @ y)

    def predict(self, feats: Sequence[Sequence[float]]) -> np.ndarray:
        if self._w is None:
            raise RuntimeError("CostModel.predict before fit")
        return np.asarray(feats, np.float64) @ self._w
