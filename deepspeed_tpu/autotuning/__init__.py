"""Autotuning: search ZeRO stage x micro-batch x remat policy x loss-chunk.

Reference parity: ``deepspeed/autotuning/autotuner.py`` (experiment
generation + scheduler + grid/random tuners, ``ds_config_optimal.json``
output). The TPU redesign collapses the reference's multi-process experiment
scheduler into two in-process phases:

1. **static prune** — every candidate config is AOT-compiled against
   abstract inputs (``jax.jit(...).lower(...).compile()``) and its
   ``memory_analysis()`` is checked against the per-device HBM budget.
   No step is executed; configs that cannot fit are rejected for free
   (the reference must actually launch and OOM to learn this).
2. **measure** — surviving candidates run a few timed steps through the
   real engine; the tuner ranks them by the configured metric and writes
   ``ds_config_optimal.json``.
"""

from deepspeed_tpu.autotuning.autotuner import Autotuner, autotune
from deepspeed_tpu.autotuning.config import AutotuningConfig

__all__ = ["Autotuner", "AutotuningConfig", "autotune"]
