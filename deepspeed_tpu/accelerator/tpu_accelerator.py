"""TPU (and CPU-mesh) accelerator implementation over JAX.

Reference parity: ``accelerator/cuda_accelerator.py`` reimagined for XLA:
- streams/events: XLA dispatch is already async; ``synchronize`` drains it.
- RNG: functional ``jax.random`` keys instead of stateful generators; a
  per-device stateful tracker lives in ``runtime/activation_checkpointing``.
- memory stats come from ``device.memory_stats()``.
- op builders resolve against the Pallas/C++ kernel registry.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import Any, List, Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self, platform: Optional[str] = None):
        super().__init__()
        self._name = platform or "tpu"
        self._communication_backend_name = "xla"
        self._current_device = 0

    # --------------------------------------------------------------- #
    @property
    def _jax(self):
        import jax
        return jax

    def _devices(self):
        jax = self._jax
        try:
            return jax.devices()
        except RuntimeError:
            return jax.devices("cpu")

    def _local_devices(self):
        return self._jax.local_devices()

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def device(self, device_index: Optional[int] = None):
        devs = self._local_devices()
        return devs[device_index if device_index is not None else self._current_device]

    @contextlib.contextmanager
    def device_ctx(self, device_index: Optional[int] = None):
        with self._jax.default_device(self.device(device_index)):
            yield

    def set_device(self, device_index: int) -> None:
        self._current_device = device_index

    def current_device(self) -> int:
        return self._current_device

    def current_device_name(self) -> str:
        return f"{self._name}:{self._current_device}"

    def device_count(self) -> int:
        return len(self._devices())

    def local_device_count(self) -> int:
        return len(self._local_devices())

    def synchronize(self, device_index: Optional[int] = None) -> None:
        self._jax.effects_barrier()

    # ------------------------- RNG --------------------------------- #
    # Functional RNG: there is no mutable global generator — seeding
    # returns a fresh key the caller threads explicitly (reference
    # abstract_accelerator.py:44-67 surface, functional semantics).
    def random_seed(self, seed: int):
        self._seed = int(seed)
        return self._jax.random.key(seed)

    manual_seed = random_seed
    manual_seed_all = random_seed

    def initial_seed(self) -> int:
        """The last seed passed to manual_seed/random_seed (reference
        ``initial_seed()``: no arguments, returns the current seed)."""
        return getattr(self, "_seed", 0)

    def random(self):
        """The RNG namespace (reference ``accelerator.random`` returns
        ``torch.random``); here it is ``jax.random``."""
        return self._jax.random

    def is_available(self) -> bool:
        """True when the REQUESTED platform has devices (the generic
        device fallback would otherwise make this unconditionally true)."""
        try:
            return len(self._jax.devices(self._name)) > 0
        except RuntimeError:
            return False

    def default_generator(self, device_index: int):
        # Functional RNG: the "generator" is just a key derived per device.
        return self._jax.random.key(device_index)

    # ------------------------- memory ------------------------------ #
    def _stats(self, device_index: Optional[int] = None) -> dict:
        try:
            return self.device(device_index).memory_stats() or {}
        except Exception:
            return {}

    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        return self._stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        return self._stats(device_index).get("peak_bytes_in_use", 0)

    def memory_cached(self, device_index: Optional[int] = None) -> int:
        return self._stats(device_index).get("pool_bytes", 0)

    def max_memory_cached(self, device_index: Optional[int] = None) -> int:
        return self._stats(device_index).get("largest_alloc_size", 0)

    def total_memory(self, device_index: Optional[int] = None) -> int:
        return self._stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index: Optional[int] = None) -> int:
        stats = self._stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    def empty_cache(self) -> None:
        # XLA owns the allocator; nothing to flush.
        pass

    # per-chip bf16 matmul peak by device kind: the MFU denominator used
    # by the telemetry gauge and bench.py (DS_PEAK_TFLOPS overrides for
    # kinds not in the table)
    _PEAK_TFLOPS = (("v5p", 459.0), ("v5e", 197.0), ("v5lite", 197.0),
                    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0))

    def peak_tflops(self) -> float:
        """Per-chip bf16 peak TFLOP/s, or 0.0 when unknown (the MFU gauge
        then reads 0 rather than fabricating a denominator)."""
        import os
        env = os.environ.get("DS_PEAK_TFLOPS")
        if env:
            return float(env)
        try:
            kind = getattr(self._devices()[0], "device_kind", "").lower()
        except Exception:
            return 0.0
        kind = kind.replace(" ", "")
        for tag, peak in self._PEAK_TFLOPS:
            if tag in kind:
                return peak
        return 0.0

    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        return self._stats(device_index)

    def memory_report(self) -> dict:
        """Per-local-device memory summary for the health/env surfaces:
        ``{device_name: {bytes_in_use, peak_bytes_in_use, bytes_limit,
        headroom_bytes}}``. Devices whose backend exposes no memory stats
        (e.g. the CPU test mesh) map to an empty dict — callers render
        "no stats" rather than fabricated zeros."""
        out = {}
        for i in range(self.local_device_count()):
            stats = self._stats(i)
            if stats:
                used = stats.get("bytes_in_use", 0)
                limit = stats.get("bytes_limit", 0)
                out[self.device_name(i)] = {
                    "bytes_in_use": used,
                    "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
                    "bytes_limit": limit,
                    "headroom_bytes": max(limit - used, 0),
                }
            else:
                out[self.device_name(i)] = {}
        return out

    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        # Not exposed by PJRT; peak stats are monotone per process.
        pass

    # ------------------------- dtype ------------------------------- #
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True

    def is_triton_supported(self) -> bool:
        return False

    def preferred_dtype(self):
        import jax.numpy as jnp
        return jnp.bfloat16

    def supported_dtypes(self) -> List[Any]:
        import jax.numpy as jnp
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    # ------------------------- comm / misc ------------------------- #
    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def on_accelerator(self, array) -> bool:
        try:
            platform = getattr(array, "platform", None)
            if callable(platform):
                return array.platform() != "cpu"
            shards = array.addressable_shards
            return shards[0].device.platform != "cpu"
        except Exception:
            return False

    def pin_memory(self, array):
        try:
            jax = self._jax
            dev = self.device()
            host_sharding = jax.sharding.SingleDeviceSharding(dev, memory_kind="pinned_host")
            return jax.device_put(array, host_sharding)
        except Exception:
            return array

    def range_push(self, msg: str) -> None:
        try:
            self._trace_stack.append(self._jax.profiler.TraceAnnotation(msg))
            self._trace_stack[-1].__enter__()
        except Exception:
            pass

    def range_pop(self) -> None:
        try:
            ann = self._trace_stack.pop()
            ann.__exit__(None, None, None)
        except Exception:
            pass

    @property
    def _trace_stack(self):
        if not hasattr(self, "_trace_stack_"):
            self._trace_stack_ = []
        return self._trace_stack_

    # ------------------------- op builders ------------------------- #
    def create_op_builder(self, class_name: str):
        builder = self.get_op_builder(class_name)
        return builder() if builder is not None else None

    def get_op_builder(self, class_name: str):
        from deepspeed_tpu.ops.registry import get_builder_class
        return get_builder_class(class_name)


class CPU_Accelerator(TPU_Accelerator):
    """CPU accelerator used by the unit tests (virtual 8-device mesh)."""

    def __init__(self):
        super().__init__(platform="cpu")
        self._communication_backend_name = "gloo"

    def _devices(self):
        return self._jax.devices("cpu")

    def is_bf16_supported(self) -> bool:
        return True

    def preferred_dtype(self):
        import jax.numpy as jnp
        return jnp.float32
