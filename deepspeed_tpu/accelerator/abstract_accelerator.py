"""Accelerator abstraction.

Reference parity: ``accelerator/abstract_accelerator.py:7-237`` — the
``DeepSpeedAccelerator`` ABC every layer talks to instead of a hard-coded
backend. The TPU rebuild keeps the indirection (it is what makes the test
suite runnable on CPU with a virtual device mesh) but the surface is JAX-
shaped: devices are ``jax.Device`` objects, "streams" collapse into XLA's
async dispatch, and op builders become a named registry of Pallas/C++ kernels
(see ``deepspeed_tpu.ops.registry``).
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # ------------------------- device APIs ------------------------- #
    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str:
        ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None):
        ...

    @abc.abstractmethod
    def set_device(self, device_index: int) -> None:
        ...

    @abc.abstractmethod
    def current_device(self) -> int:
        ...

    @abc.abstractmethod
    def current_device_name(self) -> str:
        ...

    @abc.abstractmethod
    def device_count(self) -> int:
        ...

    @abc.abstractmethod
    def local_device_count(self) -> int:
        ...

    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None) -> None:
        ...

    # ------------------------- RNG APIs ---------------------------- #
    @abc.abstractmethod
    def random_seed(self, seed: int):
        """Return a root PRNG key for ``seed`` (jax.random.key)."""

    @abc.abstractmethod
    def default_generator(self, device_index: int):
        ...

    # ------------------------- memory APIs ------------------------- #
    @abc.abstractmethod
    def memory_allocated(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def total_memory(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def available_memory(self, device_index: Optional[int] = None) -> int:
        ...

    @abc.abstractmethod
    def empty_cache(self) -> None:
        ...

    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> dict:
        ...

    @abc.abstractmethod
    def reset_peak_memory_stats(self, device_index: Optional[int] = None) -> None:
        ...

    # ------------------------- dtype APIs -------------------------- #
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool:
        ...

    @abc.abstractmethod
    def preferred_dtype(self):
        ...

    @abc.abstractmethod
    def supported_dtypes(self) -> List[Any]:
        ...

    # ------------------------- comm / misc ------------------------- #
    @abc.abstractmethod
    def communication_backend_name(self) -> str:
        ...

    @abc.abstractmethod
    def on_accelerator(self, array) -> bool:
        ...

    @abc.abstractmethod
    def pin_memory(self, array):
        """Place host array in pinned (DMA-able) host memory if supported."""

    @abc.abstractmethod
    def range_push(self, msg: str) -> None:
        """Profiler trace-annotation push (jax.profiler.TraceAnnotation)."""

    @abc.abstractmethod
    def range_pop(self) -> None:
        ...

    # ------------------------- op builder hooks -------------------- #
    @abc.abstractmethod
    def create_op_builder(self, class_name: str):
        ...

    @abc.abstractmethod
    def get_op_builder(self, class_name: str):
        ...
