"""Accelerator selection (reference: accelerator/real_accelerator.py:35-56).

Selection order:
1. explicit ``set_accelerator()``
2. ``DS_ACCELERATOR`` env var (``tpu`` | ``cpu``)
3. runtime probe: whatever ``jax.default_backend()`` reports.
"""

from __future__ import annotations

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.tpu_accelerator import CPU_Accelerator, TPU_Accelerator

_accelerator: Optional[DeepSpeedAccelerator] = None


def is_current_accelerator_supported() -> bool:
    return get_accelerator()._name in ("tpu", "cpu", "axon")


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is not None:
        return _accelerator

    accelerator_name = os.environ.get("DS_ACCELERATOR", None)
    if accelerator_name is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
        accelerator_name = "cpu" if backend == "cpu" else backend

    if accelerator_name == "cpu":
        _accelerator = CPU_Accelerator()
    else:
        _accelerator = TPU_Accelerator(platform=accelerator_name)
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator) -> None:
    global _accelerator
    _accelerator = accel
