"""Collective micro-benchmarks (reference ``benchmarks/communication/`` +
``bin/ds_bench``).

Sweeps message sizes through the comm facade's collectives on the active
mesh and reports latency / algorithmic BW / bus BW per op+size — the same
table ``ds_bench`` prints. Sync is a host fetch of a reduction (the only
reliable barrier over remote device transports).
"""

from __future__ import annotations

import argparse
import time
from typing import List

import numpy as np


def _bw_factor(op: str, n: int) -> float:
    """algbw→busbw correction factor (ring-collective cost model, matches
    the reference's utils in benchmarks/communication/utils.py)."""
    if n <= 1:
        return 1.0
    if op == "all_reduce":
        return 2.0 * (n - 1) / n
    if op in ("all_gather", "reduce_scatter", "all_to_all"):
        return (n - 1) / n
    return 1.0


def run_op(op: str, size_bytes: int, mesh, trials: int = 20) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import deepspeed_tpu.comm as dist

    n = mesh.devices.size
    numel = max(n, (size_bytes // 4 // n) * n)
    # stacked-rank layout: dim0 indexes ranks (the facade's eager contract)
    x = jnp.arange(numel, dtype=jnp.float32).reshape(n, numel // n)
    axis = mesh.axis_names[0]
    x = jax.device_put(x, NamedSharding(mesh, P(axis)))

    fns = {
        "all_reduce": lambda t: dist.all_reduce(t),
        "all_gather": lambda t: dist.all_gather(t),
        "reduce_scatter": lambda t: dist.reduce_scatter(t),
        "all_to_all": lambda t: dist.all_to_all_single(t),
        "broadcast": lambda t: dist.broadcast(t, src=0),
    }
    # the facade compiles + caches the shard_map program internally; do NOT
    # jit here (collectives need the facade's eager path outside shard_map)
    fn = fns[op]
    out = fn(x)
    float(jnp.sum(out))  # warm + sync

    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    float(jnp.sum(out))
    dt = (time.perf_counter() - t0) / trials

    algbw = size_bytes / dt / 1e9
    busbw = algbw * _bw_factor(op, n)
    return {"op": op, "size": size_bytes, "latency_us": dt * 1e6,
            "algbw_GBps": algbw, "busbw_GBps": busbw}


def main(argv: List[str] = None):
    parser = argparse.ArgumentParser(description="collective micro-benchmarks")
    parser.add_argument("--ops", type=str,
                        default="all_reduce,all_gather,reduce_scatter,all_to_all,broadcast")
    parser.add_argument("--minsize", type=int, default=1 << 12)
    parser.add_argument("--maxsize", type=int, default=1 << 26)
    parser.add_argument("--trials", type=int, default=20)
    args = parser.parse_args(argv)

    import deepspeed_tpu.comm as dist

    if not dist.has_mesh():
        dist.init_mesh()
    mesh = dist.get_mesh()
    n = mesh.devices.size
    print(f"comm bench over mesh {dict(zip(mesh.axis_names, mesh.devices.shape))} ({n} devices)")
    print(f"{'op':<16}{'size':>12}{'latency(us)':>14}{'algbw(GB/s)':>13}{'busbw(GB/s)':>13}")

    for op in args.ops.split(","):
        size = args.minsize
        while size <= args.maxsize:
            r = run_op(op, size, mesh, args.trials)
            print(f"{r['op']:<16}{r['size']:>12}{r['latency_us']:>14.1f}"
                  f"{r['algbw_GBps']:>13.3f}{r['busbw_GBps']:>13.3f}")
            size *= 8


if __name__ == "__main__":
    main()
