"""Benchmark suites (reference ``benchmarks/`` + ``bin/ds_bench``)."""
