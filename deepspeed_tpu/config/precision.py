"""fp16 / bf16 mixed-precision sub-configs.

Reference parity: ``deepspeed/runtime/config.py:118-220`` (fp16/bf16 dict
extractors) and ``deepspeed/runtime/fp16/loss_scaler.py`` scale parameters.
On TPU, bf16 is the native fast path (MXU); fp16 is kept for parity and uses
dynamic loss scaling folded into the compiled step.
"""

from __future__ import annotations

from pydantic import Field

from deepspeed_tpu.config.config_utils import ConfigModel


class FP16Config(ConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 => dynamic loss scaling
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0

    @property
    def initial_dynamic_scale(self) -> float:
        return 2.0**self.initial_scale_power if self.dynamic_loss_scale else self.loss_scale


class BF16Config(ConfigModel):
    enabled: bool = False
    # TPU-native extension: accumulate grads in fp32 even when compute is bf16
    accumulate_grads_in_fp32: bool = True


class AMPConfig(ConfigModel):
    enabled: bool = False
    opt_level: str = "O1"


class FloatingPointConfig(ConfigModel):
    """Aggregated precision selection used by the engine."""
    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    amp: AMPConfig = Field(default_factory=AMPConfig)

    @property
    def compute_dtype(self):
        import jax.numpy as jnp
        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32
