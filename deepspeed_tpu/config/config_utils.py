"""Typed config base class.

Capability parity with the reference's ``deepspeed/runtime/config_utils.py``:
a pydantic model base with deprecated-field machinery (old keys keep working,
emit a warning, and auto-populate their replacement), dict-style access
helpers, and scientific-notation-tolerant int parsing.
"""

from __future__ import annotations

from functools import reduce
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict, model_validator

from deepspeed_tpu.utils.logging import logger


class ConfigModel(BaseModel):
    """Base for all typed sub-configs.

    Field deprecation: declare ``json_schema_extra={"deprecated": True,
    "new_param": "other_field", ...}`` on a field. Setting the deprecated field
    warns and (if ``set_new_param``, default True) writes the value through to
    the replacement field, applying ``new_param_fn`` on the way.
    """

    model_config = ConfigDict(
        validate_default=True,
        validate_assignment=True,
        use_enum_values=True,
        populate_by_name=True,
        extra="allow",
        protected_namespaces=(),
        arbitrary_types_allowed=True,
    )

    def __init__(self, strict: bool = False, **data):
        if not strict:  # This is temporary until we refactor all DS configs, allows HF to load models
            data = {k: v for k, v in data.items() if (v != "auto" or k == "replace_method")}
        super().__init__(**data)
        self._deprecated_fields_check()

    def _process_deprecated_field(self, dep_field: str) -> None:
        fields_set = self.model_fields_set
        pydantic_config = self
        kwargs = type(pydantic_config).model_fields[dep_field].json_schema_extra or {}
        new_param_fn = kwargs.get("new_param_fn", lambda x: x)
        param_value = new_param_fn(getattr(pydantic_config, dep_field))
        new_field = kwargs.get("new_param", "")
        dep_msg = kwargs.get("deprecated_msg", "")
        if dep_field in fields_set:
            logger.warning(f"Config parameter {dep_field} is deprecated" +
                           (f" use {new_field} instead" if new_field else "") +
                           (f". {dep_msg}" if dep_msg else ""))
            if new_field and kwargs.get("set_new_param", True):
                if new_field in fields_set:
                    raise ValueError(f"Cannot provide deprecated parameter '{dep_field}' and replacing "
                                     f"parameter '{new_field}' together")
                # A. Get the object with the new param
                # B. Get the explicit keys to traverse (handles nested.fields)
                field_splits = new_field.split(".")
                if len(field_splits) > 1:
                    obj = reduce(getattr, field_splits[:-1], pydantic_config)
                else:
                    obj = pydantic_config
                try:
                    setattr(obj, field_splits[-1], param_value)
                except Exception as e:
                    logger.error(f"Tried setting value for '{new_field}' with value from deprecated "
                                 f"'{dep_field}'")
                    raise e

    def _deprecated_fields_check(self) -> None:
        for field_name, field_info in type(self).model_fields.items():
            extra = field_info.json_schema_extra
            if isinstance(extra, dict) and extra.get("deprecated", False):
                self._process_deprecated_field(field_name)

    # dict-style conveniences used widely in the reference codebase
    def dict(self, **kwargs) -> Dict[str, Any]:
        return self.model_dump(**kwargs)

    def json(self, **kwargs) -> str:
        return self.model_dump_json(**kwargs)

    def get(self, key: str, default: Any = None) -> Any:
        return getattr(self, key, default)

    def __getitem__(self, key: str) -> Any:
        return getattr(self, key)


def get_scalar_param(param_dict: Dict, param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: Dict, param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: Dict, param_name: str, param_default_value: Any) -> Any:
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys when parsing a JSON config (reference behavior)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, v in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class pp_int(int):
    """An int that pretty-prints in scientific notation in config dumps."""

    def __new__(cls, val: int, custom_print_str: str | None = None):
        inst = super().__new__(cls, val)
        inst.custom_print_str = custom_print_str
        return inst

    def __repr__(self):
        if self.custom_print_str:
            return self.custom_print_str
        return f"{self.real:.1e}"


def deep_update(base: dict, override: dict) -> dict:
    """Recursive dict merge returning a new dict (shared by the nested-dict
    config schemas: data_pipeline, compression)."""
    import copy

    out = copy.deepcopy(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_update(out[k], v)
        else:
            out[k] = v
    return out
