"""Master config object.

Reference parity: ``deepspeed/runtime/config.py`` — ``DeepSpeedConfig`` parses
and validates the single JSON config dict, resolves the batch-size triad
``train_batch = micro_batch × gradient_accumulation_steps × dp_world_size``
(reference ``runtime/config.py:853-907``), and exposes typed sub-configs.

TPU-native additions: a ``mesh`` section declaring named parallel axes
(``dp``/``fsdp``/``tp``/``pp``/``ep``/``sp``) used to build the
``jax.sharding.Mesh`` the engine runs on.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, Optional, Union

from deepspeed_tpu.config import constants as C
from deepspeed_tpu.config.config_utils import (ConfigModel, dict_raise_error_on_duplicate_keys,
                                               get_scalar_param)
from deepspeed_tpu.config.precision import AMPConfig, BF16Config, FP16Config
from deepspeed_tpu.monitor.config import DeepSpeedMonitorConfig, get_monitor_config
from deepspeed_tpu.runtime.zero.config import ZeroConfig, get_zero_config
from deepspeed_tpu.utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


class CheckpointConfig(ConfigModel):
    """Typed view of the ``"checkpoint"`` section's fault-tolerance knobs
    (the reference keys ``tag_validation``/``load_universal``/
    ``use_node_local_storage`` ride through as extra fields and are parsed
    where they always were)."""

    # storage engine: "safe" = crash-safe two-phase npz+manifest format
    # (single-process); "orbax" = multi-host sharded writes. Multi-process
    # jobs fall back to orbax automatically.
    engine: str = "safe"
    # two-phase async save: snapshot on the training thread, persist on the
    # background writer. Off by default so save_checkpoint() returning
    # means "durably on disk" unless opted in.
    async_save: bool = False
    # bounded writer queue: snapshots held in host memory at once
    max_pending: int = 2
    # retention: keep this many newest tags (0 = keep all). The newest
    # VERIFIED tag and the `latest` target are never GC'd.
    keep_last: int = 0
    # transient I/O error retry budget (exponential backoff)
    retries: int = 3
    retry_backoff_s: float = 0.5
    # verify the blake2b manifest before any load touches engine state
    verify_on_load: bool = True
    # SIGTERM/SIGINT grace handling: drain the writer, emergency-save to
    # save_dir, exit 128+signum. Requires save_dir.
    preemption_save: bool = False
    save_dir: Optional[str] = None


ADAGRAD_OPTIMIZER = "adagrad"
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
FUSED_ADAM_OPTIMIZER = "fusedadam"
FUSED_LAMB_OPTIMIZER = "fusedlamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
LION_OPTIMIZER = "lion"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [
    ADAGRAD_OPTIMIZER, ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, LION_OPTIMIZER, SGD_OPTIMIZER
]


def get_fp16_config(param_dict: Dict) -> FP16Config:
    return FP16Config(**param_dict.get(C.FP16, {}))


def get_bf16_config(param_dict: Dict) -> BF16Config:
    bf16_dict = param_dict.get(C.BFLOAT16, param_dict.get(C.BFLOAT16_OLD, {}))
    return BF16Config(**bf16_dict)


def get_amp_config(param_dict: Dict) -> AMPConfig:
    return AMPConfig(**param_dict.get(C.AMP, {}))


def get_optimizer_name(param_dict: Dict) -> Optional[str]:
    if C.OPTIMIZER in param_dict and C.TYPE in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.TYPE]
    return C.OPTIMIZER_TYPE_DEFAULT


def get_optimizer_params(param_dict: Dict) -> Optional[Dict]:
    if get_optimizer_name(param_dict) is not None and C.OPTIMIZER_PARAMS in param_dict[C.OPTIMIZER]:
        return param_dict[C.OPTIMIZER][C.OPTIMIZER_PARAMS]
    return None

def get_optimizer_gradient_clipping(param_dict: Dict) -> Optional[float]:
    optimizer_params = get_optimizer_params(param_dict)
    if optimizer_params is not None and C.MAX_GRAD_NORM in optimizer_params:
        return optimizer_params[C.MAX_GRAD_NORM]
    return None


def get_scheduler_name(param_dict: Dict) -> Optional[str]:
    if C.SCHEDULER in param_dict and C.TYPE in param_dict[C.SCHEDULER]:
        return param_dict[C.SCHEDULER][C.TYPE]
    return C.SCHEDULER_TYPE_DEFAULT


def get_scheduler_params(param_dict: Dict) -> Optional[Dict]:
    if get_scheduler_name(param_dict) is not None and C.SCHEDULER_PARAMS in param_dict[C.SCHEDULER]:
        return param_dict[C.SCHEDULER][C.SCHEDULER_PARAMS]
    return None


class DeepSpeedConfig:
    """Parses + validates the framework config (a dict or a path to JSON)."""

    def __init__(self,
                 config: Union[str, Dict],
                 mpu=None,
                 mesh=None,
                 world_size: Optional[int] = None):
        if isinstance(config, dict):
            self._param_dict = copy.deepcopy(config)
        elif isinstance(config, str) and os.path.exists(config):
            with open(config) as f:
                self._param_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        else:
            raise DeepSpeedConfigError(
                f"Expected a string path to an existing config file, or a dict. Received: {config}")

        # Data-parallel world size used for batch triad resolution. Priority:
        # explicit arg > mpu (reference contract) > mesh dp axes > jax.device_count.
        if world_size is not None:
            self.world_size = world_size
        elif mpu is not None:
            self.world_size = mpu.get_data_parallel_world_size()
        elif mesh is not None:
            ws = 1
            for ax in ("dp", "fsdp"):
                if ax in mesh.shape:
                    ws *= mesh.shape[ax]
            self.world_size = ws
        else:
            try:
                import jax
                self.world_size = jax.device_count()
            except Exception:
                self.world_size = 1

        self._initialize_params(self._param_dict)
        self._configure_train_batch_size()
        self._do_sanity_check()

    # ------------------------------------------------------------------ #

    def _initialize_params(self, param_dict: Dict) -> None:
        self.train_batch_size = get_scalar_param(param_dict, C.TRAIN_BATCH_SIZE, C.TRAIN_BATCH_SIZE_DEFAULT)
        self.train_micro_batch_size_per_gpu = get_scalar_param(param_dict, C.TRAIN_MICRO_BATCH_SIZE_PER_GPU,
                                                               C.TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT)
        self.gradient_accumulation_steps = get_scalar_param(param_dict, C.GRADIENT_ACCUMULATION_STEPS,
                                                            C.GRADIENT_ACCUMULATION_STEPS_DEFAULT)
        self.steps_per_print = get_scalar_param(param_dict, C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.dump_state = get_scalar_param(param_dict, C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.disable_allgather = get_scalar_param(param_dict, C.DISABLE_ALLGATHER, C.DISABLE_ALLGATHER_DEFAULT)
        self.communication_data_type = get_scalar_param(param_dict, C.COMMUNICATION_DATA_TYPE,
                                                        C.COMMUNICATION_DATA_TYPE_DEFAULT)
        self.prescale_gradients = get_scalar_param(param_dict, C.PRESCALE_GRADIENTS, C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = get_scalar_param(param_dict, C.GRADIENT_PREDIVIDE_FACTOR,
                                                          C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT)
        self.sparse_gradients_enabled = get_scalar_param(param_dict, C.SPARSE_GRADIENTS, C.SPARSE_GRADIENTS_DEFAULT)

        self.zero_config = get_zero_config(param_dict)
        self.zero_optimization_stage = self.zero_config.stage
        self.zero_enabled = self.zero_optimization_stage > 0

        self.fp16_config = get_fp16_config(param_dict)
        self.fp16_enabled = self.fp16_config.enabled
        self.fp16_auto_cast = self.fp16_config.auto_cast
        self.bf16_config = get_bf16_config(param_dict)
        self.bfloat16_enabled = self.bf16_config.enabled
        assert not (self.fp16_enabled and self.bfloat16_enabled), "bf16 and fp16 modes cannot be simultaneously enabled"
        self.fp16_master_weights_and_gradients = self.fp16_config.fp16_master_weights_and_grads
        self.amp_config = get_amp_config(param_dict)
        self.amp_enabled = self.amp_config.enabled
        self.loss_scale = self.fp16_config.loss_scale
        self.initial_dynamic_scale = self.fp16_config.initial_dynamic_scale
        self.dynamic_loss_scale_args = dict(
            init_scale=2**self.fp16_config.initial_scale_power,
            scale_window=self.fp16_config.loss_scale_window,
            min_scale=self.fp16_config.min_loss_scale,
            delayed_shift=self.fp16_config.hysteresis,
        ) if self.fp16_config.dynamic_loss_scale else None

        self.gradient_clipping = get_scalar_param(param_dict, C.GRADIENT_CLIPPING, C.GRADIENT_CLIPPING_DEFAULT)

        self.optimizer_name = get_optimizer_name(param_dict)
        if self.optimizer_name is not None and self.optimizer_name.lower() in DEEPSPEED_OPTIMIZERS:
            self.optimizer_name = self.optimizer_name.lower()
        self.optimizer_params = get_optimizer_params(param_dict)
        self.optimizer_legacy_fusion = get_scalar_param(param_dict.get(C.OPTIMIZER, {}), C.LEGACY_FUSION,
                                                        C.LEGACY_FUSION_DEFAULT)
        self.zero_allow_untested_optimizer = get_scalar_param(param_dict, C.ZERO_ALLOW_UNTESTED_OPTIMIZER,
                                                              C.ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT)
        self.scheduler_name = get_scheduler_name(param_dict)
        self.scheduler_params = get_scheduler_params(param_dict)

        self.wall_clock_breakdown = get_scalar_param(param_dict, C.WALL_CLOCK_BREAKDOWN,
                                                     C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = get_scalar_param(param_dict, C.MEMORY_BREAKDOWN, C.MEMORY_BREAKDOWN_DEFAULT)
        self.monitor_config: DeepSpeedMonitorConfig = get_monitor_config(param_dict)
        from deepspeed_tpu.monitor.config import get_telemetry_config
        self.telemetry_config = get_telemetry_config(param_dict)

        self.gradient_accumulation_dtype = param_dict.get(C.DATA_TYPES, {}).get(C.GRAD_ACCUM_DTYPE,
                                                                                C.GRAD_ACCUM_DTYPE_DEFAULT)

        # sub-sections whose typed configs live in their subsystems; parsed lazily
        self.pipeline = param_dict.get("pipeline", {})
        self.pld_enabled = param_dict.get(C.PROGRESSIVE_LAYER_DROP, {}).get(C.PLD_ENABLED, C.PLD_ENABLED_DEFAULT)
        self.pld_params = param_dict.get(C.PROGRESSIVE_LAYER_DROP, {}) if self.pld_enabled else False
        self.curriculum_enabled_legacy = param_dict.get(C.CURRICULUM_LEARNING, {}).get(C.CURRICULUM_ENABLED,
                                                                                       C.CURRICULUM_ENABLED_DEFAULT)
        self.curriculum_params_legacy = param_dict.get(C.CURRICULUM_LEARNING, False)
        # MoQ: progressive quantization-aware training (reference
        # "quantize_training" section, runtime/quantize.py + eigenvalue.py)
        qt = param_dict.get("quantize_training", {})
        self.quantize_training_enabled = bool(qt.get("enabled", False))
        self.quantize_training = qt if self.quantize_training_enabled else {}

        from deepspeed_tpu.runtime.data_pipeline.config import get_data_efficiency_config
        self.data_efficiency_config = get_data_efficiency_config(param_dict)
        self.data_efficiency_enabled = self.data_efficiency_config.get("enabled", False)

        checkpoint_params = param_dict.get(C.CHECKPOINT, {})
        validation_mode = checkpoint_params.get(C.CHECKPOINT_TAG_VALIDATION,
                                                C.CHECKPOINT_TAG_VALIDATION_DEFAULT).title()
        self.checkpoint_tag_validation_enabled = validation_mode != "Ignore"
        self.checkpoint_tag_validation_fail = validation_mode == "Fail"
        if validation_mode not in C.CHECKPOINT_TAG_VALIDATION_MODES:
            raise DeepSpeedConfigError(f"Checkpoint config contains invalid tag_validation value: {validation_mode}")
        self.load_universal_checkpoint = checkpoint_params.get(C.LOAD_UNIVERSAL_CHECKPOINT,
                                                               C.LOAD_UNIVERSAL_CHECKPOINT_DEFAULT)
        self.use_node_local_storage = checkpoint_params.get(C.USE_NODE_LOCAL_STORAGE_CHECKPOINT,
                                                            C.USE_NODE_LOCAL_STORAGE_CHECKPOINT_DEFAULT)
        self.checkpoint_config = CheckpointConfig(**checkpoint_params)
        if self.checkpoint_config.engine not in ("safe", "orbax"):
            raise DeepSpeedConfigError(
                f"checkpoint.engine={self.checkpoint_config.engine!r} "
                "(expected 'safe' or 'orbax')")
        if self.checkpoint_config.preemption_save and not self.checkpoint_config.save_dir:
            raise DeepSpeedConfigError(
                "checkpoint.preemption_save requires checkpoint.save_dir")
        self.dataloader_drop_last = get_scalar_param(param_dict, C.DATALOADER_DROP_LAST,
                                                     C.DATALOADER_DROP_LAST_DEFAULT)

        from deepspeed_tpu.comm.config import DeepSpeedCommsConfig
        self.comms_config = DeepSpeedCommsConfig(param_dict)

        from deepspeed_tpu.profiling.config import DeepSpeedFlopsProfilerConfig
        self.flops_profiler_config = DeepSpeedFlopsProfilerConfig(**param_dict.get("flops_profiler", {}))

        from deepspeed_tpu.runtime.activation_checkpointing.config import DeepSpeedActivationCheckpointingConfig
        self.activation_checkpointing_config = DeepSpeedActivationCheckpointingConfig(
            **param_dict.get("activation_checkpointing", {}))

        from deepspeed_tpu.compression.config import get_compression_config
        self.compression_config = get_compression_config(param_dict)

        from deepspeed_tpu.elasticity.config import ElasticityConfig
        self.elasticity_enabled = param_dict.get(C.ELASTICITY, {}).get(C.ENABLED, C.ENABLED_DEFAULT)
        self.elasticity_config = ElasticityConfig(param_dict.get(C.ELASTICITY, {})) if self.elasticity_enabled \
            else None

        from deepspeed_tpu.inference.config import WeightQuantConfig
        self.weight_quantization_config = WeightQuantConfig(
            **param_dict["weight_quantization"]) if "weight_quantization" in param_dict else None

        # TPU-native mesh axes: {"dp": -1} means "all remaining devices on dp"
        self.mesh_axes: Dict[str, int] = dict(param_dict.get(C.MESH, C.MESH_AXES_DEFAULT))

        # Vocab-head loss kernel override: None leaves the model config's
        # fused_cross_entropy alone; "auto"/"on"/"off" is pushed into the
        # client model by the engine (runtime/engine.py)
        self.fused_cross_entropy = get_scalar_param(param_dict, C.FUSED_CROSS_ENTROPY,
                                                    C.FUSED_CROSS_ENTROPY_DEFAULT)
        if self.fused_cross_entropy not in (None, "auto", "on", "off"):
            raise DeepSpeedConfigError(
                f"fused_cross_entropy={self.fused_cross_entropy!r} "
                "(expected 'auto', 'on' or 'off')")

        # Sparse attention section (structure configs parsed by ops.sparse_attention)
        self.sparse_attention = param_dict.get(C.SPARSE_ATTENTION, None)

        self.nebula_config = param_dict.get("nebula", {})
        from deepspeed_tpu.autotuning.config import AutotuningConfig
        self.autotuning_config = AutotuningConfig(**param_dict.get("autotuning", {}))

    # ------------------------------------------------------------------ #
    # Batch triad (reference runtime/config.py:853-907)

    def _batch_assertion(self) -> None:
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        assert train_batch > 0, f"Train batch size: {train_batch} has to be greater than 0"
        assert micro_batch > 0, f"Micro batch size per gpu: {micro_batch} has to be greater than 0"
        assert grad_acc > 0, f"Gradient accumulation steps: {grad_acc} has to be greater than 0"
        assert train_batch == micro_batch * grad_acc * self.world_size, (
            f"Check batch related parameters. train_batch_size is not equal "
            f"to micro_batch_per_gpu * gradient_acc_step * world_size "
            f"{train_batch} != {micro_batch} * {grad_acc} * {self.world_size}")

    def _set_batch_related_parameters(self) -> None:
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps

        # all values are provided nothing needs to be set
        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            return
        # global_accumulation_steps needs to be set
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= self.world_size
            self.gradient_accumulation_steps = grad_acc
        # micro_batch_per_gpu needs to be set
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // self.world_size
            micro_batch //= grad_acc
            self.train_micro_batch_size_per_gpu = micro_batch
        # train_batch_size needs to be set
        elif micro_batch is not None and grad_acc is not None:
            train_batch_size = micro_batch * grad_acc
            train_batch_size *= self.world_size
            self.train_batch_size = train_batch_size
        # gradient_accumulation_steps and micro_batch_per_gpus is set
        elif train_batch is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = train_batch // self.world_size
        # train_batch_size and gradient_accumulation_step is set
        elif micro_batch is not None:
            self.train_batch_size = micro_batch * self.world_size
            self.gradient_accumulation_steps = 1
        else:
            raise DeepSpeedConfigError("Either train_batch_size or train_micro_batch_size_per_gpu needs to be "
                                       "provided")

    def _configure_train_batch_size(self) -> None:
        self._set_batch_related_parameters()
        self._batch_assertion()

    def _do_sanity_check(self) -> None:
        if self.zero_enabled and self.zero_optimization_stage > 3:
            raise DeepSpeedConfigError(f"Max supported ZeRO stage is 3, got {self.zero_optimization_stage}")
        if self.fp16_master_weights_and_gradients:
            assert self.zero_enabled and self.zero_optimization_stage in (
                1, 2), "Fp16_master_weights_and_grads is only supported with ZeRO Stage 1/2 for now."

    def print_user_config(self) -> str:
        return json.dumps(self._param_dict, sort_keys=True, indent=4, default=repr)

    def print(self, name: str) -> None:
        logger.info(f"{name}:")
        for arg in sorted(vars(self)):
            if arg != "_param_dict":
                logger.info(f"  {arg} {'.' * (29 - len(arg))} {getattr(self, arg)}")
        logger.info(f"  json = {self.print_user_config()}")
