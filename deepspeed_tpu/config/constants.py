"""Config key names and defaults (reference: deepspeed/runtime/constants.py)."""

#############################################
# Routes
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer and lr scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False
SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False
ZERO_FORCE_DS_CPU_OPTIMIZER = "zero_force_ds_cpu_optimizer"
ZERO_FORCE_DS_CPU_OPTIMIZER_DEFAULT = True

#############################################
# Steps
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

#############################################
# Training options
#############################################
PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

#############################################
# bf16 / fp16 / amp
#############################################
BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # keeping for backwards compatibility
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_AUTO_CAST = "auto_cast"
FP16_AUTO_CAST_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

#############################################
# Gradient clipping
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

#############################################
# Communication options
#############################################
COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None

#############################################
# Sparse attention, checkpointing, misc
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

#############################################
# Gradient-average toggles (reference parity)
#############################################
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

#############################################
# Checkpoint
#############################################
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
USE_NODE_LOCAL_STORAGE_CHECKPOINT_DEFAULT = False

#############################################
# Data types
#############################################
DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

#############################################
# Checkpoint tag validation modes
#############################################
CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]

#############################################
# Drop last (dataloader)
#############################################
DATALOADER_DROP_LAST = "dataloader_drop_last"
DATALOADER_DROP_LAST_DEFAULT = False

#############################################
# PLD
#############################################
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Curriculum learning (legacy path)
#############################################
CURRICULUM_LEARNING = "curriculum_learning"
CURRICULUM_ENABLED = "enabled"
CURRICULUM_ENABLED_DEFAULT = False

#############################################
# Elasticity
#############################################
ELASTICITY = "elasticity"
ENABLED = "enabled"
ENABLED_DEFAULT = False
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000
MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]
MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000
MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0
VERSION = "version"
LATEST_ELASTICITY_VERSION = 0.2
ELASTICITY_DEFAULT_VERSION = 0.2
PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True
IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False
MODEL_PARALLEL_SIZE = "model_parallel_size"
MODEL_PARALLEL_SIZE_DEFAULT = 1
NUM_GPUS_PER_NODE = "num_gpus_per_node"
NUM_GPUS_PER_NODE_DEFAULT = 1

#############################################
# Mesh / parallel axes (TPU-native extension)
#############################################
MESH = "mesh"
MESH_AXES_DEFAULT = {"dp": -1}

#############################################
# Vocab-head loss kernel (TPU-native extension): overrides the model
# config's fused_cross_entropy ("auto"|"on"|"off") when set
#############################################
FUSED_CROSS_ENTROPY = "fused_cross_entropy"
FUSED_CROSS_ENTROPY_DEFAULT = None
