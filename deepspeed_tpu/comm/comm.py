"""Communication facade.

Reference parity: ``deepspeed/comm/comm.py`` — module-level collective
functions with op-level profiling, group management, and ``init_distributed``
rank discovery. Rebuilt for XLA SPMD:

- **Groups are mesh axes.** A "process group" is a named axis (or tuple of
  axes) of the framework mesh (see ``deepspeed_tpu.comm.mesh``). XLA lowers
  the collectives onto ICI/DCN rings; there are no communicator handles.

- **One API, two contexts.** Each collective works both *inside* a
  ``shard_map``-traced region (operands are tracers; lowers to
  ``lax.psum``/``all_gather``/``psum_scatter``/``all_to_all``/``ppermute``)
  and *eagerly* on concrete global arrays (wrapped in a jitted ``shard_map``
  over the group axis). Eager calls follow the stacked-rank convention: the
  leading array dim indexes ranks in the group, mirroring how the reference's
  per-rank tensors line up across processes. Eager calls are what ds_bench
  and the comm unit tests exercise; production training steps trace the same
  functions inside their compiled step.

- ``init_distributed`` (reference ``comm/comm.py:530``) maps to
  ``jax.distributed.initialize`` with env discovery for both torch-style
  (MASTER_ADDR/RANK/WORLD_SIZE) and JAX-style coordinator variables.
"""

from __future__ import annotations

import functools
import os
import time
from enum import Enum
from typing import Callable, Optional, Sequence, Union

import numpy as np

from deepspeed_tpu.utils import comms_logging
from deepspeed_tpu.utils.logging import logger

_mesh = None  # the framework-wide mesh, set by init_mesh/set_mesh
_mesh_tls = None  # lazy threading.local: per-thread mesh-override stack
_comms_logger = None
_initialized = False


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4
    UNUSED = 5


GroupLike = Union[None, str, Sequence[str]]


def comms_logger() -> comms_logging.CommsLogger:
    global _comms_logger
    if _comms_logger is None:
        _comms_logger = comms_logging.CommsLogger()
    return _comms_logger


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None, verbose=None, debug=None) -> None:
    """Wire comms-logger settings from the master config (reference comm.py:79)."""
    cl = comms_logger()
    if deepspeed_config is not None:
        cl.configure(deepspeed_config.comms_config)
    if enabled is not None:
        cl.enabled = enabled
    if prof_all is not None:
        cl.prof_all = prof_all
    if prof_ops is not None:
        cl.prof_ops = prof_ops
    if verbose is not None:
        cl.verbose = verbose
    if debug is not None:
        cl.debug = debug


# --------------------------------------------------------------------- #
# Mesh / group management

def set_mesh(mesh) -> None:
    global _mesh
    _mesh = mesh


def _mesh_override():
    """The CURRENT thread's innermost mesh override, or None."""
    tls = _mesh_tls
    stack = getattr(tls, "stack", None) if tls is not None else None
    return stack[-1] if stack else None


def mesh_override(mesh):
    """Context manager pinning :func:`get_mesh`/:func:`has_mesh` to
    ``mesh`` for the CURRENT THREAD only (re-entrant: a stack). This is
    how an engine scopes its traces to its own mesh — the always-on
    serving loop runs on a dedicated thread, and mutating the
    process-global ``_mesh`` from there would race a training engine (or
    another serving engine) tracing concurrently on another thread. The
    global mesh is never touched: other threads keep seeing it."""
    import contextlib

    @contextlib.contextmanager
    def scope():
        global _mesh_tls
        if mesh is None:
            raise ValueError("mesh_override needs a mesh (None would "
                             "shadow the global instead of pinning one)")
        if _mesh_tls is None:
            import threading
            _mesh_tls = threading.local()
        stack = getattr(_mesh_tls, "stack", None)
        if stack is None:
            stack = _mesh_tls.stack = []
        stack.append(mesh)
        try:
            yield mesh
        finally:
            stack.pop()
    return scope()


def get_mesh():
    ov = _mesh_override()
    if ov is not None:
        return ov
    global _mesh
    if _mesh is None:
        from deepspeed_tpu.comm.mesh import build_mesh
        _mesh = build_mesh()
    return _mesh


def has_mesh() -> bool:
    return _mesh_override() is not None or _mesh is not None


def init_mesh(axes=None, devices=None):
    from deepspeed_tpu.comm.mesh import build_mesh
    set_mesh(build_mesh(axes, devices))
    return _mesh


def _resolve_axes(group: GroupLike) -> tuple:
    """Group → tuple of mesh axis names present in the mesh. None = world.

    Axes missing from the mesh are dropped (a group of size 1, like the
    reference's single-rank process groups, makes every collective a no-op).
    """
    from deepspeed_tpu.utils.logging import warn_once
    mesh = get_mesh()
    if group is None:
        return tuple(mesh.axis_names)
    axes = (group,) if isinstance(group, str) else tuple(group)
    for a in axes:
        if a not in mesh.shape:
            warn_once(f"Collective group axis '{a}' is not in the mesh {tuple(mesh.axis_names)}; "
                      f"treating as a size-1 group (no-op). Check for typos if this is unexpected.")
    return tuple(a for a in axes if a in mesh.shape)


def get_world_size(group: GroupLike = None) -> int:
    from deepspeed_tpu.comm.mesh import axis_size
    mesh = get_mesh()
    return axis_size(mesh, _resolve_axes(group))


def get_rank(group: GroupLike = None) -> int:
    """Process-level rank (host index). Device-level position on a mesh axis
    is only meaningful inside a traced region (use ``axis_index``)."""
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))


def axis_index(axis: str):
    """Device's coordinate along ``axis``; traced-context only."""
    import jax
    return jax.lax.axis_index(axis)


# --------------------------------------------------------------------- #
# init_distributed

def init_distributed(dist_backend: Optional[str] = None,
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method=None,
                     dist_init_required=None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1) -> None:
    """Bring up the multi-process JAX runtime (reference comm/comm.py:530).

    Single-process (the common TPU-slice-per-process and unit-test case) is a
    no-op. Multi-process is detected from JAX coordinator env vars or
    torch-style MASTER_ADDR/WORLD_SIZE/RANK, which are translated.
    """
    global _initialized
    if _initialized:
        return
    import jax

    coord = os.environ.get("COORDINATOR_ADDRESS")
    nproc = world_size if world_size > 0 else int(os.environ.get("WORLD_SIZE", os.environ.get("NUM_PROCESSES", 1)))
    proc_id = rank if rank >= 0 else int(os.environ.get("RANK", os.environ.get("PROCESS_ID", 0)))

    # MPI / SLURM rank discovery (reference comm/comm.py:595 mpi_discovery):
    # mpirun/srun set their own env instead of RANK/WORLD_SIZE
    if auto_mpi_discovery and nproc <= 1:
        if "OMPI_COMM_WORLD_SIZE" in os.environ:
            nproc = int(os.environ["OMPI_COMM_WORLD_SIZE"])
            proc_id = int(os.environ.get("OMPI_COMM_WORLD_RANK", 0))
        elif int(os.environ.get("SLURM_STEP_NUM_TASKS", 0)) > 1:
            # srun sets step-level task counts; plain sbatch scripts (where a
            # single python process must NOT join a phantom world) do not
            nproc = int(os.environ["SLURM_STEP_NUM_TASKS"])
            proc_id = int(os.environ.get("SLURM_PROCID", 0))
        elif "PMI_SIZE" in os.environ:
            nproc = int(os.environ["PMI_SIZE"])
            proc_id = int(os.environ.get("PMI_RANK", 0))

    if coord is None and "MASTER_ADDR" in os.environ and nproc > 1:
        coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', distributed_port)}"
    if coord is None and nproc > 1 and "SLURM_LAUNCH_NODE_IPADDR" in os.environ:
        coord = f"{os.environ['SLURM_LAUNCH_NODE_IPADDR']}:{distributed_port}"

    if nproc > 1:
        if verbose:
            logger.info(f"Initializing distributed JAX: coordinator={coord} "
                        f"process={proc_id}/{nproc}")
        jax.distributed.initialize(coordinator_address=coord, num_processes=nproc, process_id=proc_id)
    elif verbose:
        logger.info("Single-process run; jax.distributed not initialized")
    _initialized = True


def is_initialized() -> bool:
    return _initialized


# --------------------------------------------------------------------- #
# Collective implementations

def _is_traced(x) -> bool:
    import jax
    return isinstance(x, jax.core.Tracer)


_eager_cache: dict = {}


def _eager_collective(x, axes: tuple, body: Callable, key=None, in_spec=None, out_spec=None):
    """Run ``body`` under shard_map over the group axes of the global mesh,
    sharding the leading dim of ``x`` over the group (stacked-rank layout).

    Compiled executables are cached on (op key, axes, shape, dtype) so
    repeated eager calls (benchmarks, tests) don't re-trace.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = get_mesh()
    cache_key = (mesh, key, axes, x.shape, str(x.dtype)) if key is not None else None
    fn = _eager_cache.get(cache_key)
    if fn is None:
        spec_in = in_spec if in_spec is not None else P(axes if len(axes) > 1 else axes[0])
        spec_out = out_spec if out_spec is not None else spec_in
        fn = jax.jit(
            shard_map(body, mesh=mesh, in_specs=(spec_in,), out_specs=spec_out, check_vma=False))
        if cache_key is not None:
            if len(_eager_cache) > 512:
                _eager_cache.clear()
            _eager_cache[cache_key] = fn
    return fn(x)


def _log_wrap(name: str, group_pos: int = 0):
    """timed_op equivalent (reference comm/comm.py:108-149): wall-clock the
    eager path and record bandwidth when the comms logger is enabled.
    ``group_pos`` is the index of ``group`` within ``*args`` (after tensor)
    so positionally-passed groups are still attributed correctly."""

    def decorator(fn):

        @functools.wraps(fn)
        def wrapper(tensor, *args, **kwargs):
            cl = comms_logger()
            log_name = kwargs.pop("log_name", name)
            prof = cl.enabled and (cl.prof_all or name in cl.prof_ops) and not _is_traced(tensor)
            if not prof:
                return fn(tensor, *args, **kwargs)
            import jax
            jax.block_until_ready(tensor)
            t0 = time.perf_counter()
            result = fn(tensor, *args, **kwargs)
            jax.block_until_ready(result)
            ms = (time.perf_counter() - t0) * 1e3
            group = kwargs.get("group", args[group_pos] if len(args) > group_pos else None)
            n = max(1, get_world_size(group))
            # stacked-rank layout: per-rank payload is 1/n of the global array
            msg_size = tensor.size * tensor.dtype.itemsize // n
            cl.append(name, log_name, ms, msg_size, n)
            return result

        return wrapper

    return decorator


@_log_wrap("all_reduce", group_pos=1)
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: GroupLike = None, async_op: bool = False):
    """Reduce across the group; every participant gets the result.

    Traced: ``tensor`` is a per-shard value, returns ``lax.psum``-family over
    the axis. Eager: leading dim of the global array indexes ranks; each
    rank-slice of the result equals the reduction of all slices.
    """
    from jax import lax

    axes = _resolve_axes(group)
    if not axes:
        return tensor
    ax = axes if len(axes) > 1 else axes[0]
    reducers = {
        ReduceOp.SUM: lax.psum,
        ReduceOp.MAX: lax.pmax,
        ReduceOp.MIN: lax.pmin,
        ReduceOp.AVG: lambda t, a: lax.pmean(t, a),
    }
    if op == ReduceOp.PRODUCT:
        # sign-aware product: |prod| via log-sum-exp, sign via negative count
        def reducer(t, a):
            import jax.numpy as jnp
            magnitude = jnp.exp(lax.psum(jnp.log(jnp.abs(t)), a))
            neg_count = lax.psum((t < 0).astype(t.dtype), a)
            sign = 1.0 - 2.0 * (neg_count % 2)
            return sign * magnitude
    else:
        reducer = reducers[op]
    if _is_traced(tensor):
        return reducer(tensor, ax)
    return _eager_collective(tensor, axes, lambda t: reducer(t, ax), key=("all_reduce", op.name))


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: GroupLike = None, async_op: bool = False):
    return all_reduce(tensor, op=op, group=group)


@_log_wrap("all_gather", group_pos=0)
def all_gather(tensor, group: GroupLike = None, axis: int = 0, tiled: bool = True, async_op: bool = False):
    """Gather shards along ``axis`` from every group member.

    Traced: ``lax.all_gather(..., tiled=True)`` (concatenated, the layout the
    reference's ``all_gather_into_tensor`` produces). Eager: input sharded on
    the leading dim; output is fully replicated.
    """
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = _resolve_axes(group)
    if not axes:
        return tensor
    ax = axes if len(axes) > 1 else axes[0]
    if _is_traced(tensor):
        return lax.all_gather(tensor, ax, axis=axis, tiled=tiled)
    return _eager_collective(tensor, axes, lambda t: lax.all_gather(t, ax, axis=axis, tiled=tiled),
                             key=("all_gather", axis, tiled), out_spec=P())


def all_gather_into_tensor(output_tensor=None, tensor=None, group: GroupLike = None, async_op: bool = False):
    """Fused-tensor allgather (reference comm/torch.py:34 capability). Output
    buffer arg accepted for API parity; JAX is functional so it is ignored."""
    return all_gather(tensor, group=group)


@_log_wrap("reduce_scatter", group_pos=1)
def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, group: GroupLike = None, axis: int = 0,
                   async_op: bool = False):
    """Reduce across the group then scatter shards along ``axis``."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    axes = _resolve_axes(group)
    if not axes:
        return tensor
    ax = axes if len(axes) > 1 else axes[0]

    def reduce_op(t):
        out = lax.psum_scatter(t, ax, scatter_dimension=axis, tiled=True)
        if op == ReduceOp.AVG:
            out = out / get_world_size(group)
        return out

    if _is_traced(tensor):
        return reduce_op(tensor)

    # Eager stacked-rank layout: dim0 indexes ranks; each rank's tensor is its
    # slice, and it gets back tensor_size/world elements (reference semantics).
    def body(t):
        return reduce_op(t[0])[None]

    return _eager_collective(tensor, axes, body, key=("reduce_scatter", op.name, axis))


def reduce_scatter_tensor(output_tensor=None, tensor=None, op: ReduceOp = ReduceOp.SUM, group: GroupLike = None,
                          async_op: bool = False):
    return reduce_scatter(tensor, op=op, group=group)


@_log_wrap("all_to_all", group_pos=0)
def all_to_all_single(tensor, group: GroupLike = None, split_axis: int = 0, concat_axis: int = 0,
                      async_op: bool = False):
    """Transpose shards across the group (MoE dispatch primitive).

    Traced: ``lax.all_to_all``. Eager: leading dim = ranks; each rank's slice
    is split into world-size chunks and chunk *i* goes to rank *i*.
    """
    from jax import lax

    axes = _resolve_axes(group)
    if not axes:
        return tensor
    ax = axes if len(axes) > 1 else axes[0]
    if _is_traced(tensor):
        return lax.all_to_all(tensor, ax, split_axis=split_axis, concat_axis=concat_axis, tiled=True)

    # Eager stacked-rank layout: dim0 indexes ranks; rank i's tensor is split
    # into world chunks along ``split_axis`` and chunk j goes to rank j.
    def body(t):
        return lax.all_to_all(t[0], ax, split_axis=split_axis, concat_axis=concat_axis, tiled=True)[None]

    return _eager_collective(tensor, axes, body, key=("all_to_all", split_axis, concat_axis))


@_log_wrap("broadcast", group_pos=1)
def broadcast(tensor, src: int = 0, group: GroupLike = None, async_op: bool = False):
    """Every participant gets rank-``src``'s value.

    Traced: implemented as a masked psum (select src shard, sum). Eager:
    returns the global array with src's leading-dim slice broadcast to all.
    """
    import jax.numpy as jnp
    from jax import lax

    axes = _resolve_axes(group)
    if not axes:
        return tensor
    ax = axes if len(axes) > 1 else axes[0]

    def body(t):
        idx = lax.axis_index(ax)
        masked = jnp.where(idx == src, t, jnp.zeros_like(t))
        return lax.psum(masked, ax)

    if _is_traced(tensor):
        return body(tensor)
    return _eager_collective(tensor, axes, body, key=("broadcast", src))


@_log_wrap("ppermute", group_pos=1)
def ring_send_recv(tensor, shift: int = 1, group: GroupLike = None):
    """Neighbour exchange over the group ring — the SPMD form of the
    reference's pipeline send/recv (``runtime/pipe/p2p.py``): every rank
    sends to ``(rank+shift) % n`` and receives from ``(rank-shift) % n``."""
    from jax import lax

    axes = _resolve_axes(group)
    if not axes:
        return tensor
    ax = axes[0]
    n = get_world_size(group)
    perm = [(i, (i + shift) % n) for i in range(n)]
    if _is_traced(tensor):
        return lax.ppermute(tensor, ax, perm)
    return _eager_collective(tensor, axes, lambda t: lax.ppermute(t, ax, perm), key=("ppermute", shift))


def send(tensor, dst: int, group: GroupLike = None, tag: int = 0):
    raise NotImplementedError(
        "Point-to-point send/recv between arbitrary ranks is not an SPMD primitive; "
        "use ring_send_recv (ppermute) or the pipeline engine's stage transfer.")


def recv(tensor, src: int, group: GroupLike = None, tag: int = 0):
    raise NotImplementedError(
        "Point-to-point send/recv between arbitrary ranks is not an SPMD primitive; "
        "use ring_send_recv (ppermute) or the pipeline engine's stage transfer.")


def barrier(group: GroupLike = None, async_op: bool = False):
    """Synchronize all processes: a tiny psum everyone must join."""
    import jax
    import jax.numpy as jnp
    x = all_reduce(jnp.zeros((get_world_size(group),)), group=group)
    jax.block_until_ready(x)
    return x


def monitored_barrier(group: GroupLike = None, timeout=None, wait_all_ranks: bool = False):
    return barrier(group)


# torch.distributed-shaped aliases kept for drop-in familiarity
def get_data_parallel_world_size():
    from deepspeed_tpu.comm.mesh import data_parallel_axes
    return get_world_size(data_parallel_axes(get_mesh()))


def get_model_parallel_world_size():
    return get_world_size("tp") if "tp" in get_mesh().shape else 1


def log_summary(show_straggler: bool = False):
    return comms_logger().log_all(print_log=True, show_straggler=show_straggler)


# ------------------------------------------------------------------ #
# Remaining reference-surface functions (deepspeed/comm/comm.py). SPMD
# semantics notes: rooted collectives (reduce/gather with a dst) compute
# the same value on EVERY rank — XLA collectives have no single-receiver
# form, and the extra copies are free under SPMD. The dst/src arguments
# are accepted for call-shape parity.

def is_available() -> bool:
    """Reference torch.distributed.is_available analogue — the JAX
    collective machinery is always importable."""
    return True


def get_world_group() -> GroupLike:
    """The world "process group": the all-axes GroupLike (None)."""
    return None


def reduce(tensor, dst: int = 0, op: ReduceOp = ReduceOp.SUM,
           group: GroupLike = None, async_op: bool = False):
    """Rooted reduce. SPMD form: every rank holds the reduced value (see
    module note); ``dst`` is accepted for parity."""
    return all_reduce(tensor, op=op, group=group, async_op=async_op)


def gather(tensor, gather_list=None, dst: int = 0, group: GroupLike = None,
           axis: int = 0, async_op: bool = False):
    """Rooted gather. SPMD form: every rank holds the gathered tensor
    (= all_gather); ``gather_list``/``dst`` accepted for parity."""
    return all_gather(tensor, group=group, axis=axis, async_op=async_op)


@_log_wrap("scatter", group_pos=1)
def scatter(tensor, src: int = 0, group: GroupLike = None, axis: int = 0,
            async_op: bool = False):
    """Scatter the src rank's tensor along ``axis``: group rank r keeps
    chunk r. Under SPMD scatter IS a resharding — the global value stays
    the full tensor, and the sharding carries the split:

    - traced (inside a shard_map over the group axes): a true dynamic
      slice by the device's own group index;
    - eager: the same array resharded over the group axis along ``axis``
      (each device's local view is its chunk).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    axes = _resolve_axes(group)
    if not axes:
        return tensor
    ws = get_world_size(group)
    if tensor.shape[axis] % ws:
        raise ValueError(f"scatter: axis {axis} size {tensor.shape[axis]} "
                         f"not divisible by group size {ws}")
    ax = axes if len(axes) > 1 else axes[0]
    if _is_traced(tensor):
        idx = lax.axis_index(ax)
        size = tensor.shape[axis] // ws
        return lax.dynamic_slice_in_dim(tensor, idx * size, size, axis=axis)
    spec = P(*([None] * axis + [ax]))
    return jax.device_put(jnp.asarray(tensor),
                          NamedSharding(get_mesh(), spec))


def get_global_rank(group: GroupLike = None, group_rank: int = 0) -> int:
    """Translate a group-local rank to the global rank (reference
    utils/groups-style lookup): ranks enumerate mesh coordinates in axis
    order; non-group axes take the calling process's own coordinates (the
    first mesh position owned by this process)."""
    import jax

    mesh = get_mesh()
    axes = _resolve_axes(group)
    gsize = 1
    for name in axes:
        gsize *= mesh.shape[name]
    if not 0 <= group_rank < gsize:
        raise ValueError(f"group_rank {group_rank} out of range for group "
                         f"{axes} of size {gsize}")
    devs = np.asarray(mesh.devices)
    names = list(mesh.shape)
    base = None
    for pos, dev in np.ndenumerate(devs):
        if dev.process_index == jax.process_index():
            base = pos
            break
    coords = {n: (int(base[i]) if base is not None else 0)
              for i, n in enumerate(names)}
    rem = group_rank
    for name in reversed(axes):
        coords[name] = rem % mesh.shape[name]
        rem //= mesh.shape[name]
    flat = 0
    for name in names:
        flat = flat * mesh.shape[name] + coords[name]
    return flat


def new_group(ranks=None):
    """Reference ``new_group(ranks)``. Mesh axes ARE the process groups
    here: the world list returns the world group; any other rank subset
    must be expressed as a mesh axis (build the mesh with that axis)."""
    if ranks is None or sorted(ranks) == list(range(get_world_size())):
        return None
    raise NotImplementedError(
        "arbitrary rank subsets are not representable as mesh collectives; "
        "declare the grouping as a mesh axis (config mesh={...}) and pass "
        "the axis name as the group")


def destroy_process_group(group: GroupLike = None) -> None:
    """Groups are mesh axes — nothing to tear down. Clearing the world
    group drops the cached mesh (reference destroy_process_group)."""
    if group is None:
        set_mesh(None)


class _CompletedWork:
    """Handle returned by isend/irecv: XLA dispatch is asynchronous by
    nature, so the 'work' is complete from the caller's perspective."""

    def __init__(self, result=None):
        self.result = result

    def wait(self, timeout=None) -> bool:
        return True

    def is_completed(self) -> bool:
        return True


def isend(tensor, dst: int, group: GroupLike = None, tag: int = 0):
    """Async send. Same contract as :func:`send`: arbitrary-rank p2p is not
    an SPMD primitive — raises with the ring_send_recv/pipeline guidance.
    (Kept so reference code fails loudly at the call site, not on import.)"""
    return _CompletedWork(send(tensor, dst, group=group, tag=tag))


def irecv(tensor, src: int, group: GroupLike = None, tag: int = 0):
    """Async recv; same loud contract as :func:`recv` (see isend)."""
    return _CompletedWork(recv(tensor, src, group=group, tag=tag))
