"""Comms-logger config (reference: deepspeed/comm/config.py)."""

from __future__ import annotations

from typing import List

from deepspeed_tpu.config.config_utils import ConfigModel

COMMS_LOGGER = "comms_logger"


class CommsLoggerConfig(ConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = []


class DeepSpeedCommsConfig:

    def __init__(self, ds_config: dict):
        self.comms_logger_enabled = COMMS_LOGGER in ds_config
        if self.comms_logger_enabled:
            self.comms_logger = CommsLoggerConfig(**ds_config[COMMS_LOGGER])
        else:
            self.comms_logger = CommsLoggerConfig()
