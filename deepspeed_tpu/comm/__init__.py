"""deepspeed_tpu.comm — collective communication facade over mesh axes.

Usage mirrors the reference's ``deepspeed.comm``::

    import deepspeed_tpu.comm as dist
    dist.init_distributed()
    dist.init_mesh({"dp": -1, "tp": 2})
    y = dist.all_reduce(x, group="dp")
"""

from deepspeed_tpu.comm.comm import (ReduceOp, all_gather, all_gather_into_tensor, all_reduce, all_to_all_single,
                                     axis_index, barrier, broadcast, comms_logger, configure, destroy_process_group,
                                     gather, get_global_rank, get_local_rank, get_mesh, get_rank, get_world_group,
                                     get_world_size, has_mesh, inference_all_reduce, init_distributed, init_mesh,
                                     mesh_override,
                                     irecv, is_available, is_initialized, isend, log_summary, monitored_barrier,
                                     new_group, recv, reduce, reduce_scatter, reduce_scatter_tensor, ring_send_recv,
                                     scatter, send, set_mesh)
from deepspeed_tpu.comm.mesh import (axis_size, bound_axis_size,
                                     build_hybrid_mesh, build_mesh,
                                     data_parallel_axes)

__all__ = [
    "ReduceOp", "all_gather", "all_gather_into_tensor", "all_reduce", "all_to_all_single", "axis_index", "barrier",
    "broadcast", "comms_logger", "configure", "destroy_process_group", "gather", "get_global_rank", "get_local_rank",
    "get_mesh", "get_rank", "get_world_group", "get_world_size", "has_mesh", "inference_all_reduce",
    "init_distributed", "init_mesh", "irecv", "is_available", "is_initialized", "isend", "log_summary",
    "mesh_override", "monitored_barrier", "new_group", "recv", "reduce", "reduce_scatter", "reduce_scatter_tensor", "ring_send_recv",
    "scatter", "send", "set_mesh", "axis_size", "bound_axis_size", "build_hybrid_mesh", "build_mesh", "data_parallel_axes",
]
