"""Device-mesh construction for all parallel axes.

This is the TPU-native replacement for the reference's process-group world
(``deepspeed/comm/comm.py:179`` ``new_group`` + ``deepspeed/utils/groups.py``):
instead of explicit NCCL communicators per parallel dimension, one
``jax.sharding.Mesh`` with named axes is built once and every subsystem
addresses its collectives by axis name.

Canonical axis order (outer → inner): ``("pp", "dp", "fsdp", "ep", "tp", "sp")``.
Outer axes map to DCN (slower, inter-slice) and inner axes to ICI, matching
how ``mesh_utils.create_hybrid_device_mesh`` lays out devices, so TP/SP
collectives always ride ICI.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from deepspeed_tpu.utils.logging import logger

# outer → inner; pp outermost (least communication), tp/sp innermost (most)
CANONICAL_AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "tp", "sp")


def _resolve_axis_sizes(axes: Dict[str, int], n_devices: int) -> Dict[str, int]:
    """Fill in a single ``-1`` axis so the product equals ``n_devices``."""
    sizes = dict(axes)
    wildcard = [name for name, size in sizes.items() if size == -1]
    if len(wildcard) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {wildcard}")
    known = math.prod(size for size in sizes.values() if size != -1)
    if wildcard:
        if n_devices % known != 0:
            raise ValueError(f"Device count {n_devices} not divisible by fixed axes product {known}")
        sizes[wildcard[0]] = n_devices // known
    else:
        if known != n_devices:
            raise ValueError(f"Mesh axes product {known} != device count {n_devices}")
    return sizes


def build_mesh(axes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence] = None,
               axis_order: Sequence[str] = CANONICAL_AXIS_ORDER) -> Mesh:
    """Build a named-axis mesh over ``devices``.

    ``axes`` maps axis name → size, with at most one ``-1`` meaning "all
    remaining devices". Axes not mentioned get size 1 and are dropped from
    the mesh only if absent from ``axes`` entirely.

    On multi-host TPU, devices from ``jax.devices()`` are already ordered so
    that contiguous blocks share ICI; keeping the canonical (outer→inner)
    order therefore places the innermost axes on ICI neighbours.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if axes is None:
        axes = {"dp": -1}

    sizes = _resolve_axis_sizes(axes, len(devices))

    # order the declared axes canonically; unknown axes go innermost
    names = sorted(sizes, key=lambda n: axis_order.index(n) if n in axis_order else len(axis_order))
    shape = tuple(sizes[n] for n in names)
    mesh_devices = np.array(devices).reshape(shape)
    mesh = Mesh(mesh_devices, tuple(names))
    logger.info(f"Built device mesh {dict(zip(names, shape))} over {len(devices)} devices")
    return mesh


def build_hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int]) -> Mesh:
    """Multi-slice mesh: per axis, ``dcn_axes[name]`` replicas span slices
    over DCN and ``ici_axes[name]`` chips span within a slice over ICI
    (the reference's multi-node NCCL topology, rebuilt on
    ``mesh_utils.create_hybrid_device_mesh``).

    Both dicts must cover the same axis names; the resulting mesh axis size
    is the elementwise product. Example for 2 slices of 16 chips::

        build_hybrid_mesh(ici_axes={"dp": 1, "tp": 16}, dcn_axes={"dp": 2, "tp": 1})
        # -> Mesh {"dp": 2, "tp": 16}, dp over DCN, tp over ICI
    """
    import jax
    from jax.experimental import mesh_utils

    if set(ici_axes) != set(dcn_axes):
        raise ValueError(f"ici_axes and dcn_axes must name the same axes, got {set(ici_axes)} vs {set(dcn_axes)}")
    names = [n for n in CANONICAL_AXIS_ORDER if n in ici_axes] + \
            [n for n in ici_axes if n not in CANONICAL_AXIS_ORDER]
    ici_shape = tuple(ici_axes[n] for n in names)
    dcn_shape = tuple(dcn_axes[n] for n in names)
    mesh_devices = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=jax.devices())
    return Mesh(mesh_devices, tuple(names))


def bound_axis_size(name) -> int:
    """Size of a manual/collective axis bound in the CURRENT trace (a
    shard_map/pmap body). ``jax.lax.axis_size`` where the installed jax has
    it; on older versions (e.g. 0.4.x) the classic psum-of-1 idiom, which
    jax constant-folds to the axis size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def axis_size(mesh: Mesh, axis) -> int:
    """Product of sizes of (possibly multiple) mesh axes."""
    if axis is None:
        return math.prod(mesh.shape.values())
    if isinstance(axis, str):
        return mesh.shape[axis] if axis in mesh.shape else 1
    return math.prod(mesh.shape[a] for a in axis if a in mesh.shape)


def data_parallel_axes(mesh: Mesh) -> List[str]:
    """Axes over which the batch is sharded (dp + fsdp when present)."""
    return [ax for ax in ("dp", "fsdp") if ax in mesh.shape and mesh.shape[ax] > 1] or \
           [ax for ax in ("dp", "fsdp") if ax in mesh.shape]
