from deepspeed_tpu.ops.aio.aio_binding import AsyncIOHandle, aligned_array, padded_numel

__all__ = ["AsyncIOHandle", "aligned_array", "padded_numel"]
