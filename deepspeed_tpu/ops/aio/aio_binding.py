"""ctypes bindings for the native async I/O engine (csrc/aio.cpp).

Reference parity: the ``aio_handle`` pybind surface
(``csrc/aio/py_lib/py_ds_aio.cpp`` / ``deepspeed_py_aio_handle.cpp:14-40``):
block_size/queue_depth/thread_count knobs, sync_/async_ pread/pwrite and
``wait``. Queue depth and event overlap are subsumed by the thread pool.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from deepspeed_tpu.ops import native
from deepspeed_tpu.ops.native import c_i64

_configured = False
ALIGN = 4096


def _lib():
    global _configured
    lib = native.get_lib()
    if not _configured:
        lib.ds_aio_handle_new.argtypes = [c_i64, ctypes.c_int]
        lib.ds_aio_handle_new.restype = ctypes.c_void_p
        lib.ds_aio_handle_free.argtypes = [ctypes.c_void_p]
        lib.ds_aio_pread.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, c_i64]
        lib.ds_aio_pwrite.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, c_i64]
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
        lib.ds_aio_wait.restype = c_i64
        lib.ds_aio_inflight.argtypes = [ctypes.c_void_p]
        lib.ds_aio_inflight.restype = c_i64
        lib.ds_aio_last_errno.argtypes = [ctypes.c_void_p]
        lib.ds_aio_last_errno.restype = ctypes.c_int
        _configured = True
    return lib


def padded_numel(numel: int, dtype=np.float32) -> int:
    """Element count after padding to the O_DIRECT block size."""
    itemsize = np.dtype(dtype).itemsize
    nbytes = numel * itemsize
    return ((nbytes + ALIGN - 1) // ALIGN * ALIGN) // itemsize


def aligned_array(numel: int, dtype=np.float32) -> np.ndarray:
    """Allocate a 4096-byte-aligned numpy array padded up to the O_DIRECT
    block size (reference pins + aligns its aio buffers,
    ``csrc/aio/common/deepspeed_aio_utils.cpp``). The returned array holds
    ``padded_numel(numel, dtype)`` elements; callers view ``[:numel]`` for the
    logical tensor and hand the full array to the aio engine so transfers stay
    block-aligned."""
    dtype = np.dtype(dtype)
    padded = padded_numel(numel, dtype) * dtype.itemsize
    raw = np.zeros(padded + ALIGN, np.uint8)
    offset = (-raw.ctypes.data) % ALIGN
    return raw[offset:offset + padded].view(dtype)


class AsyncIOHandle:
    """Thread-pool async tensor I/O against a fast local SSD."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 32,
                 single_submit: bool = False, overlap_events: bool = True,
                 thread_count: int = 8):
        self.block_size = block_size
        self.thread_count = thread_count
        # queue_depth/single_submit/overlap_events kept for config parity
        self.queue_depth = queue_depth
        self.single_submit = single_submit
        self.overlap_events = overlap_events
        self._h = _lib().ds_aio_handle_new(block_size, thread_count)
        self._pinned: list = []  # buffers referenced by inflight C++ I/O

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                _lib().ds_aio_handle_free(h)
            except Exception:
                pass
            self._h = None

    def _ptr(self, arr: np.ndarray):
        if not arr.flags["C_CONTIGUOUS"]:
            raise ValueError("aio buffers must be C-contiguous")
        return arr.ctypes.data_as(ctypes.c_void_p)

    # --- async ---------------------------------------------------------- #
    def async_pread(self, buffer: np.ndarray, filename: str) -> None:
        # retain the buffer until wait(): worker threads hold raw pointers
        self._pinned.append(buffer)
        _lib().ds_aio_pread(self._h, self._ptr(buffer), filename.encode(), buffer.nbytes)

    def async_pwrite(self, buffer: np.ndarray, filename: str) -> None:
        self._pinned.append(buffer)
        _lib().ds_aio_pwrite(self._h, self._ptr(buffer), filename.encode(), buffer.nbytes)

    def wait(self) -> int:
        """Block until all inflight I/O completes; raises on I/O errors."""
        errors = _lib().ds_aio_wait(self._h)
        self._pinned.clear()
        if errors:
            err = _lib().ds_aio_last_errno(self._h)
            detail = f": {os.strerror(err)}" if err else ""
            raise IOError(f"aio: {errors} chunk transfer(s) failed{detail}")
        return 0

    def inflight(self) -> int:
        return _lib().ds_aio_inflight(self._h)

    # --- sync ----------------------------------------------------------- #
    def sync_pread(self, buffer: np.ndarray, filename: str) -> None:
        self.async_pread(buffer, filename)
        self.wait()

    def sync_pwrite(self, buffer: np.ndarray, filename: str) -> None:
        self.async_pwrite(buffer, filename)
        self.wait()
