"""Fused logits-free cross-entropy Pallas kernel for the vocab head.

Computes ``mean CE(h @ W + b, labels)`` without ever materialising the
``[tokens, V]`` logits: the forward streams ``W`` in vocab blocks and keeps a
running ``(max, logsumexp, label_logit)`` state in VMEM scratch per token
tile, so HBM traffic is O(tokens·D + D·V) instead of O(tokens·V) — the TPU
re-expression of the reference's fused softmax/cross-entropy kernels
(``csrc/transformer/softmax_kernels.cu``, inference fused logits in
``csrc/transformer/inference``). The backward recomputes each vocab block's
logits on the fly from the saved logsumexp (no [tokens, V] residual either)
and accumulates ``dh = (softmax - onehot) @ W_blk^T`` and
``dW_blk = h^T @ (softmax - onehot)`` per block.

Like the flash kernels in this package, the streaming softmax runs in the
**log2 domain** (logits pre-scaled by log2(e), ``exp2`` instead of ``exp`` —
the VPU evaluates exp2 faster) and every matmul keeps its storage dtype
(bf16 operands, f32 accumulate) so the dots ride the MXU at full rate.

Vocab padding is handled by pre-biasing: the bias vector is padded with a
large negative on the pad columns, so padded logits underflow to zero
probability in both passes and never pollute the logsumexp — no in-kernel
bounds checks. Ignore-index / masked labels are handled OUTSIDE the
custom_vjp boundary: the kernel returns per-token nll and the (differentiable)
masked mean runs in XLA, so the backward coefficient each kernel consumes is
exactly the cotangent AD hands it (zero on masked and padded tokens).

Wired into the model zoo via ``models/transformer.py vocab_head_ce`` (config
``fused_cross_entropy: auto|on|off``). Runs compiled on TPU, interpreted
elsewhere (the CPU unit tier exercises it numerically via interpret mode).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASKED = -1e30  # pad-column bias: exp2 underflows to exactly 0
_LOG2E = 1.4426950408889634


def _round8(n: int) -> int:
    return -(-max(8, n) // 8) * 8


# --------------------------------------------------------------------- #
# kernels. Shared geometry: h [Np, D] token-tiled (bt rows), w [D, Vp]
# vocab-tiled (bv cols), bias/labels/rows ride as [1, Np] / [1, Vp] so the
# trailing block dims tile lanes (same trick as flash_attention's row specs).


def _block_logits(h_ref, w_ref, b_ref):
    """One (bt, bv) block of log2-domain logits: (h @ w_blk + b_blk)·log2e.
    Storage-dtype operands (bf16 runs the MXU at full rate), f32 accumulate;
    pad columns carry a _MASKED bias and underflow to p=0 downstream."""
    s = jax.lax.dot_general(h_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return (s + b_ref[:].astype(jnp.float32)) * _LOG2E


def _fwd_kernel(h_ref, w_ref, b_ref, lab_ref, nll_ref, lse_ref,
                m_scr, l_scr, g_scr, *, bt, bv):
    # grid (nt, nv), vocab innermost: the (m, l, gold) running state lives in
    # VMEM scratch across vocab steps; outputs written once on the last step
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _MASKED)
        l_scr[:] = jnp.zeros_like(l_scr)
        g_scr[:] = jnp.zeros_like(g_scr)

    s = _block_logits(h_ref, w_ref, b_ref)

    # gold logit: each token's label falls in exactly one vocab block; a
    # lane-wise compare-and-sum gathers it without any dynamic indexing
    lab_local = lab_ref[0] - j * bv
    cols = jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    hit = cols == lab_local[:, None]
    g_scr[:, :1] = g_scr[:, :1] + jnp.sum(jnp.where(hit, s, 0.0), axis=1,
                                          keepdims=True)

    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp2(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(jnp.exp2(s - m_new), axis=1, keepdims=True)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nv - 1)
    def _():
        # every vocab tile holds at least one unmasked column (pad < bv), so
        # l >= exp2(max - max) = 1 and the log is safe
        lse2 = m_scr[:, 0] + jnp.log2(l_scr[:, 0])
        lse_ref[0] = lse2
        # natural-log nll; masked/padded tokens get a finite garbage value
        # that the outer (differentiable) masked mean zeroes out
        nll_ref[0] = (lse2 - g_scr[:, 0]) / _LOG2E


def _softmax_minus_onehot(h_ref, w_ref, b_ref, lab_ref, lse_ref, coef_ref,
                          j, bt, bv):
    """(p - onehot)·coef for one block, recomputed from the saved log2-domain
    logsumexp — the shared core of both backward kernels."""
    s = _block_logits(h_ref, w_ref, b_ref)
    p = jnp.exp2(s - lse_ref[0][:, None])  # pad cols: exp2(-huge) = 0
    lab_local = lab_ref[0] - j * bv
    cols = jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    onehot = (cols == lab_local[:, None]).astype(jnp.float32)
    return (p - onehot) * coef_ref[0][:, None]


def _dh_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, coef_ref, dh_ref,
               dh_scr, *, bt, bv):
    # grid (nt, nv), vocab innermost: dh for one token tile accumulates over
    # vocab blocks in scratch
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    ds = _softmax_minus_onehot(h_ref, w_ref, b_ref, lab_ref, lse_ref,
                               coef_ref, j, bt, bv).astype(w_ref.dtype)
    dh_scr[:] = dh_scr[:] + jax.lax.dot_general(
        ds, w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == nv - 1)
    def _():
        dh_ref[:] = dh_scr[:].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, b_ref, lab_ref, lse_ref, coef_ref,
               dw_ref, db_ref, dw_scr, db_scr, *, bt, bv):
    # grid (nv, nt), tokens innermost: dw/db for one vocab block accumulate
    # over token tiles in scratch
    i = pl.program_id(1)
    nt = pl.num_programs(1)
    j = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    ds = _softmax_minus_onehot(h_ref, w_ref, b_ref, lab_ref, lse_ref,
                               coef_ref, j, bt, bv)
    dw_scr[:] = dw_scr[:] + jax.lax.dot_general(
        h_ref[:], ds.astype(h_ref.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    db_scr[:1] = db_scr[:1] + jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(i == nt - 1)
    def _():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)
        db_ref[0] = db_scr[0].astype(db_ref.dtype)


# --------------------------------------------------------------------- #
# custom-VJP wrapper (one cached build per static geometry)


@functools.lru_cache(maxsize=32)
def _build(D: int, bt: int, bv: int, bv_dw: int, interpret: bool):
    """Per-token-nll CE with custom VJP on padded [Np, D] / [D, Vp] operands.

    Returns ``nll [1, Np]`` f32; the (masked, differentiable) mean runs in
    XLA outside, so AD delivers each token's loss coefficient — including
    valid-mask zeros and the 1/count scale — as the nll cotangent, which the
    backward kernels consume directly.
    """

    def h_spec():
        return pl.BlockSpec((bt, D), lambda i, j: (i, 0))

    def w_spec(bvx=bv):
        return pl.BlockSpec((D, bvx), lambda i, j: (0, j))

    def vrow_spec(bvx=bv):
        # bias rides [1, Vp]
        return pl.BlockSpec((1, bvx), lambda i, j: (0, j))

    def trow_spec():
        # labels / lse / coef / nll ride [1, Np]
        return pl.BlockSpec((1, bt), lambda i, j: (0, i))

    def fwd_call(hp, wp, bp, labp):
        Np, D = hp.shape
        Vp = wp.shape[1]
        kernel = functools.partial(_fwd_kernel, bt=bt, bv=bv)
        nll, lse = pl.pallas_call(
            kernel,
            grid=(Np // bt, Vp // bv),
            in_specs=[h_spec(), w_spec(), vrow_spec(), trow_spec()],
            out_specs=[trow_spec(), trow_spec()],
            out_shape=[jax.ShapeDtypeStruct((1, Np), jnp.float32),
                       jax.ShapeDtypeStruct((1, Np), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((bt, 128), jnp.float32),
                            pltpu.VMEM((bt, 128), jnp.float32),
                            pltpu.VMEM((bt, 128), jnp.float32)],
            interpret=interpret,
        )(hp, wp, bp, labp)
        return nll, lse

    @jax.custom_vjp
    def ce_nll(hp, wp, bp, labp):
        return fwd_call(hp, wp, bp, labp)[0]

    def ce_fwd(hp, wp, bp, labp):
        nll, lse = fwd_call(hp, wp, bp, labp)
        return nll, (hp, wp, bp, labp, lse)

    def ce_bwd(res, g):
        hp, wp, bp, labp, lse = res
        Np, D = hp.shape
        Vp = wp.shape[1]
        coef = g.astype(jnp.float32)  # [1, Np]: valid·ĝ/count from the mean

        dh = pl.pallas_call(
            functools.partial(_dh_kernel, bt=bt, bv=bv),
            grid=(Np // bt, Vp // bv),
            in_specs=[h_spec(), w_spec(), vrow_spec(), trow_spec(),
                      trow_spec(), trow_spec()],
            out_specs=h_spec(),
            out_shape=jax.ShapeDtypeStruct((Np, D), hp.dtype),
            scratch_shapes=[pltpu.VMEM((bt, D), jnp.float32)],
            interpret=interpret,
        )(hp, wp, bp, labp, lse, coef)

        # transposed grid: token tiles innermost so one (D, bv_dw) dw block
        # accumulates across them in scratch (bv_dw may be finer than the
        # forward's bv to keep the f32 accumulator within VMEM at large D)
        kh_spec = pl.BlockSpec((bt, D), lambda j, i: (i, 0))
        kw_spec = pl.BlockSpec((D, bv_dw), lambda j, i: (0, j))
        kv_spec = pl.BlockSpec((1, bv_dw), lambda j, i: (0, j))
        kt_spec = pl.BlockSpec((1, bt), lambda j, i: (0, i))
        dw, db = pl.pallas_call(
            functools.partial(_dw_kernel, bt=bt, bv=bv_dw),
            grid=(Vp // bv_dw, Np // bt),
            in_specs=[kh_spec, kw_spec, kv_spec, kt_spec, kt_spec, kt_spec],
            out_specs=[kw_spec, kv_spec],
            out_shape=[jax.ShapeDtypeStruct((D, Vp), wp.dtype),
                       jax.ShapeDtypeStruct((1, Vp), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((D, bv_dw), jnp.float32),
                            pltpu.VMEM((8, bv_dw), jnp.float32)],
            interpret=interpret,
        )(hp, wp, bp, labp, lse, coef)

        return (dh, dw, db.astype(bp.dtype),
                np.zeros(labp.shape, jax.dtypes.float0))

    ce_nll.defvjp(ce_fwd, ce_bwd)
    return ce_nll


# --------------------------------------------------------------------- #
# public entry point


def fused_cross_entropy(h, w, labels, bias=None, valid=None,
                        block_t: Optional[int] = None,
                        block_v: Optional[int] = None,
                        interpret: Optional[bool] = None):
    """Mean token cross-entropy of the vocab head ``h @ w + bias`` vs
    ``labels``, logits never materialised.

    h: [..., D] features (any leading shape; bf16 or f32); w: [D, V];
    bias: optional [V]; labels: [...] int (must be in [0, V) — mask
    ignore-index positions via ``valid`` and clamp the labels, exactly like
    ``chunked_vocab_ce``'s safe_labels); valid: optional [...] bool/float
    keep-mask. Returns the scalar mean nll over valid tokens
    (``sum(nll·valid) / max(sum(valid), 1)`` — empty masks yield 0, matching
    the XLA reference path).

    Differentiable through ``jax.custom_vjp`` w.r.t. h, w, and bias, and
    composes with jit/remat/shard_map (fully-manual contexts). Runs compiled
    on TPU, interpreted elsewhere (``interpret=None`` auto-selects).
    """
    D = h.shape[-1]
    V = w.shape[-1]
    if w.shape[0] != D:
        raise ValueError(f"w {w.shape} does not match features D={D}")
    N = 1
    for d in labels.shape:
        N *= d
    if h.size != N * D:
        raise ValueError(f"h {h.shape} does not match labels {labels.shape}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # token tile: whole (8-aligned) token set when it fits one block; else
    # 128-aligned so the [1, Np] row blocks tile lanes legally. Large-D
    # heads (7B-class, D >= 4096) take the finer defaults so the (bt, D)
    # dh accumulator and (D, bv) weight blocks stay within VMEM.
    bt = block_t or (128 if D >= 4096 else 256)
    n8 = _round8(N)
    bt = min(bt, n8)
    if n8 > bt and bt % 128:
        bt = -(-bt // 128) * 128
    Np = -(-N // bt) * bt

    # vocab tile: same alignment rules on the [1, Vp] bias/db rows
    bv = block_v or (256 if D >= 4096 else 512)
    v8 = _round8(V)
    bv = min(bv, v8)
    if v8 > bv and bv % 128:
        bv = -(-bv // 128) * 128
    Vp = -(-V // bv) * bv
    # dw accumulator (D, bv_dw) f32 must fit VMEM comfortably at large D;
    # halve while it exceeds ~4 MB. Every halving keeps bv_dw = bv / 2^k, a
    # divisor of bv and hence of Vp (Vp = ceil(V/bv)·bv), so the dw grid
    # always tiles exactly.
    bv_dw = bv
    while bv_dw % 2 == 0 and bv_dw > 128 and D * bv_dw * 4 > (4 << 20):
        bv_dw //= 2

    hp = h.reshape(N, D)
    if w.dtype != hp.dtype:
        # the in-kernel dots need matching operand dtypes; the cast sits
        # OUTSIDE the custom_vjp, so AD casts dw back to w's dtype itself
        w = w.astype(hp.dtype)
    labp = labels.reshape(N).astype(jnp.int32)
    vf = (jnp.ones((N,), jnp.float32) if valid is None
          else valid.reshape(N).astype(jnp.float32))
    b = (jnp.zeros((V,), jnp.float32) if bias is None
         else bias.astype(jnp.float32))

    if Np != N:
        hp = jnp.pad(hp, ((0, Np - N), (0, 0)))
        labp = jnp.pad(labp, (0, Np - N))
        vf = jnp.pad(vf, (0, Np - N))
    if Vp != V:
        w = jnp.pad(w, ((0, 0), (0, Vp - V)))
        # pad columns get a -1e30 bias: zero probability in fwd AND bwd
        b = jnp.pad(b, (0, Vp - V), constant_values=_MASKED)

    ce_nll = _build(D, bt, bv, bv_dw, bool(interpret))
    nll = ce_nll(hp, w, b[None, :], labp[None, :])  # [1, Np]
    # masked mean OUTSIDE the custom_vjp: AD turns it into the per-token
    # backward coefficient (0 on masked/padded tokens, 1/count elsewhere)
    return jnp.sum(nll[0] * vf) / jnp.maximum(jnp.sum(vf), 1.0)
