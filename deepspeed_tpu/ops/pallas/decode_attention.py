"""Pallas decode attention: one new token per sequence against the KV cache.

TPU-native replacement for the reference's fused ``softmax_context`` decode
kernel (KV-append + attention over the cached keys,
``csrc/transformer/inference/csrc/pt_binding.cpp:1668-1793``; workspace
``csrc/transformer/inference/includes/inference_context.h:49``).

Decode attention is HBM-bandwidth-bound: the cost is streaming the KV cache
once. The einsum fallback pays H/KV times that for GQA models when it
materialises a repeated copy of both cache halves before the dot. This
kernel:

* streams k/v blocks straight from the ``[B, Smax, KV, Hd]`` cache layout
  (no repeat, no transpose) — every cache block is fetched exactly once and
  ALL kv-head groups are consumed while it sits in VMEM (a static unrolled
  loop over the KV groups; KV is small). Keeping the full ``(KV, Hd)``
  minor dims in the block is also what Mosaic's tiling requires: a
  kv-head-sliced block of sublane extent 1 over a KV>1 array is not a legal
  TPU block shape;
* keeps the running (m, l, acc) streaming-softmax state in VMEM scratch
  across the sequence-block grid dimension, writing the ``[KV, P, Hd]``
  output tile once;
* masks ``kpos > pos`` blocks entirely (``pl.when``) and clamps the block
  index map at the last live block, so the dead cache tail costs neither
  DMA nor FLOPs;
* supports ALiBi slopes and an additive key-side pad bias ``[B, Smax]``
  (left-padded prompt slots).

Grid: ``(B, Smax/bk)`` — sequence blocks innermost so scratch carries.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30


def _kernel(pos_ref, q_ref, k_ref, v_ref, bias_ref, slope_ref, o_ref,
            m_ref, l_ref, acc_ref, *, bk, n_blocks, kv, group,
            has_bias, has_alibi):
    i = pl.program_id(1)
    pos = pos_ref[0]

    @pl.when(i == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    koff = i * bk
    run = koff <= pos  # whole block beyond the cached prefix → skip

    @pl.when(run)
    def _():
        kpos1 = koff + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if has_bias:
            bias = bias_ref[0, 0][None, :]
        # static unroll over kv groups: each group reads its own sublane of
        # the shared k/v block and its own row-slice of the scratch state
        for g in range(kv):
            rows = pl.ds(g * group, group)
            q = q_ref[0, g].astype(jnp.float32)          # [P, Hd] (pre-scaled)
            k = k_ref[0, :, g].astype(jnp.float32)       # [bk, Hd]
            v = v_ref[0, :, g].astype(jnp.float32)       # [bk, Hd]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            kpos = jnp.broadcast_to(kpos1, s.shape)      # [P, bk]
            if has_alibi:
                s = s + slope_ref[g][:, None] * (kpos - pos).astype(jnp.float32)
            if has_bias:
                s = s + bias
            s = jnp.where(kpos <= pos, s, _NEG)

            m_prev = m_ref[rows, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            # m/l live lane-broadcast in (H, 128) scratch (full-vreg stores)
            l_ref[rows, :] = l_ref[rows, :] * alpha[:, None] \
                + jnp.sum(p, axis=1)[:, None]
            m_ref[rows, :] = jnp.broadcast_to(m_new[:, None], (group, 128))
            acc_ref[rows, :] = acc_ref[rows, :] * alpha[:, None] + p @ v

    @pl.when(i == n_blocks - 1)
    def _():
        for g in range(kv):
            rows = pl.ds(g * group, group)
            o_ref[0, g] = (acc_ref[rows, :]
                           / l_ref[rows, 0][:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bk", "has_bias", "has_alibi",
                                             "interpret"))
def _decode_call(q, ck, cv, pos, bias, slopes, *, bk, has_bias, has_alibi,
                 interpret):
    B, KV, P, Hd = q.shape
    Smax = ck.shape[1]
    n_blocks = Smax // bk
    grid = (B, n_blocks)

    # clamp the sequence-block index at the last block containing pos: dead
    # tail iterations revisit that block, which the pipeline does NOT
    # re-fetch — the kernel is bandwidth-bound, so with a workspace much
    # larger than the live prefix this is the dominant saving (the pl.when
    # guard then skips their FLOPs too)
    def kv_idx(b, i, sc):
        return (b, jnp.minimum(i, sc[0] // bk), 0, 0)

    in_specs = [
        pl.BlockSpec((1, KV, P, Hd), lambda b, i, sc: (b, 0, 0, 0)),
        pl.BlockSpec((1, bk, KV, Hd), kv_idx),
        pl.BlockSpec((1, bk, KV, Hd), kv_idx),
        # [B, 1, Smax]: the singleton keeps the sublane block extent equal to
        # its array dim (Mosaic forbids sublane-1 blocks over a larger dim)
        pl.BlockSpec((1, 1, bk),
                     lambda b, i, sc: (b, 0, jnp.minimum(i, sc[0] // bk))),
        pl.BlockSpec((KV, P), lambda b, i, sc: (0, 0)),  # alibi slopes
    ]
    out = pl.pallas_call(
        functools.partial(_kernel, bk=bk, n_blocks=n_blocks, kv=KV, group=P,
                          has_bias=has_bias, has_alibi=has_alibi),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, KV, P, Hd), lambda b, i, sc: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((KV * P, 128), jnp.float32),  # running max
                pltpu.VMEM((KV * P, 128), jnp.float32),  # running denom
                pltpu.VMEM((KV * P, Hd), jnp.float32),   # running numerator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KV, P, Hd), q.dtype),
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, ck, cv,
      bias.reshape(B, 1, Smax), slopes)
    return out


def decode_attention(q, ck, cv, pos, *, pad_bias=None, alibi_slopes=None,
                     scale: Optional[float] = None,
                     interpret: Optional[bool] = None):
    """Attention of one new token per sequence against the KV cache.

    q ``[B, H, Hd]`` (the single new token's heads, rope already applied);
    ck/cv ``[B, Smax, KV, Hd]`` with the new k/v already written at ``pos``;
    ``pos`` [] int32 — the new token's 0-based position (attends ``<= pos``).
    GQA head h reads kv head ``h // (H // KV)`` (``jnp.repeat`` order).
    Returns ``[B, H, Hd]``.

    Returns None when the shape is outside the kernel's envelope (caller
    falls back to the einsum path): Smax not divisible by the 128 block,
    or head_dim not lane-aligned.
    """
    B, H, Hd = q.shape
    Smax, KV = ck.shape[1], ck.shape[2]
    if H % KV != 0 or Hd % 64 != 0:
        return None
    bk = next((b for b in (512, 256, 128) if Smax % b == 0), None)
    if bk is None:
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    P = H // KV
    scale = Hd**-0.5 if scale is None else scale
    qg = (q * scale).reshape(B, KV, P, Hd)
    if pad_bias is None:
        bias = jnp.zeros((B, Smax), jnp.float32)
    else:
        bias = pad_bias.astype(jnp.float32)
    if alibi_slopes is None:
        slopes = jnp.zeros((KV, P), jnp.float32)
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(KV, P)
    out = _decode_call(qg, ck, cv, pos, bias, slopes, bk=bk,
                       has_bias=pad_bias is not None,
                       has_alibi=alibi_slopes is not None,
                       interpret=bool(interpret))
    return out.reshape(B, H, Hd)
