"""Pallas paged decode attention: block-table KV gather inside the kernel.

The serving-side companion of :mod:`decode_attention` (vLLM PagedAttention
re-expressed for TPU): the KV cache is not one contiguous ``[B, Smax, ...]``
workspace but a POOL of fixed-size blocks ``[num_blocks, block_size, KV, Hd]``
shared by every in-flight request, and each request owns a *block table* —
the list of pool blocks holding its logical token positions. Continuous
batching retires/admits requests per step, so physical KV placement is
arbitrary; the kernel follows the table instead of a dense stride.

Design (mirrors ``decode_attention``, which documents the TPU reasoning):

* grid ``(num_requests, max_blocks_per_request)`` — block index innermost so
  the running (m, l, acc) streaming-softmax scratch carries across a
  request's blocks;
* the k/v BlockSpec index map reads the block table (scalar prefetch) to
  turn the logical block index ``i`` into a pool block id — the gather
  happens in the DMA engine, never materialising a contiguous per-request
  cache copy;
* per-request positions: ``pos[b]`` is the 0-based position of request
  ``b``'s new token (attends ``kpos <= pos[b]``) — requests at different
  depths decode in the same fused step (iteration-level batching);
* the block index is clamped at the request's last live block, so the dead
  tail of the table costs neither DMA nor FLOPs (``pl.when`` guards the
  compute);
* ALiBi slopes and an additive key-side ``pad_bias`` over LOGICAL positions
  keep parity with the dense kernel.

Interpret mode on CPU — the unit tier pins parity vs ``decode_attention``
on randomized block tables.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30


def _kernel(bt_ref, pos_ref, q_ref, k_ref, v_ref, bias_ref, slope_ref, o_ref,
            m_ref, l_ref, acc_ref, *, bs, n_blocks, kv, group,
            has_bias, has_alibi):
    b = pl.program_id(0)
    i = pl.program_id(1)
    pos = pos_ref[b]

    @pl.when(i == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    koff = i * bs
    run = koff <= pos  # whole block beyond the request's prefix → skip

    @pl.when(run)
    def _():
        # LOGICAL key positions of this block — the table gather only moved
        # the physical storage; attention geometry stays logical
        kpos1 = koff + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        if has_bias:
            bias = bias_ref[0, 0][None, :]
        for g in range(kv):
            rows = pl.ds(g * group, group)
            q = q_ref[0, g].astype(jnp.float32)          # [P, Hd] (pre-scaled)
            k = k_ref[0, :, g].astype(jnp.float32)       # [bs, Hd]
            v = v_ref[0, :, g].astype(jnp.float32)       # [bs, Hd]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            kpos = jnp.broadcast_to(kpos1, s.shape)      # [P, bs]
            if has_alibi:
                s = s + slope_ref[g][:, None] * (kpos - pos).astype(jnp.float32)
            if has_bias:
                s = s + bias
            s = jnp.where(kpos <= pos, s, _NEG)

            m_prev = m_ref[rows, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            l_ref[rows, :] = l_ref[rows, :] * alpha[:, None] \
                + jnp.sum(p, axis=1)[:, None]
            m_ref[rows, :] = jnp.broadcast_to(m_new[:, None], (group, 128))
            acc_ref[rows, :] = acc_ref[rows, :] * alpha[:, None] + p @ v

    @pl.when(i == n_blocks - 1)
    def _():
        for g in range(kv):
            rows = pl.ds(g * group, group)
            o_ref[0, g] = (acc_ref[rows, :]
                           / l_ref[rows, 0][:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("bs", "has_bias", "has_alibi",
                                             "interpret"))
def _paged_call(q, kp, vp, bt, pos, bias, slopes, *, bs, has_bias, has_alibi,
                interpret):
    B, KV, P, Hd = q.shape
    n_blocks = bt.shape[1]
    grid = (B, n_blocks)

    # clamp the block index at the request's last LIVE table entry: dead
    # tail iterations revisit that pool block (no re-fetch — same index)
    # and the pl.when guard skips their FLOPs
    def kv_idx(b, i, bt_s, pos_s):
        return (bt_s[b, jnp.minimum(i, pos_s[b] // bs)], 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, KV, P, Hd), lambda b, i, bt_s, pos_s: (b, 0, 0, 0)),
        pl.BlockSpec((1, bs, KV, Hd), kv_idx),
        pl.BlockSpec((1, bs, KV, Hd), kv_idx),
        # bias over LOGICAL positions, [B, n_blocks, bs]: block index follows
        # the clamped logical block (not the pool id)
        pl.BlockSpec((1, 1, bs),
                     lambda b, i, bt_s, pos_s:
                     (b, jnp.minimum(i, pos_s[b] // bs), 0)),
        pl.BlockSpec((KV, P), lambda b, i, bt_s, pos_s: (0, 0)),
    ]
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, n_blocks=n_blocks, kv=KV, group=P,
                          has_bias=has_bias, has_alibi=has_alibi),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, KV, P, Hd),
                                   lambda b, i, bt_s, pos_s: (b, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((KV * P, 128), jnp.float32),  # running max
                pltpu.VMEM((KV * P, 128), jnp.float32),  # running denom
                pltpu.VMEM((KV * P, Hd), jnp.float32),   # running numerator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KV, P, Hd), q.dtype),
        interpret=interpret,
    )(bt, pos, q, kp, vp, bias.reshape(B, bt.shape[1], bs), slopes)
    return out


def paged_envelope_ok(H: int, KV: int, Hd: int, bs: int) -> bool:
    """Whether a (heads, kv_heads, head_dim, block_size) shape sits inside
    the kernel's envelope. The ONE home of the envelope — the transformer's
    shard_map dispatch checks it against PER-SHARD shapes before entering a
    manual region (a shard_map body cannot fall back per-shard), and
    :func:`paged_decode_attention` checks it to decide None-vs-kernel."""
    return H % KV == 0 and Hd % 64 == 0 and bs % 128 == 0


def paged_decode_attention(q, kp, vp, block_tables, pos, *, pad_bias=None,
                           alibi_slopes=None, scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Attention of one new token per request against a PAGED KV cache.

    q ``[B, H, Hd]`` (one new token per running request, rope applied);
    kp/vp ``[num_blocks, block_size, KV, Hd]`` — the shared block pools,
    with each request's new k/v already written at its slot;
    ``block_tables`` ``[B, max_blocks]`` int32 pool block ids (logical block
    ``j`` of request ``b`` lives in pool block ``block_tables[b, j]``; dead
    tail entries may be anything — they are clamped away);
    ``pos`` ``[B]`` int32 per-request 0-based position of the new token
    (request ``b`` attends logical positions ``<= pos[b]``).
    ``pad_bias`` ``[B, max_blocks * block_size]`` additive f32 bias over
    logical positions. GQA head h reads kv head ``h // (H // KV)``.
    Returns ``[B, H, Hd]``.

    Returns None when the shape is outside the kernel's envelope (caller
    falls back to a gather + einsum path): block_size not a multiple of
    128, head_dim not lane-aligned, or H % KV != 0.
    """
    B, H, Hd = q.shape
    bs, KV = kp.shape[1], kp.shape[2]
    if not paged_envelope_ok(H, KV, Hd, bs):
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    P = H // KV
    scale = Hd**-0.5 if scale is None else scale
    qg = (q * scale).reshape(B, KV, P, Hd)
    n_blocks = block_tables.shape[1]
    if pad_bias is None:
        bias = jnp.zeros((B, n_blocks * bs), jnp.float32)
    else:
        bias = pad_bias.astype(jnp.float32)
    if alibi_slopes is None:
        slopes = jnp.zeros((KV, P), jnp.float32)
    else:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(KV, P)
    out = _paged_call(qg, kp, vp,
                      jnp.asarray(block_tables, jnp.int32),
                      jnp.asarray(pos, jnp.int32).reshape(B),
                      bias, slopes, bs=bs,
                      has_bias=pad_bias is not None,
                      has_alibi=alibi_slopes is not None,
                      interpret=bool(interpret))
    return out.reshape(B, H, Hd)
