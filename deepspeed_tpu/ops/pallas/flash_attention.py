"""Pallas flash attention for TPU (training: forward + custom-VJP backward).

Blockwise streaming-softmax attention that never materialises the [S, S]
score matrix: HBM traffic is O(S·Hd) instead of O(S²), q/k/v blocks are
DMA'd into VMEM by the pallas pipeline and every matmul lands on the MXU.
Replaces the reference's fused CUDA attention/softmax kernels
(``csrc/transformer/softmax_kernels.cu``, training layer
``csrc/transformer/ds_transformer_cuda.cpp``; inference ``softmax_context``
in ``csrc/transformer/inference/csrc/pt_binding.cpp``) with the
TPU-idiomatic design.

Grid layout (forward): ``(B, H, Sq/bq, Sk/bk)`` — the kv dimension is
innermost, so the (m, l, acc) running-softmax state lives in VMEM scratch
across kv steps and the output block is written once on the last step.
Backward recomputes p from the saved logsumexp (no S² residuals): one
kernel accumulates dq over kv blocks, a second accumulates dk/dv over q
blocks.

Two VPU optimisations matter on TPU (softmax is VPU-bound while the dots
ride the MXU):

* the streaming softmax runs in the **log2 domain** (logits pre-scaled by
  log2(e), ``exp2`` instead of ``exp``) — the VPU evaluates exp2 faster;
* the common case (causal, no user mask, no alibi, no padding) takes a
  **plain fast path**: fully-visible blocks below the diagonal skip masking
  entirely, and diagonal blocks add one precomputed triangular bias block
  instead of running per-element iota/compare/select.

The kernel's forward outputs (o, lse) carry ``checkpoint_name`` tags
("flash_o"/"flash_lse") so activation-checkpoint policies (e.g. the model
zoo's ``remat="selective"``) can save the attention residuals and run the
backward kernels without re-running the forward kernel.

Supports causal masking, an additive key-side mask bias [B, S], and ALiBi
slopes. Runs compiled on TPU, interpreted elsewhere (CPU unit tests).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASKED = -1e30  # large-negative for masked logits (exp2 underflows to 0)
_LOG2E = 1.4426950408889634


def _block_bias(qoff, koff, bq, bk, seq_len, causal, slope, mask_blk):
    """Additive log2-domain bias for a (bq, bk) score block from GLOBAL
    positions: alibi + causal/pad masking + user key mask."""
    qpos = qoff + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = koff + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    bias = (slope * _LOG2E) * (kpos - qpos).astype(jnp.float32)  # slope==0 → no-op
    valid = kpos < seq_len
    if causal:
        valid = valid & (qpos >= kpos)
    bias = jnp.where(valid, bias, _MASKED)
    return bias + mask_blk[None, :] * _LOG2E


def _dispatch(run, i, j, plain, causal, update, logits, tri_ref, bias):
    """Apply ``update`` to the block's log2-domain logits with the cheapest
    masking that is correct: nothing for fully-visible plain blocks, one
    precomputed triangular block on the plain diagonal (i == j), or the
    general computed bias. Shared by the forward and both backward kernels."""
    if plain and causal:
        @pl.when(jnp.logical_and(run, i == j))
        def _():
            update(logits() + tri_ref[:])

        @pl.when(jnp.logical_and(run, i != j))
        def _():
            update(logits())
    elif plain:
        @pl.when(run)
        def _():
            update(logits())
    else:
        @pl.when(run)
        def _():
            update(logits() + bias())


def _make_tri(bq, bk):
    """Precomputed (bq, bk) diagonal-block causal bias: 0 keep / -1e30 drop."""
    r = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(r >= c, 0.0, _MASKED).astype(jnp.float32)


def _packed_dispatch(run, i, j, causal, step, logits, tri_ref, P):
    """Packed-kernel analogue of :func:`_dispatch`: per packed head p, run
    ``step(p, logits(p) [+ tri])`` with the diagonal tri only where needed."""
    if causal:
        @pl.when(jnp.logical_and(run, i == j))
        def _():
            for p in range(P):
                step(p, logits(p) + tri_ref[:])

        @pl.when(jnp.logical_and(run, i != j))
        def _():
            for p in range(P):
                step(p, logits(p))
    else:
        @pl.when(run)
        def _():
            for p in range(P):
                step(p, logits(p))


def _parse_rest(rest, plain, has_layout):
    idx = 0
    tri_ref = None
    if plain:
        tri_ref, idx = rest[0], 1
    layout_ref = None
    if has_layout:
        layout_ref, idx = rest[idx], idx + 1
    return tri_ref, layout_ref, rest[idx:]


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, slope_ref, *rest,
                scale, causal, seq_len, bq, bk, plain, has_layout):
    tri_ref, layout_ref, (o_ref, lse_ref, m_scr, l_scr, acc_scr) = \
        _parse_rest(rest, plain, has_layout)
    # refs (leading dims squeezed): q/o (bq, Hd); k/v (bk, Hd); mask (bk,);
    # lse (bq,); slope (1, 1) in SMEM
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    i = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _MASKED)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qoff, koff = i * bq, j * bk
    # skip blocks above the causal diagonal AND blocks the sparsity layout
    # zeroes out (block-sparse attention, reference ops/sparse_attention/)
    needed = True if not causal else (koff <= qoff + bq - 1)
    run = needed if layout_ref is None else jnp.logical_and(needed, layout_ref[0, 0] > 0)

    def logits():
        # keep q/k in their storage dtype (bf16) for the MXU dot — f32
        # operands run at a fraction of the MXU's bf16 rate; f32 accumulate
        return jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * (scale * _LOG2E)

    def update(s):
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        p = jnp.exp2(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    _dispatch(run, i, j, plain, causal, update, logits, tri_ref,
              lambda: _block_bias(qoff, koff, bq, bk, seq_len, causal,
                                  slope_ref[0, 0], mask_ref[0].astype(jnp.float32)))

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[:, :1]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[:] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # log2-domain "safe" logsumexp: +big for fully-masked rows so bwd
        # p=exp2(s-lse)=0
        lse_ref[0] = jnp.where(l[:, 0] > 0, m_scr[:, 0] + jnp.log2(safe_l[:, 0]), -_MASKED)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref, slope_ref,
               *rest, scale, causal, seq_len, bq, bk, plain, has_layout):
    tri_ref, layout_ref, (dq_ref, dq_scr) = _parse_rest(rest, plain, has_layout)
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    i = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    qoff, koff = i * bq, j * bk
    needed = True if not causal else (koff <= qoff + bq - 1)
    run = needed if layout_ref is None else jnp.logical_and(needed, layout_ref[0, 0] > 0)

    def logits():
        return jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * (scale * _LOG2E)

    def update(s):
        p = jnp.exp2(s - lse_ref[0][:, None])
        dp = jax.lax.dot_general(do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0][:, None]) * scale).astype(k_ref.dtype)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k_ref[:], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _dispatch(run, i, j, plain, causal, update, logits, tri_ref,
              lambda: _block_bias(qoff, koff, bq, bk, seq_len, causal,
                                  slope_ref[0, 0], mask_ref[0].astype(jnp.float32)))

    @pl.when(j == nk - 1)
    def _():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref, slope_ref,
                *rest, scale, causal, seq_len, bq, bk, plain, has_layout):
    tri_ref, layout_ref, (dk_ref, dv_ref, dk_scr, dv_scr) = \
        _parse_rest(rest, plain, has_layout)
    # grid (B, KV, nk, G, nq): q blocks innermost, then the G query heads of
    # the kv group — dk/dv for one kv block accumulate in scratch across BOTH
    # inner axes, which is what makes the kernel GQA-native (kv gradients sum
    # over the group's query heads without ever materialising repeated kv)
    i = pl.program_id(4)
    nq = pl.num_programs(4)
    g = pl.program_id(3)
    ng = pl.num_programs(3)
    j = pl.program_id(2)

    @pl.when(jnp.logical_and(i == 0, g == 0))
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    qoff, koff = i * bq, j * bk
    needed = True if not causal else (koff <= qoff + bq - 1)
    run = needed if layout_ref is None else jnp.logical_and(needed, layout_ref[0, 0] > 0)

    def logits():
        return jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * (scale * _LOG2E)

    def update(s):
        p = jnp.exp2(s - lse_ref[0][:, None]).astype(do_ref.dtype)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p, do_ref[:], (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[:], v_ref[:],
                                 (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p.astype(jnp.float32) * (dp - delta_ref[0][:, None]) * scale).astype(q_ref.dtype)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q_ref[:], (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _dispatch(run, i, j, plain, causal, update, logits, tri_ref,
              lambda: _block_bias(qoff, koff, bq, bk, seq_len, causal,
                                  slope_ref[0, 0], mask_ref[0].astype(jnp.float32)))

    @pl.when(jnp.logical_and(i == nq - 1, g == ng - 1))
    def _():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


# --------------------------------------------------------------------- #
# packed-heads layout (Hd < 128): q/k/v stay [B, S, H*Hd] — the natural
# projection output layout — and each program covers P = 128//Hd heads, so
# every VMEM block is a full 128-lane tile (no lane padding) and NO XLA-side
# transpose is needed on inputs or outputs in either pass. Plain-causal
# only; masked/alibi/sparse shapes use the general [B, H, S, Hd] kernels.

def _packed_fwd_kernel(q_ref, k_ref, v_ref, tri_ref, o_ref, lse_ref,
                       m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, P, Hd):
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    i = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _MASKED)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = True if not causal else (j * bk <= i * bq + bq - 1)

    def step(p, s):
        sl = slice(p * Hd, (p + 1) * Hd)
        m_prev = m_scr[:, p * Hd:p * Hd + 1]
        l_prev = l_scr[:, p * Hd:p * Hd + 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        pmat = jnp.exp2(s - m_new)
        l_new = l_prev * alpha + jnp.sum(pmat, axis=1, keepdims=True)
        acc_scr[:, sl] = acc_scr[:, sl] * alpha + jax.lax.dot_general(
            pmat.astype(v_ref.dtype), v_ref[:, sl], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:, sl] = jnp.broadcast_to(m_new, (m_new.shape[0], Hd))
        l_scr[:, sl] = jnp.broadcast_to(l_new, (l_new.shape[0], Hd))

    def logits(p):
        sl = slice(p * Hd, (p + 1) * Hd)
        return jax.lax.dot_general(q_ref[:, sl], k_ref[:, sl], (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * (scale * _LOG2E)

    _packed_dispatch(run, i, j, causal, step, logits, tri_ref, P)

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[:]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[:] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        for p in range(P):
            c = p * Hd
            lse_ref[p] = jnp.where(l[:, c] > 0, m_scr[:, c] + jnp.log2(safe_l[:, c]),
                                   -_MASKED)


def _packed_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, tri_ref,
                      dq_ref, dq_scr, *, scale, causal, bq, bk, P, Hd):
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    i = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True if not causal else (j * bk <= i * bq + bq - 1)

    def logits(p):
        sl = slice(p * Hd, (p + 1) * Hd)
        return jax.lax.dot_general(q_ref[:, sl], k_ref[:, sl], (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * (scale * _LOG2E)

    def step(p, s):
        sl = slice(p * Hd, (p + 1) * Hd)
        pmat = jnp.exp2(s - lse_ref[p][:, None])
        dp = jax.lax.dot_general(do_ref[:, sl], v_ref[:, sl], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (pmat * (dp - delta_ref[p][:, None]) * scale).astype(k_ref.dtype)
        dq_scr[:, sl] = dq_scr[:, sl] + jax.lax.dot_general(
            ds, k_ref[:, sl], (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _packed_dispatch(run, i, j, causal, step, logits, tri_ref, P)

    @pl.when(j == nk - 1)
    def _():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _packed_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, tri_ref,
                       dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, bq, bk, P, Hd):
    # grid (B, H2, nk, nq): q blocks innermost
    i = pl.program_id(3)
    nq = pl.num_programs(3)
    j = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True if not causal else (j * bk <= i * bq + bq - 1)

    def logits(p):
        sl = slice(p * Hd, (p + 1) * Hd)
        return jax.lax.dot_general(q_ref[:, sl], k_ref[:, sl], (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32) * (scale * _LOG2E)

    def step(p, s):
        sl = slice(p * Hd, (p + 1) * Hd)
        pmat = jnp.exp2(s - lse_ref[p][:, None]).astype(do_ref.dtype)
        dv_scr[:, sl] = dv_scr[:, sl] + jax.lax.dot_general(
            pmat, do_ref[:, sl], (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[:, sl], v_ref[:, sl], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (pmat.astype(jnp.float32) * (dp - delta_ref[p][:, None]) * scale).astype(q_ref.dtype)
        dk_scr[:, sl] = dk_scr[:, sl] + jax.lax.dot_general(
            ds, q_ref[:, sl], (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    _packed_dispatch(run, i, j, causal, step, logits, tri_ref, P)

    @pl.when(i == nq - 1)
    def _():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


@functools.lru_cache(maxsize=32)
def _build_packed(causal: bool, scale: float, bq: int, bk: int, interpret: bool,
                  P: int, Hd: int):
    """Custom-VJP flash on [B, S, H*Hd] inputs, P heads per program."""
    lanes = P * Hd

    def xq_spec():
        # block (bq, P*Hd) over [B, S, D] at head-group h
        return pl.BlockSpec((None, bq, lanes), lambda b, h, i, j: (b, i, h))

    def xkv_spec():
        return pl.BlockSpec((None, bk, lanes), lambda b, h, i, j: (b, j, h))

    tri_spec = pl.BlockSpec((bq, bk), lambda b, h, i, j: (0, 0))
    row_spec = pl.BlockSpec((None, None, P, bq), lambda b, h, i, j: (b, h, 0, i))

    def fwd_call(q, k, v, tri):
        B, Sp, D = q.shape
        H2 = D // lanes
        nq, nk = Sp // bq, Sp // bk
        kernel = functools.partial(_packed_fwd_kernel, scale=scale, causal=causal,
                                   bq=bq, bk=bk, P=P, Hd=Hd)
        o, lse = pl.pallas_call(
            kernel,
            grid=(B, H2, nq, nk),
            in_specs=[xq_spec(), xkv_spec(), xkv_spec(), tri_spec],
            out_specs=[xq_spec(), row_spec],
            out_shape=[
                jax.ShapeDtypeStruct((B, Sp, D), q.dtype),
                jax.ShapeDtypeStruct((B, H2, P, Sp), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, lanes), jnp.float32),
                pltpu.VMEM((bq, lanes), jnp.float32),
                pltpu.VMEM((bq, lanes), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, tri)
        return checkpoint_name(o, "flash_o"), checkpoint_name(lse, "flash_lse")

    @jax.custom_vjp
    def flash(q, k, v, tri):
        return fwd_call(q, k, v, tri)[0]

    def flash_fwd(q, k, v, tri):
        o, lse = fwd_call(q, k, v, tri)
        return o, (q, k, v, tri, o, lse)

    def flash_bwd(res, g):
        q, k, v, tri, o, lse = res
        B, Sp, D = q.shape
        H2 = D // lanes
        nq, nk = Sp // bq, Sp // bk
        # per-head delta rows: sum g*o over each head's lane group
        delta = (g.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
            B, Sp, H2, P, Hd).sum(-1).transpose(0, 2, 3, 1)  # [B, H2, P, Sp]

        dq_kernel = functools.partial(_packed_dq_kernel, scale=scale, causal=causal,
                                      bq=bq, bk=bk, P=P, Hd=Hd)
        dq = pl.pallas_call(
            dq_kernel,
            grid=(B, H2, nq, nk),
            in_specs=[xq_spec(), xkv_spec(), xkv_spec(), xq_spec(),
                      row_spec, row_spec, tri_spec],
            out_specs=xq_spec(),
            out_shape=jax.ShapeDtypeStruct((B, Sp, D), q.dtype),
            scratch_shapes=[pltpu.VMEM((bq, lanes), jnp.float32)],
            interpret=interpret,
        )(q, k, v, g, lse, delta, tri)

        kq_spec = pl.BlockSpec((None, bq, lanes), lambda b, h, j, i: (b, i, h))
        kkv_spec = pl.BlockSpec((None, bk, lanes), lambda b, h, j, i: (b, j, h))
        krow_spec = pl.BlockSpec((None, None, P, bq), lambda b, h, j, i: (b, h, 0, i))
        ktri_spec = pl.BlockSpec((bq, bk), lambda b, h, j, i: (0, 0))

        dkv_kernel = functools.partial(_packed_dkv_kernel, scale=scale, causal=causal,
                                       bq=bq, bk=bk, P=P, Hd=Hd)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(B, H2, nk, nq),
            in_specs=[kq_spec, kkv_spec, kkv_spec, kq_spec, krow_spec, krow_spec,
                      ktri_spec],
            out_specs=[kkv_spec, kkv_spec],
            out_shape=[
                jax.ShapeDtypeStruct((B, Sp, D), q.dtype),
                jax.ShapeDtypeStruct((B, Sp, D), q.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, lanes), jnp.float32),
                pltpu.VMEM((bk, lanes), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, g, lse, delta, tri)

        return dq, dk, dv, jnp.zeros_like(tri)

    flash.defvjp(flash_fwd, flash_bwd)
    return flash


def _q_spec(bq, Hd):
    return pl.BlockSpec((None, None, bq, Hd), lambda b, h, i, j: (b, h, i, 0))


def _kv_spec(bk, Hd, G=1):
    # GQA: query head h reads kv head h // G — the index map IS the repeat,
    # so the group's shared kv block is DMA'd once per program with no
    # H/KV-times-larger HBM copy (replaces the jnp.repeat the dispatch
    # used to do; reference analogue: softmax_context's kv-head indexing in
    # csrc/transformer/inference/csrc/pt_binding.cpp)
    return pl.BlockSpec((None, None, bk, Hd), lambda b, h, i, j: (b, h // G, j, 0))


def _row_spec(bq):
    # rows ride as [B, H, 1, Sp] so the trailing block dims (1, bq) tile
    return pl.BlockSpec((None, None, 1, bq), lambda b, h, i, j: (b, h, 0, i))


def _mask_spec(bk):
    # mask rides as [B, 1, Sp]
    return pl.BlockSpec((None, 1, bk), lambda b, h, i, j: (b, 0, j))


def _slope_spec():
    # slopes ride as [H, 8, 128] (value broadcast) so each head's block
    # meets the (8, 128) tile minimum; kernels read slope_ref[0, 0]
    return pl.BlockSpec((None, 8, 128), lambda b, h, i, j: (h, 0, 0))


def _tri_spec(bq, bk):
    # the (bq, bk) diagonal-block causal bias, same block for every program
    return pl.BlockSpec((bq, bk), lambda b, h, i, j: (0, 0))


def _layout_spec():
    # block layout rides as [H, nq*8, nk*128] f32 (each (h,i,j) entry
    # broadcast over an (8,128) tile); kernels read layout_ref[0, 0]
    return pl.BlockSpec((None, 8, 128), lambda b, h, i, j: (h, i, j))


@functools.lru_cache(maxsize=32)
def _build(causal: bool, scale: float, bq: int, bk: int, seq_len: int, interpret: bool,
           has_layout: bool = False, plain: bool = False, kv_group: int = 1):
    """Build the custom-VJP flash function for one static configuration.

    Operates on padded [B, H, Sp, Hd] q / [B, KV, Sp, Hd] k,v
    (KV = H // kv_group; GQA is native — query head h reads kv head
    h // kv_group via the BlockSpec index map), mask [B, Sp] additive f32,
    slopes [H, 1] f32 (zeros ⇒ no alibi). ``plain`` is the no-mask/no-alibi/
    no-padding fast path (tri = precomputed diagonal-block causal bias).
    """

    G = kv_group
    maybe_tri = [_tri_spec(bq, bk)] if plain else []
    maybe_layout = [_layout_spec()] if has_layout else []
    statics = dict(scale=scale, causal=causal, seq_len=seq_len, bq=bq, bk=bk,
                   plain=plain, has_layout=has_layout)

    def fwd_call(q, k, v, mask, slopes, *extra):
        B, H, Sp, Hd = q.shape
        nq, nk = Sp // bq, Sp // bk
        kernel = functools.partial(_fwd_kernel, **statics)
        o, lse = pl.pallas_call(
            kernel,
            grid=(B, H, nq, nk),
            in_specs=[_q_spec(bq, Hd), _kv_spec(bk, Hd, G), _kv_spec(bk, Hd, G),
                      _mask_spec(bk), _slope_spec()] + maybe_tri + maybe_layout,
            out_specs=[_q_spec(bq, Hd), _row_spec(bq)],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, Sp, Hd), q.dtype),
                jax.ShapeDtypeStruct((B, H, 1, Sp), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, Hd), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, mask, slopes, *extra)
        # named so remat policies can save the attention residuals and skip
        # re-running the forward kernel inside the backward pass
        return checkpoint_name(o, "flash_o"), checkpoint_name(lse, "flash_lse")

    @jax.custom_vjp
    def flash(q, k, v, mask, slopes, *extra):
        return fwd_call(q, k, v, mask, slopes, *extra)[0]

    def flash_fwd(q, k, v, mask, slopes, *extra):
        o, lse = fwd_call(q, k, v, mask, slopes, *extra)
        return o, (q, k, v, mask, slopes, extra, o, lse)

    def bwd_impl(res, g, glse):
        """Shared backward: ``glse`` (cotangent of the log2-domain lse
        [B, H, 1, Sp], or None) folds into delta — d s_k gains
        p_k * d lse_nat and lse2 = log2(e) * lse_nat, so
        delta' = delta - log2(e) * glse reuses the dq/dkv kernels unchanged."""
        q, k, v, mask, slopes, extra, o, lse = res
        B, H, Sp, Hd = q.shape
        nq, nk = Sp // bq, Sp // bk
        delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[:, :, None, :]
        if glse is not None:
            delta = delta - _LOG2E * glse.astype(jnp.float32)

        dq_kernel = functools.partial(_dq_kernel, **statics)
        dq = pl.pallas_call(
            dq_kernel,
            grid=(B, H, nq, nk),
            in_specs=[_q_spec(bq, Hd), _kv_spec(bk, Hd, G), _kv_spec(bk, Hd, G),
                      _q_spec(bq, Hd), _row_spec(bq), _row_spec(bq),
                      _mask_spec(bk), _slope_spec()] + maybe_tri + maybe_layout,
            out_specs=_q_spec(bq, Hd),
            out_shape=jax.ShapeDtypeStruct((B, H, Sp, Hd), q.dtype),
            scratch_shapes=[pltpu.VMEM((bq, Hd), jnp.float32)],
            interpret=interpret,
        )(q, k, v, g, lse, delta, mask, slopes, *extra)

        # grid (B, KV, nk, G, nq): q blocks innermost, then the group's query
        # heads — one dk/dv block accumulates across both in scratch
        KV = H // G
        kq_spec = pl.BlockSpec((None, None, bq, Hd),
                               lambda b, kv, j, gg, i: (b, kv * G + gg, i, 0))
        kk_spec = pl.BlockSpec((None, None, bk, Hd),
                               lambda b, kv, j, gg, i: (b, kv, j, 0))
        krow_spec = pl.BlockSpec((None, None, 1, bq),
                                 lambda b, kv, j, gg, i: (b, kv * G + gg, 0, i))
        kmask_spec = pl.BlockSpec((None, 1, bk), lambda b, kv, j, gg, i: (b, 0, j))
        kslope_spec = pl.BlockSpec((None, 8, 128),
                                   lambda b, kv, j, gg, i: (kv * G + gg, 0, 0))
        kmaybe_tri = ([pl.BlockSpec((bq, bk), lambda b, kv, j, gg, i: (0, 0))]
                      if plain else [])
        kmaybe_layout = ([pl.BlockSpec((None, 8, 128),
                                       lambda b, kv, j, gg, i: (kv * G + gg, i, j))]
                         if has_layout else [])

        dkv_kernel = functools.partial(_dkv_kernel, **statics)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid=(B, KV, nk, G, nq),
            in_specs=[kq_spec, kk_spec, kk_spec, kq_spec, krow_spec, krow_spec,
                      kmask_spec, kslope_spec] + kmaybe_tri + kmaybe_layout,
            out_specs=[kk_spec, kk_spec],
            out_shape=[
                jax.ShapeDtypeStruct((B, KV, Sp, Hd), q.dtype),
                jax.ShapeDtypeStruct((B, KV, Sp, Hd), q.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, Hd), jnp.float32),
                pltpu.VMEM((bk, Hd), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, g, lse, delta, mask, slopes, *extra)

        return (dq, dk, dv, jnp.zeros_like(mask), jnp.zeros_like(slopes),
                *(jnp.zeros_like(l) for l in extra))

    def flash_bwd(res, g):
        return bwd_impl(res, g, None)

    flash.defvjp(flash_fwd, flash_bwd)

    # (o, lse) variant for callers that combine partial attentions across
    # blocks (ring attention): lse is the raw log2-domain [B, H, 1, Sp]
    # kernel output; its cotangent rides the same backward kernels
    @jax.custom_vjp
    def flash_lse(q, k, v, mask, slopes, *extra):
        return fwd_call(q, k, v, mask, slopes, *extra)

    def flash_lse_fwd(q, k, v, mask, slopes, *extra):
        o, lse = fwd_call(q, k, v, mask, slopes, *extra)
        return (o, lse), (q, k, v, mask, slopes, extra, o, lse)

    def flash_lse_bwd(res, cot):
        g, glse = cot
        return bwd_impl(res, g, glse)

    flash_lse.defvjp(flash_lse_fwd, flash_lse_bwd)
    return flash, flash_lse


def flash_attention(q, k, v, mask_bias=None, causal: bool = True, alibi_slopes=None,
                    scale: Optional[float] = None, block_q: Optional[int] = None,
                    block_k: Optional[int] = None, block_layout=None,
                    interpret: Optional[bool] = None, return_lse: bool = False):
    """Flash attention on [B, S, H, Hd] q/k/v (same contract as
    :func:`deepspeed_tpu.ops.attention.mha_attention`; mask_bias is the
    additive key-side [B, S] bias). Pads S up to the block size internally.

    ``block_layout``: optional [H, nb, nb] 0/1 block-sparsity layout (from
    :mod:`deepspeed_tpu.ops.sparse_attention`); the kernel block size then
    follows the layout's block size S/nb, and zero blocks are skipped in
    forward AND backward — true block-sparse flash attention.

    GQA is native: k/v may carry KV = H / group kv heads ([B, S, KV, Hd]);
    query head h attends kv head ``h // (H // KV)`` (``jnp.repeat`` order)
    via BlockSpec index maps — no repeated kv copy in HBM or VMEM, and
    dk/dv come back at [B, S, KV, Hd] (summed over the group in-kernel).

    ``return_lse=True`` returns ``(out, lse)`` with lse the **log2-domain**
    logsumexp [B, H, S] (fully-masked rows carry +1e30); both outputs are
    differentiable — ring attention combines partial blocks through it.
    Uses the general kernel (no packed-heads fast path).
    """
    B, S, H, Hd = q.shape
    KV = k.shape[2]
    if H % KV:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KV}")
    kv_group = H // KV
    scale = float(scale if scale is not None else Hd**-0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # default blocks: one program per (b, h) when the whole sequence fits
    # (fewest program launches — measured fastest at S ≤ 1024); for longer
    # sequences 1024² blocks: chip-measured 8.7% faster than 512² at S=2048
    # on the GQA bench shape (fewer launches beats the finer causal
    # block-skip), while the f32 logits tile (4 MB) still fits VMEM at any
    # S — EXCEPT when 1024 would pad the sequence more than 512 does
    # (e.g. S=1536/2560), where the extra causal-legal padded rows cost
    # more than the launch savings
    if block_q is None or block_k is None:
        _s8 = -(-max(8, S) // 8) * 8
        if _s8 <= 1024:
            _default = _s8            # whole sequence, 8-aligned, one block
        else:
            _default = 1024 if (-(-S // 1024)) * 1024 <= (-(-S // 512)) * 512 \
                else 512
        block_q = block_q or _default
        block_k = block_k or _default

    if block_layout is not None:
        nb = block_layout.shape[-1]
        if S % nb != 0:
            raise ValueError(f"seq len {S} not divisible by layout blocks {nb}")
        lb = S // nb
        if lb < 8 or lb % 8 != 0:
            raise ValueError(
                f"layout block size {lb} (= S/{nb}) must be a multiple of 8 for "
                f"TPU tiling; use a coarser SparsityConfig block")
        block_q = block_k = lb

    # block sizes: multiples of 8 (TPU sublane tiling) — unaligned S gets a
    # single rounded-up block absorbed by the padding below
    s8 = -(-max(8, S) // 8) * 8
    bq = min(block_q, s8)
    bk = min(block_k, s8)
    if block_layout is None:
        # when the sequence spans multiple blocks, the (1, bq)/(1, bk) row
        # and mask blocks tile the lane dim and must be 128-aligned (the
        # layout path instead requires bq == the layout's block size)
        if s8 > bq and bq % 128:
            bq = -(-bq // 128) * 128
        if s8 > bk and bk % 128:
            bk = -(-bk // 128) * 128
    # pad S to a common multiple of both block sizes
    lcm = bq * bk // _gcd(bq, bk)
    Sp = -(-S // lcm) * lcm

    # fast path: no user mask, no alibi, no sparsity layout, no padding —
    # masking reduces to one precomputed triangular bias on diagonal blocks
    plain = (mask_bias is None and alibi_slopes is None and block_layout is None
             and Sp == S and (not causal or bq == bk))

    # packed-heads fastest path: small head_dim packs P heads into one full
    # 128-lane tile and q/k/v stay in their natural [B, S, H*Hd] layout —
    # no transposes, no lane padding, P× fewer programs. MHA only: GQA's
    # shared kv heads break the per-head lane-group pairing, and GQA models
    # are Hd=128-class anyway (general kernel, zero lane padding)
    if (plain and kv_group == 1 and not return_lse and Hd < 128
            and 128 % Hd == 0 and H % (128 // Hd) == 0):
        P128 = 128 // Hd
        fn = _build_packed(causal, scale, bq, bk, interpret, P128, Hd)
        tri = _make_tri(bq, bk)
        out = fn(q.reshape(B, S, H * Hd), k.reshape(B, S, H * Hd),
                 v.reshape(B, S, H * Hd), tri)
        return out.reshape(B, S, H, Hd)

    def pad_s(x, axis):
        if Sp == S:
            return x
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, Sp - S)
        return jnp.pad(x, widths)

    qt = pad_s(jnp.transpose(q, (0, 2, 1, 3)), 2)
    kt = pad_s(jnp.transpose(k, (0, 2, 1, 3)), 2)
    vt = pad_s(jnp.transpose(v, (0, 2, 1, 3)), 2)

    mask = (jnp.zeros((B, 1, Sp), jnp.float32) if mask_bias is None
            else pad_s(mask_bias.astype(jnp.float32), 1)[:, None, :])
    slopes = (jnp.zeros((H,), jnp.float32) if alibi_slopes is None
              else jnp.asarray(alibi_slopes, jnp.float32).reshape(H))
    slopes = jnp.broadcast_to(slopes[:, None, None], (H, 8, 128))

    extra = ()
    if plain:
        extra = (_make_tri(bq, bk),)
    if block_layout is not None:
        nq, nk = Sp // bq, Sp // bk
        layout = jnp.asarray(block_layout, jnp.float32)
        if layout.ndim == 2:
            layout = jnp.broadcast_to(layout[None], (H,) + layout.shape)
        # pad blocks (attend nowhere / never attended)
        layout = jnp.pad(layout, ((0, 0), (0, nq - layout.shape[1]), (0, nk - layout.shape[2])))
        # each (h,i,j) entry broadcast over an (8,128) tile for BlockSpec tiling
        layout = jnp.repeat(jnp.repeat(layout, 8, axis=1), 128, axis=2)
        extra = extra + (layout,)

    fn, fn_lse = _build(causal, scale, bq, bk, S, interpret, block_layout is not None,
                        plain, kv_group)
    if return_lse:
        out, lse = fn_lse(qt, kt, vt, mask, slopes, *extra)
        return (jnp.transpose(out[:, :, :S, :], (0, 2, 1, 3)),
                lse[:, :, 0, :S])
    out = fn(qt, kt, vt, mask, slopes, *extra)
    return jnp.transpose(out[:, :, :S, :], (0, 2, 1, 3))


def _gcd(a: int, b: int) -> int:
    while b:
        a, b = b, a % b
    return a
