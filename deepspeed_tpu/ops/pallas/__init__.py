"""Pallas TPU kernels — the device-side native-op tranche.

TPU-native replacements for the reference's CUDA kernel families
(SURVEY.md §2.2): attention/softmax (``csrc/transformer/softmax_kernels.cu``,
inference ``softmax_context``) → :mod:`flash_attention`; the vocab head's
fused softmax-xent (``csrc/transformer/inference`` fused logits) →
:mod:`fused_cross_entropy`; quantization with stochastic rounding
(``csrc/quantization/``) → :mod:`quantization`; fused optimizer step
(``csrc/adam/multi_tensor_adam.cu``) → :mod:`fused_adam`.

Every kernel runs compiled on TPU and in interpreter mode on CPU (that is
what the unit suite exercises); the wrappers pick automatically.
"""

from deepspeed_tpu.ops.pallas.decode_attention import decode_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
from deepspeed_tpu.ops.pallas.fused_cross_entropy import fused_cross_entropy
from deepspeed_tpu.ops.pallas.paged_decode_attention import \
    paged_decode_attention

__all__ = ["decode_attention", "flash_attention", "fused_cross_entropy",
           "paged_decode_attention"]
