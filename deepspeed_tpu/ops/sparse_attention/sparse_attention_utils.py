"""Sparse-attention integration utilities.

Reference parity: ``deepspeed/ops/sparse_attention/sparse_attention_utils.py``
(``SparseAttentionUtils``) — padding inputs to the sparsity block size,
extending position embeddings for longer sequences, and swapping a model's
self-attention for sparse self-attention.

TPU redesign: the zoo models are functional, so "module surgery" becomes a
config replacement (``replace_self_attention`` returns a new model whose
``TransformerConfig.sparse_attention`` carries the layout — every layer then
dispatches through ``models/transformer.py::_sparse_model_attention``), and
position extension is a pure params transform.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np


def pad_to_block_size(block_size: int, input_ids, attention_mask=None,
                      token_type_ids=None, pad_token_id: int = 0,
                      ) -> Tuple[int, Any, Any, Any]:
    """Pad [B, S] inputs along the sequence to a multiple of ``block_size``
    (reference ``SparseAttentionUtils.pad_to_block_size``). Padded positions
    get ``pad_token_id`` and attention_mask 0 (a mask is synthesised if the
    caller had none, so the pad tokens never attend). Returns
    ``(pad_len, input_ids, attention_mask, token_type_ids)``."""
    S = input_ids.shape[1]
    pad_len = (-S) % block_size
    if pad_len == 0:
        return 0, input_ids, attention_mask, token_type_ids
    if attention_mask is None:
        attention_mask = jnp.ones(input_ids.shape, jnp.int32)
    widths = ((0, 0), (0, pad_len))
    input_ids = jnp.pad(input_ids, widths, constant_values=pad_token_id)
    attention_mask = jnp.pad(attention_mask, widths)
    if token_type_ids is not None:
        token_type_ids = jnp.pad(token_type_ids, widths)
    return pad_len, input_ids, attention_mask, token_type_ids


def unpad_sequence_output(pad_len: int, sequence_output):
    """Strip the padding added by :func:`pad_to_block_size` from a
    [B, S, ...] output (reference ``unpad_sequence_output``)."""
    if pad_len == 0:
        return sequence_output
    return sequence_output[:, :-pad_len]


def extend_position_embedding(params: Dict, new_max_seq: int,
                              path: Tuple[str, ...] = ("embed", "positions")):
    """Extend learned position embeddings to ``new_max_seq`` by repeating
    the trained table (reference ``extend_position_embedding``, which tiles
    BERT/RoBERTa weights k-fold). Returns a NEW params tree; the caller must
    also rebuild the model with ``max_seq=new_max_seq`` (functional configs
    replace the reference's in-place ``config.max_position_embeddings``
    mutation)."""
    sub = params
    for key in path[:-1]:
        sub = sub[key]
    old = np.asarray(sub[path[-1]])
    P, D = old.shape
    if new_max_seq <= P:
        raise ValueError(f"new_max_seq={new_max_seq} does not exceed the "
                         f"current table ({P})")
    reps = -(-new_max_seq // P)
    new = np.tile(old, (reps, 1))[:new_max_seq]

    def rebuild(tree, keys):
        if not keys:
            return jnp.asarray(new)
        out = dict(tree)
        out[keys[0]] = rebuild(tree[keys[0]], keys[1:])
        return out

    return rebuild(params, list(path))


def replace_self_attention(model, sparsity_config,
                           max_seq: Optional[int] = None):
    """Return a new model whose every layer runs block-sparse attention over
    ``sparsity_config``'s layout (reference
    ``replace_model_self_attention_with_sparse_self_attention``). Supports
    the zoo ``CausalLM`` and ``BertModel`` families; ``max_seq`` optionally
    raises the sequence limit at the same time (pair with
    :func:`extend_position_embedding`)."""
    from deepspeed_tpu.models.bert import BertModel
    from deepspeed_tpu.models.causal_lm import CausalLM

    if isinstance(model, BertModel):
        bc = model.config
        if max_seq is not None:
            bc = dataclasses.replace(bc, max_seq=max_seq)
        out = BertModel(bc, with_mlm_head=model.with_mlm_head)
        out.zoo_cfg = dataclasses.replace(out.zoo_cfg,
                                          sparse_attention=sparsity_config)
        return out
    if isinstance(model, CausalLM):
        from deepspeed_tpu.models.pipeline import PipelinedCausalLM
        cfg = model.config
        over = {"sparse_attention": sparsity_config}
        if max_seq is not None:
            over["max_seq"] = max_seq
        cfg = dataclasses.replace(cfg, **over)
        if isinstance(model, PipelinedCausalLM):
            return type(model)(cfg, model.num_stages,
                               param_dtype=model.param_dtype)
        return type(model)(cfg, model.param_dtype)
    raise TypeError(f"cannot sparsify {type(model).__name__}; expected a zoo "
                    "CausalLM or BertModel")
