"""Block-sparsity layout configs (reference
``ops/sparse_attention/sparsity_config.py`` — Dense / Fixed / Variable /
BigBird / BSLongformer / LocalSlidingWindow).

Each config produces ``make_layout(seq_len) → [num_heads, nb, nb]`` int32
(1 = attend). The reference feeds these layouts to Triton block-sparse
kernels; here they feed the Pallas block-sparse flash kernel
(``flash_attention(block_layout=...)``) or the dense-mask fallback. Default
``block=128`` (vs the reference's 16): MXU tiles are 128-wide, so smaller
blocks waste the systolic array.
"""

from __future__ import annotations

import random
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base (reference ``:34``): head count, block size, per-head layouts."""

    def __init__(self, num_heads: int, block: int = 128, different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq len {seq_len} must be divisible by block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attend (reference ``:125``): the dense-fallback config."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + periodic global blocks (reference ``:155``)."""

    def __init__(self, num_heads: int, block: int = 128, different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional", horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError("num_local_blocks must be divisible by num_global_blocks")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError("attention must be uni/bidirectional")
        self.attention = attention
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional attention")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("different global patterns require different_layout_per_head")
        self.num_different_global_patterns = num_different_global_patterns

    def _set_local(self, layout: np.ndarray, h: int) -> None:
        nb = layout.shape[1]
        for start in range(0, nb, self.num_local_blocks):
            end = min(start + self.num_local_blocks, nb)
            for r in range(start, end):
                hi = (r + 1) if self.attention == "unidirectional" else end
                layout[h, r, start:hi] = 1

    def _set_global(self, layout: np.ndarray, h: int) -> None:
        nb = layout.shape[1]
        first = (h // max(1, self.num_heads // self.num_different_global_patterns)
                 ) % self.num_different_global_patterns
        # last num_global_blocks of each local window (offset per pattern)
        for start in range(0, nb, self.num_local_blocks):
            end = min(start + self.num_local_blocks, nb)
            g_lo = start + (first + 1) * (self.num_local_blocks // self.num_global_blocks) \
                - self.num_global_blocks
            g_lo = min(max(g_lo, start), end - self.num_global_blocks)
            g_hi = g_lo + self.num_global_blocks
            # vertical: every later row attends to the global blocks
            row0 = g_lo if self.attention == "bidirectional" else g_lo
            for r in range(0 if self.attention == "bidirectional" else g_lo, nb):
                if self.attention == "unidirectional" and r < g_lo:
                    continue
                layout[h, r, g_lo:g_hi] = 1
            if self.horizontal_global_attention:
                layout[h, g_lo:g_hi, :] = 1

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self._set_local(layout, h)
            self._set_global(layout, h)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Random + variable local windows + explicit global blocks
    (reference ``:303``)."""

    def __init__(self, num_heads: int, block: int = 128, different_layout_per_head: bool = False,
                 num_random_blocks: int = 0, local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional", horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        rng = random.Random(0)
        for h in range(self.num_layout_heads):
            # variable local windows, cycling the last size
            start = 0
            i = 0
            while start < nb:
                size = self.local_window_blocks[min(i, len(self.local_window_blocks) - 1)]
                end = min(start + size, nb)
                for r in range(start, end):
                    hi = (r + 1) if self.attention == "unidirectional" else end
                    layout[h, r, start:hi] = 1
                start = end
                i += 1
            # random blocks
            for r in range(nb):
                for _ in range(self.num_random_blocks):
                    layout[h, r, rng.randrange(nb)] = 1
            # global blocks
            if self.global_block_end_indices is None:
                cols = self.global_block_indices
            else:
                cols = []
                for lo, hi in zip(self.global_block_indices, self.global_block_end_indices):
                    cols.extend(range(lo, hi))
            for c in (c for c in cols if c < nb):
                layout[h, :, c] = 1
                if self.horizontal_global_attention:
                    layout[h, c, :] = 1
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global (reference ``:496``)."""

    def __init__(self, num_heads: int, block: int = 128, different_layout_per_head: bool = False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(f"need >= {self.num_sliding_window_blocks} blocks, got {nb}")
        rng = random.Random(0)
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for r in range(nb):
                layout[h, r, max(0, r - w):min(nb, r + w + 1)] = 1  # sliding window
                for _ in range(self.num_random_blocks):             # random
                    layout[h, r, rng.randrange(nb)] = 1
            g = self.num_global_blocks
            layout[h, :, :g] = 1                                     # global cols
            layout[h, :g, :] = 1                                     # global rows
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + explicit global indices (reference ``:585``)."""

    def __init__(self, num_heads: int, block: int = 128, different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for r in range(nb):
                layout[h, r, max(0, r - w):min(nb, r + w + 1)] = 1
            if self.global_block_end_indices is None:
                cols = self.global_block_indices
            else:
                cols = []
                for lo, hi in zip(self.global_block_indices, self.global_block_end_indices):
                    cols.extend(range(lo, hi))
            for c in (c for c in cols if c < nb):
                layout[h, :, c] = 1
                layout[h, c, :] = 1
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference ``:678``)."""

    def __init__(self, num_heads: int, block: int = 128,
                 num_sliding_window_blocks: int = 3, attention: str = "unidirectional"):
        super().__init__(num_heads, block, different_layout_per_head=False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for r in range(nb):
            lo = max(0, r - w)
            hi = min(nb, r + w + 1) if self.attention == "bidirectional" else r + 1
            layout[0, r, lo:hi] = 1
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout
