"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``).

The reference implements block-sparse attention with Triton kernels driven
by a C++ LUT builder (``csrc/sparse_attention/utils.cpp``); the TPU build
expresses the same sparsity structures as block layouts consumed by the
Pallas block-sparse flash kernel (splash-attention style) with a dense-mask
fallback for CPU.
"""

from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
    extend_position_embedding, pad_to_block_size, replace_self_attention,
    unpad_sequence_output)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (SparseSelfAttention,
                                                                      layout_to_token_bias,
                                                                      sparse_attention_core)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (BigBirdSparsityConfig,
                                                                BSLongformerSparsityConfig,
                                                                DenseSparsityConfig,
                                                                FixedSparsityConfig,
                                                                LocalSlidingWindowSparsityConfig,
                                                                SparsityConfig,
                                                                VariableSparsityConfig)

__all__ = [
    "SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
    "VariableSparsityConfig", "BigBirdSparsityConfig", "BSLongformerSparsityConfig",
    "LocalSlidingWindowSparsityConfig", "SparseSelfAttention", "layout_to_token_bias",
    "sparse_attention_core", "pad_to_block_size", "unpad_sequence_output",
    "extend_position_embedding", "replace_self_attention",
]
