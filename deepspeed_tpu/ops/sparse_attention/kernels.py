"""Sparse-attention op surface (reference Triton kernels
``deepspeed/ops/sparse_attention/matmul.py`` block-sparse sdd/dsd matmuls
with LUTs + ``softmax.py``; C++ LUT segmentation ``csrc/sparse_attention/
utils.cpp``).

TPU design note: the Triton+LUT machinery exists to skip zero blocks in a
hand-written GPU kernel. The Pallas flash kernel takes the block layout
directly (``block_layout`` argument — diagonal blocks, local windows,
global tokens) and skips masked blocks inside its own grid, so the LUT
builder collapses into :func:`SparsityConfig.make_layout`. This module is
the named-op home: layout construction + the layout-aware attention call.
"""

from __future__ import annotations

from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention, layout_to_token_bias)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, LocalSlidingWindowSparsityConfig, SparsityConfig,
    VariableSparsityConfig)

__all__ = ["SparseSelfAttention", "layout_to_token_bias", "SparsityConfig",
           "DenseSparsityConfig", "FixedSparsityConfig",
           "VariableSparsityConfig", "BigBirdSparsityConfig",
           "BSLongformerSparsityConfig", "LocalSlidingWindowSparsityConfig"]
