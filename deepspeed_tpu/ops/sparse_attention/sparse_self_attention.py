"""Sparse self-attention op (reference
``ops/sparse_attention/sparse_self_attention.py`` + the Triton matmul/
softmax kernels it drives, ``matmul.py``/``softmax.py``).

Two execution paths, both exactly computing softmax over the layout's
support and both differentiable:

- **pallas** (TPU): block-sparse flash attention — zero layout blocks are
  skipped in fwd and bwd (``flash_attention(block_layout=...)``); compute
  and HBM traffic scale with the density of the layout.
- **dense** (CPU/tests): the layout expanded to a token-level additive mask
  over the einsum attention.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (FixedSparsityConfig,
                                                                SparsityConfig)


def layout_to_token_bias(layout, block: int, seq_len: int):
    """[H, nb, nb] 0/1 layout → additive bias [H, S, S] (0 keep / -1e9 drop)."""
    nb = seq_len // block
    lay = jnp.asarray(layout)[:, :nb, :nb]
    tok = jnp.repeat(jnp.repeat(lay, block, axis=1), block, axis=2)
    return jnp.where(tok > 0, 0.0, -1e9).astype(jnp.float32)


class SparseSelfAttention:
    """Callable module (reference ``:24``): q/k/v [B, S, H, Hd] → [B, S, H, Hd].

    ``sparsity_config`` decides the layout; causal masking composes with the
    layout for "unidirectional" configs.
    """

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add", attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048, backend: str = "auto"):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.backend = backend
        self._layouts = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def _use_pallas(self) -> bool:
        if self.backend == "pallas":
            return True
        if self.backend == "dense":
            return False
        return jax.default_backend() == "tpu" and self.sparsity_config.block >= 128

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        B, S, H, Hd = query.shape
        layout = self.get_layout(S)
        causal = getattr(self.sparsity_config, "attention", "bidirectional") == "unidirectional"

        mask_bias = None
        if key_padding_mask is not None:
            # [B, S] 1=keep (or additive when mode == "add" with float input)
            if key_padding_mask.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
                mask_bias = key_padding_mask.astype(jnp.float32)
            else:
                mask_bias = jnp.where(key_padding_mask > 0, 0.0, -1e9).astype(jnp.float32)

        extra = None
        if attn_mask is not None:
            extra = jnp.where(attn_mask > 0, 0.0, -1e9).astype(jnp.float32)
        return sparse_attention_core(
            query, key, value, layout, self.sparsity_config.block, causal,
            mask_bias, use_pallas=self._use_pallas(), attn_bias=extra)


# beyond this, the exact dense fallback's [B, H, S, S] logits defeat the
# purpose of sparsity — reject loudly (matches models/transformer.py's
# DENSE_STREAM_THRESHOLD for the non-sparse fallbacks)
DENSE_SPARSE_MAX_SEQ = 4096


def sparse_attention_core(q, k, v, layout, block: int, causal: bool,
                          mask_bias=None, *, scale: Optional[float] = None,
                          use_pallas: bool, attn_bias=None):
    """Shared execution core: q/k/v [B, S, H, Hd] + [H, nb, nb] layout →
    [B, S, H, Hd]. Drives the block-sparse flash kernel when ``use_pallas``
    (zero blocks skipped fwd+bwd), else the exact dense token-bias einsum
    (pure jnp — vmappable and partitionable, the pipeline/CPU path). Used by
    :class:`SparseSelfAttention` and the model-level ``sparse_attention``
    config (models/transformer.py)."""
    B, S, H, Hd = q.shape
    if use_pallas and attn_bias is None:
        from deepspeed_tpu.ops.pallas import flash_attention
        return flash_attention(q, k, v, mask_bias=mask_bias, causal=causal,
                               scale=scale,
                               block_layout=jnp.asarray(layout, jnp.float32))
    if S > DENSE_SPARSE_MAX_SEQ:
        # the dense form materialises [B, H, S, S] f32 logits — at the long
        # sequences sparsity exists for, that defeats the point; reject
        # loudly rather than OOM (the kernel path streams by block; a dense
        # attn_bias is incompatible with it, pre-fold it into the layout or
        # key-side mask instead)
        raise NotImplementedError(
            f"sparse attention at S={S} > {DENSE_SPARSE_MAX_SEQ} needs the "
            "block-sparse kernel path (TPU, block >= 128, no dense "
            "attn_mask); the exact dense fallback would materialise the "
            "full score matrix")

    bias = layout_to_token_bias(layout, block, S)  # [H, S, S]
    scale = Hd**-0.5 if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = logits + bias[None, :, :, :]
    if causal:
        cm = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(cm[None, None], logits, -1e9)
    if mask_bias is not None:
        logits = logits + mask_bias[:, None, None, :]
    if attn_bias is not None:
        logits = logits + attn_bias
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
