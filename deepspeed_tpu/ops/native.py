"""ctypes loader for the host-side native library (csrc/ → libdstpu.so).

Reference parity: ``op_builder/builder.py:436-497`` (``OpBuilder.load`` JIT
compile + import). Here the native code is torch-free C++ with a C ABI: built
once with ``make`` and loaded with ctypes; each op-family binding module
declares its own argtypes on top of the handle returned by :func:`get_lib`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_LIB: Optional[ctypes.CDLL] = None
_LOCK = threading.Lock()


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def lib_path() -> str:
    return os.path.join(_repo_root(), "csrc", "build", "libdstpu.so")


def build_library(verbose: bool = False) -> str:
    """Run ``make -C csrc`` (idempotent; cheap when up to date)."""
    csrc = os.path.join(_repo_root(), "csrc")
    result = subprocess.run(["make", "-C", csrc, "-j"], capture_output=True, text=True)
    if result.returncode != 0:
        # -march=native can fail under qemu/exotic hosts; retry portable.
        result = subprocess.run(["make", "-C", csrc, "-j", "ARCHFLAGS="],
                                capture_output=True, text=True)
    if result.returncode != 0:
        raise RuntimeError(f"native build failed:\n{result.stderr[-2000:]}")
    if verbose:
        logger.info(f"built native library at {lib_path()}")
    return lib_path()


def get_lib() -> ctypes.CDLL:
    """Load (building if necessary) the shared library. Thread-safe."""
    global _LIB
    if _LIB is not None:
        return _LIB
    with _LOCK:
        if _LIB is None:
            path = lib_path()
            if not os.path.exists(path):
                build_library()
            _LIB = ctypes.CDLL(path)
    return _LIB


def available() -> bool:
    try:
        get_lib()
        return True
    except Exception as e:  # pragma: no cover - env specific
        logger.warning(f"native library unavailable: {e}")
        return False


# Common ctypes aliases used by binding modules
c_f32p = ctypes.POINTER(ctypes.c_float)
c_u16p = ctypes.POINTER(ctypes.c_uint16)
c_i64 = ctypes.c_int64
c_f32 = ctypes.c_float
c_int = ctypes.c_int


def as_f32_ptr(arr):
    return arr.ctypes.data_as(c_f32p)


def as_u16_ptr(arr):
    return arr.ctypes.data_as(c_u16p)


def check_buffer(arr, dtype, name: str, expect_size: int | None = None) -> None:
    """Validate a host buffer before handing its raw pointer to native code.

    ctypes ``data_as`` returns the base pointer of strided views, so anything
    non-contiguous (or of the wrong dtype/size) would silently corrupt memory.
    """
    import numpy as np
    if not isinstance(arr, np.ndarray):
        raise TypeError(f"{name} must be a numpy array, got {type(arr)}")
    if arr.dtype != np.dtype(dtype):
        raise TypeError(f"{name} must be {np.dtype(dtype)}, got {arr.dtype}")
    if not arr.flags["C_CONTIGUOUS"]:
        raise ValueError(f"{name} must be C-contiguous")
    if expect_size is not None and arr.size != expect_size:
        raise ValueError(f"{name} has {arr.size} elements, expected {expect_size}")
