"""Spatial (diffusers/UNet) ops — reference ``csrc/spatial/csrc/opt_bias_add.cu``
(``nhwc_bias_add``, ``nhwc_bias_add_add``, ``nhwc_bias_add_bias_add``) bound
via ``csrc/spatial/csrc/pt_binding.cpp``.

The CUDA kernels exist to get vectorized NHWC bias broadcasts without a
torch kernel launch per op; on TPU these are single fused XLA elementwise
ops — the named functions keep the reference's call surface (and NHWC
layout, which is also TPU's preferred conv layout).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["nhwc_bias_add", "nhwc_bias_add_add", "nhwc_bias_add_bias_add"]


def nhwc_bias_add(activation, bias):
    """[N, H, W, C] + [C]."""
    return activation + bias.reshape((1,) * (activation.ndim - 1) + (-1,))


def nhwc_bias_add_add(activation, bias, other):
    """(a + bias) + other — fused residual form."""
    return nhwc_bias_add(activation, bias) + other


def nhwc_bias_add_bias_add(activation, bias, other, other_bias):
    """(a + bias) + (other + other_bias) — double-bias residual form."""
    return nhwc_bias_add(activation, bias) + nhwc_bias_add(other, other_bias)
