"""Flatten/unflatten dense tensor lists.

Reference parity: ``csrc/utils/flatten_unflatten.cpp`` (UtilsBuilder) — used
by every flat-buffer optimizer. In JAX this is ``jax.flatten_util`` territory;
we keep the two-function API shape.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp


def flatten(tensors: Sequence) -> jnp.ndarray:
    """Concatenate tensors into one contiguous 1-D buffer."""
    return jnp.concatenate([jnp.ravel(t) for t in tensors]) if tensors else jnp.zeros((0,))


def unflatten(flat, tensors: Sequence) -> List:
    """View a flat buffer as the shapes of ``tensors``."""
    outputs = []
    offset = 0
    for t in tensors:
        numel = 1
        for d in t.shape:
            numel *= d
        outputs.append(jnp.reshape(flat[offset:offset + numel], t.shape).astype(t.dtype))
        offset += numel
    return outputs
