from deepspeed_tpu.ops.adagrad.cpu_adagrad import DeepSpeedCPUAdagrad

__all__ = ["DeepSpeedCPUAdagrad"]
