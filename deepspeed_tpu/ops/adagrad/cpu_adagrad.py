"""DeepSpeedCPUAdagrad — host-memory Adagrad for ZeRO-Offload.

Reference parity: ``deepspeed/ops/adagrad/cpu_adagrad.py``.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.ops.adagrad import cpu_adagrad_binding


class DeepSpeedCPUAdagrad:
    def __init__(self, lr: float = 1e-2, eps: float = 1e-10, weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.step_count = 0
        self._h: Dict[str, np.ndarray] = {}

    def register(self, key: str, numel: int) -> None:
        if key not in self._h:
            self._h[key] = np.zeros(numel, np.float32)
        elif self._h[key].size != numel:
            raise ValueError(f"partition '{key}' re-registered with {numel} elements "
                             f"but optimizer state holds {self._h[key].size}; "
                             "partitions are fixed-size once registered")

    def begin_step(self, lr: Optional[float] = None) -> None:
        self.step_count += 1
        if lr is not None:
            self.lr = lr

    def step(self, key: str, params: np.ndarray, grads: np.ndarray,
             param_out_bf16: Optional[np.ndarray] = None) -> None:
        self.register(key, params.size)
        cpu_adagrad_binding.adagrad_step(params, grads, self._h[key],
                                         lr=self.lr, eps=self.eps,
                                         weight_decay=self.weight_decay,
                                         param_out_bf16=param_out_bf16)

    def state_dict(self) -> dict:
        return {"step": self.step_count, "lr": self.lr,
                "exp_avg_sq": {k: v.copy() for k, v in self._h.items()}}

    def load_state_dict(self, sd: dict) -> None:
        self.step_count = sd["step"]
        self.lr = sd.get("lr", self.lr)
        self._h = {k: np.asarray(v, np.float32) for k, v in sd["exp_avg_sq"].items()}
