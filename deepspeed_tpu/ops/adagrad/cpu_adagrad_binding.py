"""ctypes signatures for the native cpu_adagrad kernels (csrc/cpu_adagrad.cpp).

Reference parity: export block in ``csrc/adagrad/cpu_adagrad.cpp``.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deepspeed_tpu.ops import native
from deepspeed_tpu.ops.native import c_f32, c_f32p, c_i64, c_u16p

_configured = False


def _lib():
    global _configured
    lib = native.get_lib()
    if not _configured:
        lib.ds_adagrad_step.argtypes = [c_f32p, c_f32p, c_f32p, c_i64, c_f32, c_f32, c_f32]
        lib.ds_adagrad_step_plus_copy.argtypes = [c_f32p, c_f32p, c_f32p, c_u16p, c_i64,
                                                  c_f32, c_f32, c_f32]
        _configured = True
    return lib


def adagrad_step(params: np.ndarray, grads: np.ndarray, exp_avg_sq: np.ndarray,
                 *, lr: float, eps: float, weight_decay: float,
                 param_out_bf16: Optional[np.ndarray] = None) -> None:
    native.check_buffer(params, np.float32, "params")
    native.check_buffer(grads, np.float32, "grads", params.size)
    native.check_buffer(exp_avg_sq, np.float32, "exp_avg_sq", params.size)
    if param_out_bf16 is not None:
        native.check_buffer(param_out_bf16, np.uint16, "param_out_bf16", params.size)
    lib = _lib()
    n = params.size
    if param_out_bf16 is not None:
        lib.ds_adagrad_step_plus_copy(native.as_f32_ptr(params), native.as_f32_ptr(grads),
                                      native.as_f32_ptr(exp_avg_sq),
                                      native.as_u16_ptr(param_out_bf16),
                                      n, lr, eps, weight_decay)
    else:
        lib.ds_adagrad_step(native.as_f32_ptr(params), native.as_f32_ptr(grads),
                            native.as_f32_ptr(exp_avg_sq), n, lr, eps, weight_decay)
