"""Attention ops.

The XLA einsum path below is the default; ``deepspeed_tpu.ops.flash_attention``
(Pallas, TPU) replaces it for long sequences when available. This mirrors the
reference's split between its CUDA softmax/attention kernels
(``csrc/transformer/softmax_kernels.cu``, inference ``softmax_context``) and
the torch fallbacks.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def mha_attention(q, k, v, mask_bias=None, causal: bool = True, alibi_slopes=None, scale: Optional[float] = None):
    """q: [B, S, H, Hd]; k,v: [B, S, KV, Hd] with KV | H → [B, S, H, Hd].

    GQA-native: when KV < H the query heads are reshaped into [KV, G] groups
    (query head h reads kv head ``h // G`` — ``jnp.repeat`` order, matching
    the flash/decode kernels' index maps) and contracted against the
    UNREPEATED kv, so no H/KV× HBM copy of k/v is ever materialised.

    Computed in fp32 accumulators (softmax in fp32) with inputs in compute
    dtype; XLA fuses scale+bias+mask+softmax into the attention matmuls.
    """
    B, S, H, Hd = q.shape
    KV = k.shape[2]
    scale = scale if scale is not None else Hd**-0.5
    G = H // KV

    # [B, S, KV, G, Hd]: head h = c*G + g, so h // G = c — repeat order
    q5 = q.reshape(B, S, KV, G, Hd)
    logits = jnp.einsum("bqcgd,bkcd->bcgqk", q5, k,
                        preferred_element_type=jnp.float32) * scale

    if alibi_slopes is not None:
        # additive linear biases per head: slope * -(q_pos - k_pos)
        qpos = jnp.arange(S)[:, None]
        kpos = jnp.arange(S)[None, :]
        dist = (kpos - qpos).astype(jnp.float32)  # <= 0 in causal region
        slopes5 = alibi_slopes.reshape(KV, G)
        logits = logits + slopes5[None, :, :, None, None] * dist[None, None, None, :, :]

    if causal:
        causal_mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(causal_mask[None, None, None, :, :], logits, -1e9)
    if mask_bias is not None:
        logits = logits + mask_bias[:, None]  # [B,1,1,S] -> [B,1,1,1,S] broadcast

    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bcgqk,bkcd->bqcgd", probs, v)
    return out.reshape(B, S, H, Hd)
