"""Pallas fused Adam — the TPU-native named op for the reference's
multi-tensor fused Adam (``csrc/adam/multi_tensor_adam.cu:163``,
``csrc/adam/fused_adam_frontend.cpp``; Python wrapper
``deepspeed/ops/adam/fused_adam.py``).

The multi-tensor-apply trick on GPU exists to amortise kernel-launch
overhead and make the optimizer bandwidth-bound: one kernel walks chunk
lists covering every parameter tensor. The TPU-idiomatic equivalent is a
single Pallas kernel over ONE flat buffer per optimizer slot: the engine
already keeps flat param/moment pytrees, so we flatten leaves once
(``ravel``/concat happens inside the same jit and fuses to pure layout),
then stream p/g/m/v through VMEM in (8·SUBLANES, 128)-tiles — every
element is read once and written once, which is the whole point of the
fused op (4 reads + 3 writes per element, no intermediate HBM traffic).

Two call surfaces:

* :func:`fused_adam_step` — raw kernel on 1-D flat arrays; what the op
  registry's ``FusedAdamBuilder`` loads.
* :func:`fused_adam` — optax ``GradientTransformationExtraArgs`` drop-in
  (config name ``FusedAdam``) whose ``update`` runs the kernel per leaf
  in ``emit="update"`` mode (the kernel writes the update direction
  directly — no ``new_p - p`` reconstruction, no extra pass over p, no
  bf16 cancellation), so the engine/ZeRO sharding machinery treats it
  like any other optimizer.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# (rows, 128) f32 tile per grid step: 256*128*4B = 128 KiB per operand —
# 7 operands ≈ 0.9 MiB of VMEM, far under budget, big enough to saturate
# HBM bandwidth.
_BLOCK_ROWS = 256
_LANES = 128
_BLOCK = _BLOCK_ROWS * _LANES


def _adam_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref,
                 *, b1, b2, eps, wd, adam_w, emit):
    """One (rows, 128) tile: full Adam step, everything in fp32 registers.

    sc_ref (SMEM, f32[3]): [lr, 1-b1^t, 1-b2^t] — the only per-step scalars.
    ``emit="param"`` writes ``p - lr*upd``; ``emit="update"`` writes the
    descent direction ``upd`` itself (fp32) for callers that apply it
    elsewhere (e.g. the engine's ``p - lr*u`` with a scheduled lr).
    """
    lr, bc1, bc2 = sc_ref[0], sc_ref[1], sc_ref[2]
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    if not adam_w and wd:
        # reference Adam mode: L2 folded into the gradient before moments
        g = g + wd * p
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * (g * g)
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w and wd:
        upd = upd + wd * p
    if emit == "param":
        po_ref[:] = (p - lr * upd).astype(po_ref.dtype)
    else:
        po_ref[:] = upd
    mo_ref[:] = m
    vo_ref[:] = v


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps", "wd", "adam_w",
                                             "emit", "interpret"))
def _fused_adam_flat(p, g, m, v, lr, bc1, bc2, *, b1, b2, eps, wd, adam_w,
                     emit, interpret):
    n = p.shape[0]
    pad = (-n) % _BLOCK
    padded = n + pad

    def prep(x):
        x = jnp.pad(x, (0, pad)) if pad else x
        return x.reshape(padded // _LANES, _LANES)

    rows = padded // _LANES
    grid = (rows // _BLOCK_ROWS,)
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i, sc: (i, 0))
    scalars = jnp.stack([lr, bc1, bc2]).astype(jnp.float32)
    kern = functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps, wd=wd,
                             adam_w=adam_w, emit=emit)
    out_dtype = p.dtype if emit == "param" else jnp.float32
    po, mo, vo = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[spec] * 4,
            out_specs=[spec] * 3,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), out_dtype),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(scalars, prep(p), prep(g), prep(m.astype(jnp.float32)),
      prep(v.astype(jnp.float32)))

    def unprep(x):
        flat = x.reshape(-1)
        return flat[:n] if pad else flat

    return unprep(po), unprep(mo), unprep(vo)


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps", "wd", "adam_w",
                                             "emit"))
def _jnp_adam_flat(p, g, m, v, lr, bc1, bc2, *, b1, b2, eps, wd, adam_w, emit):
    """Same math as the kernel in plain jnp — the off-TPU fallback (XLA:CPU
    fuses this fine; Pallas interpret mode is only for kernel unit tests)."""
    g = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    if not adam_w and wd:
        g = g + wd * pf
    m = b1 * m.astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v.astype(jnp.float32) + (1.0 - b2) * (g * g)
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w and wd:
        upd = upd + wd * pf
    if emit == "param":
        return (pf - lr * upd).astype(p.dtype), m, v
    return upd, m, v


def _run_adam(p, g, m, v, *, step, lr, b1, b2, eps, weight_decay, adam_w_mode,
              bias_correction, interpret, emit):
    # interpret=None: compiled kernel on TPU, jnp math elsewhere.
    # interpret=True: kernel in interpret mode (any backend).
    # interpret=False: compiled kernel (any backend — caller's risk off-TPU).
    use_kernel = True if interpret is not None else jax.default_backend() == "tpu"
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - jnp.asarray(b1, jnp.float32) ** step
        bc2 = 1.0 - jnp.asarray(b2, jnp.float32) ** step
    else:
        bc1 = jnp.float32(1.0)
        bc2 = jnp.float32(1.0)
    kw = dict(b1=float(b1), b2=float(b2), eps=float(eps),
              wd=float(weight_decay), adam_w=bool(adam_w_mode), emit=emit)
    lr = jnp.asarray(lr, jnp.float32)
    if not use_kernel:
        return _jnp_adam_flat(p, g, m, v, lr, bc1, bc2, **kw)
    return _fused_adam_flat(p, g, m, v, lr, bc1, bc2, interpret=bool(interpret),
                            **kw)


def fused_adam_step(p, g, m, v, *, step, lr, b1=0.9, b2=0.999, eps=1e-8,
                    weight_decay=0.0, adam_w_mode=True, bias_correction=True,
                    interpret: Optional[bool] = None):
    """Single fused Adam step on flat 1-D buffers.

    Returns ``(new_p, new_m, new_v)``. ``step`` is the 1-based step count
    (traced scalar is fine); ``lr`` may be a traced scalar so schedules stay
    inside jit. Moments are kept in fp32 regardless of param dtype.

    ``interpret``: None (default) = compiled Pallas kernel on TPU, identical
    jnp math elsewhere; True = kernel in interpret mode (kernel unit tests);
    False = force the compiled kernel on any backend.
    """
    return _run_adam(p, g, m, v, step=step, lr=lr, b1=b1, b2=b2, eps=eps,
                     weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                     bias_correction=bias_correction, interpret=interpret,
                     emit="param")


class FusedAdamState(NamedTuple):
    count: jax.Array  # int32 step counter
    mu: optax.Updates
    nu: optax.Updates


def fused_adam(learning_rate=None, b1=0.9, b2=0.999, eps=1e-8,
               weight_decay=0.0, adam_w_mode=True, bias_correction=True,
               interpret: Optional[bool] = None) -> optax.GradientTransformationExtraArgs:
    """Optax-compatible wrapper: kernel per leaf in ``emit="update"`` mode.

    ``learning_rate=None`` means "LR injected by the engine": the transform
    returns the POSITIVE descent direction u (the engine applies
    ``p - lr*u``, keeping the schedule inside jit — see
    ``runtime/engine.py _apply_update``). With a concrete ``learning_rate``
    it returns standard optax deltas ``-lr*u`` (``apply_updates`` adds them).
    """

    def init(params):
        # moments keep the PARAM shapes (fp32) so ZeRO/TP sharding rules and
        # checkpoint layouts treat them like any optax state; the kernel's
        # ravel is a pure layout op inside jit
        zeros = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
        return FusedAdamState(count=jnp.zeros((), jnp.int32),
                              mu=zeros, nu=jax.tree.map(jnp.copy, zeros))

    def update(grads, state, params=None, **extra):
        if params is None:
            raise ValueError("fused_adam requires params (fused update kernel)")
        count = state.count + 1
        leaves_p, treedef = jax.tree.flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        leaves_m = treedef.flatten_up_to(state.mu)
        leaves_v = treedef.flatten_up_to(state.nu)
        out_u, out_m, out_v = [], [], []
        for p, g, m, v in zip(leaves_p, leaves_g, leaves_m, leaves_v):
            u, nm, nv = _run_adam(
                p.reshape(-1), g.reshape(-1), m.reshape(-1), v.reshape(-1),
                step=count, lr=0.0,  # lr unused in emit="update"
                b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
                adam_w_mode=adam_w_mode, bias_correction=bias_correction,
                interpret=interpret, emit="update")
            u = u.reshape(p.shape)
            if learning_rate is not None:
                # schedules (callables of the step count) resolve like optax
                # optax evaluates schedules at the 0-based pre-increment
                # count; our count is 1-based
                lr_t = (learning_rate(count - 1) if callable(learning_rate)
                        else learning_rate)
                u = (-lr_t * u).astype(p.dtype)
            out_u.append(u)
            out_m.append(nm.reshape(p.shape))
            out_v.append(nv.reshape(p.shape))
        updates = jax.tree.unflatten(treedef, out_u)
        new_state = FusedAdamState(count=count,
                                   mu=jax.tree.unflatten(treedef, out_m),
                                   nu=jax.tree.unflatten(treedef, out_v))
        return updates, new_state

    return optax.GradientTransformationExtraArgs(init, update)
