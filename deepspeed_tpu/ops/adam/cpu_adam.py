"""DeepSpeedCPUAdam — host-memory Adam for ZeRO-Offload.

Reference parity: ``deepspeed/ops/adam/cpu_adam.py`` (``DeepSpeedCPUAdam``,
180 LoC) wrapping the native SIMD kernel. Here state lives in numpy fp32
arrays (one flat buffer per parameter leaf) stepped by csrc/cpu_adam.cpp;
grads arrive as numpy views of device-to-host transfers and the updated
params are returned as bf16 staging buffers ready for host-to-device.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.ops.adam import cpu_adam_binding


class DeepSpeedCPUAdam:
    """Flat-buffer Adam over host memory.

    Unlike a torch optimizer there is no param-group mutation protocol: the
    engine registers each flat fp32 master partition once by key, then calls
    :meth:`step` with that key and the grad buffer for the partition.
    """

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self._m: Dict[str, np.ndarray] = {}
        self._v: Dict[str, np.ndarray] = {}

    def register(self, key: str, numel: int) -> None:
        if key not in self._m:
            self._m[key] = np.zeros(numel, np.float32)
            self._v[key] = np.zeros(numel, np.float32)
        elif self._m[key].size != numel:
            raise ValueError(f"partition '{key}' re-registered with {numel} elements "
                             f"but optimizer state holds {self._m[key].size}; "
                             "partitions are fixed-size once registered")

    def begin_step(self, lr: Optional[float] = None) -> None:
        """Advance the shared timestep once per optimizer step (all
        partitions stepped between begin_step calls share bias correction)."""
        self.step_count += 1
        if lr is not None:
            self.lr = lr

    def step(self, key: str, params: np.ndarray, grads: np.ndarray,
             param_out_bf16: Optional[np.ndarray] = None) -> None:
        """Fused in-place update of one registered flat partition."""
        self.register(key, params.size)
        cpu_adam_binding.adam_step(
            params, grads, self._m[key], self._v[key],
            lr=self.lr, beta1=self.beta1, beta2=self.beta2, eps=self.eps,
            weight_decay=self.weight_decay, adamw_mode=self.adamw_mode,
            step=max(self.step_count, 1), param_out_bf16=param_out_bf16)

    # --- checkpoint support -------------------------------------------- #
    def state_dict(self) -> dict:
        return {
            "step": self.step_count,
            "lr": self.lr,
            "exp_avg": {k: v.copy() for k, v in self._m.items()},
            "exp_avg_sq": {k: v.copy() for k, v in self._v.items()},
        }

    def load_state_dict(self, sd: dict) -> None:
        self.step_count = sd["step"]
        self.lr = sd.get("lr", self.lr)
        self._m = {k: np.asarray(v, np.float32) for k, v in sd["exp_avg"].items()}
        self._v = {k: np.asarray(v, np.float32) for k, v in sd["exp_avg_sq"].items()}
